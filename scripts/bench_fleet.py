#!/usr/bin/env python
"""Fleet-scheduler benchmark: priority/market scheduling vs FIFO.

The fleet-scale question: under a bursty diurnal arrival pattern, what
does class-aware scheduling with an elastic preemption market buy over
the naive baseline (one FIFO queue, all-or-nothing placement, no
preemption — the pre-fleet daemon's behavior with a queue bolted on)?

Both phases run the SAME Poisson job trace
(:func:`~torchx_tpu.sim.traffic.diurnal_trace`, seeded) against the same
modeled fleet in virtual time:

* **fifo** — strict arrival order with head-of-line blocking: a gang
  waits until the head of the queue fits, serve traffic stuck behind
  wide batch gangs.
* **fleet** — the real :class:`~torchx_tpu.fleet.FleetScheduler` (not a
  reimplementation) driven through the simulator's
  :class:`~torchx_tpu.sim.SimExecutor`: priority classes, fair share,
  gang placement, and the market (elastic victims shrink via
  mesh-reshape instead of dying; grow-backs repay the debt when
  capacity frees).

This script is a thin client of :mod:`torchx_tpu.sim` — the trace
generator and the virtual-time executor live there (the full scenario
harness is ``tpx sim run``); only the FIFO baseline and the scorecard
are bench-specific. Shrunk gangs run slower (speed scales with the
replica fraction), so the market's cost side is modeled, not assumed
away. The headline: serve/interactive p99 wait must drop vs FIFO
without killing batch throughput.

Usage:
    python scripts/bench_fleet.py [--hours 2] [--slices 160]
        [--seed 11] [--out BENCH_FLEET_r02.json]
"""

from __future__ import annotations

import argparse
import heapq
import math
import json
import statistics
import tempfile


def _quantiles(samples: list[float]) -> dict:
    if not samples:
        return {"p50_s": None, "p99_s": None}
    if len(samples) == 1:
        return {
            "p50_s": round(samples[0], 2),
            "p99_s": round(samples[0], 2),
        }
    qs = statistics.quantiles(samples, n=100, method="inclusive")
    return {"p50_s": round(qs[49], 2), "p99_s": round(qs[98], 2)}


# ---------------------------------------------------------------------------
# phase A: FIFO baseline
# ---------------------------------------------------------------------------


def bench_fifo(trace: list[dict], slices: int, class_mix: dict) -> dict:
    """Strict arrival order, all-or-nothing, head-of-line blocking."""
    free = slices
    waits: dict[str, list[float]] = {k: [] for k in class_mix}
    pending: list[dict] = []
    events: list[tuple[float, int, int]] = []  # (finish, tie, replicas)
    busy_integral = 0.0
    last_t = 0.0
    now = 0.0
    tie = 0
    done = 0
    arrivals = list(trace)

    def advance(to: float) -> None:
        nonlocal busy_integral, last_t, now
        busy_integral += (slices - free) * (to - last_t)
        last_t = to
        now = to

    def drain() -> None:
        nonlocal free, tie
        while pending and pending[0]["replicas"] <= free:
            job = pending.pop(0)
            free -= job["replicas"]
            waits[job["klass"]].append(now - job["arrival"])
            tie += 1
            heapq.heappush(
                events, (now + job["duration"], tie, job["replicas"])
            )

    while arrivals or events:
        next_arrival = arrivals[0]["arrival"] if arrivals else math.inf
        next_finish = events[0][0] if events else math.inf
        if next_arrival <= next_finish:
            advance(next_arrival)
            pending.append(arrivals.pop(0))
        else:
            advance(next_finish)
            _t, _tie, replicas = heapq.heappop(events)
            free += replicas
            done += 1
        drain()
    makespan = max(last_t, 1e-9)
    return {
        "mode": "fifo",
        "completed": done,
        "makespan_s": round(makespan, 1),
        "utilization": round(busy_integral / (slices * makespan), 4),
        "kills": 0,
        "reshapes": 0,
        "wait_by_class": {k: _quantiles(v) for k, v in waits.items()},
    }


# ---------------------------------------------------------------------------
# phase B: the real FleetScheduler in virtual time
# ---------------------------------------------------------------------------


def bench_fleet(
    trace: list[dict], slices: int, state_dir: str, class_mix: dict
) -> dict:
    import types

    from torchx_tpu.fleet import FleetModel, FleetScheduler, GangRequest
    from torchx_tpu.sim import SimExecutor

    now = [0.0]
    fs = FleetScheduler(
        FleetModel.from_spec(f"sim:v5e-1x{slices}"),
        state_dir=state_dir,
        clock=lambda: now[0],
    )
    ex = SimExecutor(lambda: now[0], {j["job"]: j["duration"] for j in trace})
    fs.bind(ex)

    arrivals = list(trace)
    done = 0
    while True:
        next_arrival = arrivals[0]["arrival"] if arrivals else math.inf
        next_finish = ex.next_finish()
        if next_finish is None:
            next_finish = math.inf
        if next_arrival is math.inf and next_finish is math.inf:
            break
        if next_arrival <= next_finish:
            now[0] = next_arrival
            j = arrivals.pop(0)
            fs.submit(
                GangRequest(
                    job=j["job"],
                    tenant=j["tenant"],
                    klass=j["klass"],
                    replicas=j["replicas"],
                    elastic=j["elastic"],
                    mesh="fsdp=-1" if j["elastic"] else "",
                    min_replicas=1,
                )
            )
        else:
            now[0] = next_finish
            app_id = ex.finish(ex.pop_finished())
            done += 1
            fs.on_event(
                types.SimpleNamespace(
                    scheduler="local",
                    app_id=app_id,
                    terminal=True,
                    state=types.SimpleNamespace(name="SUCCEEDED"),
                )
            )

    waits: dict[str, list[float]] = {k: [] for k in class_mix}
    unplaced = 0
    for j in trace:
        if j["job"] in ex.placed_at:
            waits[j["klass"]].append(ex.placed_at[j["job"]] - j["arrival"])
        else:
            unplaced += 1
    makespan = max(now[0], 1e-9)
    return {
        "mode": "fleet",
        "completed": done,
        "unplaced": unplaced,
        "makespan_s": round(makespan, 1),
        "utilization": round(
            ex.busy_integral / (slices * makespan), 4
        ),
        "kills": fs.kills,
        "reshapes": fs.reshapes,
        "growbacks": fs.grows,
        "wait_by_class": {k: _quantiles(v) for k, v in waits.items()},
    }


# ---------------------------------------------------------------------------


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--hours", type=float, default=2.0)
    parser.add_argument("--slices", type=int, default=160)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument(
        "--rate-scale",
        type=float,
        default=None,
        help="arrival-rate multiplier (default: slices/16, keeping"
        " pressure comparable to the original 16-slice bench)",
    )
    parser.add_argument("--out", default=None, help="write results JSON here")
    args = parser.parse_args()

    import os

    os.environ.setdefault("TPX_EVENT_DESTINATION", "null")
    os.environ.setdefault("TPX_TRACE", "0")

    from torchx_tpu.sim import CLASS_MIX, diurnal_trace

    rate_scale = (
        args.rate_scale if args.rate_scale is not None else args.slices / 16.0
    )
    trace = diurnal_trace(args.hours, args.seed, rate_scale=rate_scale)
    by_class = {
        k: sum(1 for j in trace if j["klass"] == k) for k in CLASS_MIX
    }
    print(
        f"bench_fleet: {len(trace)} gangs over {args.hours}h virtual"
        f" onto {args.slices} slices, rate x{rate_scale:g} ({by_class})"
    )

    fifo = bench_fifo(trace, args.slices, CLASS_MIX)
    print(
        f"  fifo:  serve p99 wait {fifo['wait_by_class']['serve']['p99_s']}s,"
        f" util {fifo['utilization']:.0%}, kills {fifo['kills']}"
    )
    state_dir = tempfile.mkdtemp(prefix="tpx-bench-fleet-")
    fleet = bench_fleet(trace, args.slices, state_dir, CLASS_MIX)
    print(
        f"  fleet: serve p99 wait {fleet['wait_by_class']['serve']['p99_s']}s,"
        f" util {fleet['utilization']:.0%}, kills {fleet['kills']},"
        f" {fleet['reshapes']} preemptions taken as shrinks"
        f" ({fleet['growbacks']} grow-backs)"
    )
    result = {
        "bench": "fleet_scheduler",
        "hours": args.hours,
        "slices": args.slices,
        "seed": args.seed,
        "rate_scale": rate_scale,
        "gangs": len(trace),
        "gangs_by_class": by_class,
        "fifo": fifo,
        "fleet": fleet,
    }
    for klass in ("serve", "interactive"):
        f99 = fifo["wait_by_class"][klass]["p99_s"]
        s99 = fleet["wait_by_class"][klass]["p99_s"]
        if f99 and s99 is not None:
            result[f"{klass}_p99_wait_speedup"] = round(
                f99 / max(s99, 0.01), 1
            )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  wrote {args.out}")


if __name__ == "__main__":
    main()
