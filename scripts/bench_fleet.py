#!/usr/bin/env python
"""Fleet-scheduler benchmark: priority/market scheduling vs FIFO.

The fleet-scale question: under a bursty diurnal arrival pattern, what
does class-aware scheduling with an elastic preemption market buy over
the naive baseline (one FIFO queue, all-or-nothing placement, no
preemption — the pre-fleet daemon's behavior with a queue bolted on)?

Both phases run the SAME Poisson job trace (diurnal rate modulation,
seeded RNG) against the same modeled fleet in virtual time:

* **fifo** — strict arrival order with head-of-line blocking: a gang
  waits until the head of the queue fits, serve traffic stuck behind
  wide batch gangs.
* **fleet** — the real :class:`~torchx_tpu.fleet.FleetScheduler` (not a
  reimplementation) driven through a simulator
  :class:`~torchx_tpu.fleet.FleetExecutor` and an injected virtual
  clock: priority classes, fair share, gang placement, and the market
  (elastic victims shrink via mesh-reshape instead of dying; grow-backs
  repay the debt when capacity frees).

Shrunk gangs run slower (speed scales with the replica fraction), so
the market's cost side is modeled, not assumed away. Reported per
phase: gang wait p50/p99 per class, chip utilization over the
makespan, completions, and kills — for the fleet phase, `reshapes` is
the count of preemptions the market turned into shrinks (kills
avoided). The headline: serve/interactive p99 wait must drop vs FIFO
without killing batch throughput.

Usage:
    python scripts/bench_fleet.py [--hours 2] [--slices 16]
        [--seed 11] [--out BENCH_FLEET_r01.json]
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import random
import statistics
import tempfile


def _quantiles(samples: list[float]) -> dict:
    if not samples:
        return {"p50_s": None, "p99_s": None}
    if len(samples) == 1:
        return {
            "p50_s": round(samples[0], 2),
            "p99_s": round(samples[0], 2),
        }
    qs = statistics.quantiles(samples, n=100, method="inclusive")
    return {"p50_s": round(qs[49], 2), "p99_s": round(qs[98], 2)}


# ---------------------------------------------------------------------------
# the trace
# ---------------------------------------------------------------------------

#: class -> (arrival weight, (min,max) duration seconds, replica choices)
CLASS_MIX = {
    "serve": (0.15, (120.0, 600.0), (1, 2)),
    "interactive": (0.25, (60.0, 300.0), (1, 2)),
    "batch": (0.40, (600.0, 1800.0), (2, 4)),
    "preemptible": (0.20, (600.0, 1800.0), (2, 4)),
}


def make_trace(hours: float, seed: int) -> list[dict]:
    """Poisson arrivals with a diurnal rate (one peak per simulated
    'day' compressed into the horizon), seeded -> identical for both
    phases."""
    rng = random.Random(seed)
    horizon = hours * 3600.0
    base_rate = 1.0 / 45.0  # one arrival every ~45s off-peak
    jobs = []
    t = 0.0
    i = 0
    while True:
        # thinning: sample at the peak rate, accept by the diurnal curve
        peak = base_rate * 2.5
        t += rng.expovariate(peak)
        if t >= horizon:
            break
        phase = 2.0 * math.pi * (t / horizon)
        rate = base_rate * (1.75 + 1.5 * math.sin(phase))  # 0.25x..3.25x
        if rng.random() > rate / peak:
            continue
        r = rng.random()
        acc = 0.0
        klass = "batch"
        for name, (w, _dur, _reps) in CLASS_MIX.items():
            acc += w
            if r <= acc:
                klass = name
                break
        _w, (dlo, dhi), reps = CLASS_MIX[klass]
        elastic = klass in ("batch", "preemptible")
        replicas = rng.choice(reps)
        jobs.append(
            {
                "job": f"sim-{i:04d}",
                "arrival": t,
                "klass": klass,
                "tenant": rng.choice(("ads", "search", "research")),
                "replicas": replicas,
                "duration": rng.uniform(dlo, dhi),
                "elastic": elastic and replicas > 1,
            }
        )
        i += 1
    return jobs


# ---------------------------------------------------------------------------
# phase A: FIFO baseline
# ---------------------------------------------------------------------------


def bench_fifo(trace: list[dict], slices: int) -> dict:
    """Strict arrival order, all-or-nothing, head-of-line blocking."""
    free = slices
    waits: dict[str, list[float]] = {k: [] for k in CLASS_MIX}
    pending: list[dict] = []
    events: list[tuple[float, int, int]] = []  # (finish, tie, replicas)
    busy_integral = 0.0
    last_t = 0.0
    now = 0.0
    tie = 0
    done = 0
    arrivals = list(trace)

    def advance(to: float) -> None:
        nonlocal busy_integral, last_t, now
        busy_integral += (slices - free) * (to - last_t)
        last_t = to
        now = to

    def drain() -> None:
        nonlocal free, tie
        while pending and pending[0]["replicas"] <= free:
            job = pending.pop(0)
            free -= job["replicas"]
            waits[job["klass"]].append(now - job["arrival"])
            tie += 1
            heapq.heappush(
                events, (now + job["duration"], tie, job["replicas"])
            )

    while arrivals or events:
        next_arrival = arrivals[0]["arrival"] if arrivals else math.inf
        next_finish = events[0][0] if events else math.inf
        if next_arrival <= next_finish:
            advance(next_arrival)
            pending.append(arrivals.pop(0))
        else:
            advance(next_finish)
            _t, _tie, replicas = heapq.heappop(events)
            free += replicas
            done += 1
        drain()
    makespan = max(last_t, 1e-9)
    return {
        "mode": "fifo",
        "completed": done,
        "makespan_s": round(makespan, 1),
        "utilization": round(busy_integral / (slices * makespan), 4),
        "kills": 0,
        "reshapes": 0,
        "wait_by_class": {k: _quantiles(v) for k, v in waits.items()},
    }


# ---------------------------------------------------------------------------
# phase B: the real FleetScheduler in virtual time
# ---------------------------------------------------------------------------


class SimExecutor:
    """FleetExecutor over virtual time: each schedule() becomes a timed
    attempt; shrunk gangs run at cur/launch speed; cancel() banks the
    remaining work so the resubmit picks it up."""

    def __init__(self, clock, work: dict) -> None:
        self.clock = clock
        self.work = work  # fleet job id -> remaining full-speed seconds
        self.attempts: dict[str, dict] = {}  # handle -> attempt record
        self.events: list[tuple[float, int, str]] = []  # (finish, tie, handle)
        self.busy_integral = 0.0
        self._n = 0

    def schedule(self, job, mesh_spec):
        self._n += 1
        handle = f"local://sim/app-{self._n}"
        speed = job.cur_replicas / job.req.replicas
        finish = self.clock() + self.work[job.req.job] / speed
        self.attempts[handle] = {
            "job": job.req.job,
            "start": self.clock(),
            "speed": speed,
            "slices": job.cur_replicas,
            "live": True,
        }
        heapq.heappush(self.events, (finish, self._n, handle))
        return handle

    def cancel(self, handle):
        att = self.attempts.get(handle)
        if att is None or not att["live"]:
            return
        att["live"] = False
        elapsed = self.clock() - att["start"]
        self.work[att["job"]] = max(
            0.0, self.work[att["job"]] - elapsed * att["speed"]
        )
        self.busy_integral += att["slices"] * elapsed

    def finish(self, handle) -> str:
        """Retire a live attempt at its finish time; returns its app id."""
        att = self.attempts[handle]
        att["live"] = False
        self.work[att["job"]] = 0.0
        self.busy_integral += att["slices"] * (self.clock() - att["start"])
        return handle.rsplit("/", 1)[1]


def bench_fleet(trace: list[dict], slices: int, state_dir: str) -> dict:
    import types

    from torchx_tpu.fleet import FleetModel, FleetScheduler, GangRequest

    now = [0.0]
    work = {j["job"]: j["duration"] for j in trace}
    fs = FleetScheduler(
        FleetModel.from_spec(f"sim:v5e-1x{slices}"),
        state_dir=state_dir,
        clock=lambda: now[0],
    )
    ex = SimExecutor(lambda: now[0], work)
    fs.bind(ex)

    placed_at: dict[str, float] = {}
    orig_schedule = ex.schedule

    def schedule(job, mesh_spec):
        placed_at.setdefault(job.req.job, now[0])
        return orig_schedule(job, mesh_spec)

    ex.schedule = schedule

    arrivals = list(trace)
    done = 0
    while arrivals or ex.events:
        next_arrival = arrivals[0]["arrival"] if arrivals else math.inf
        while ex.events and not ex.attempts[ex.events[0][2]]["live"]:
            heapq.heappop(ex.events)  # cancelled attempt: dead entry
        next_finish = ex.events[0][0] if ex.events else math.inf
        if next_arrival is math.inf and next_finish is math.inf:
            break
        if next_arrival <= next_finish:
            now[0] = next_arrival
            j = arrivals.pop(0)
            fs.submit(
                GangRequest(
                    job=j["job"],
                    tenant=j["tenant"],
                    klass=j["klass"],
                    replicas=j["replicas"],
                    elastic=j["elastic"],
                    mesh="fsdp=-1" if j["elastic"] else "",
                    min_replicas=1,
                )
            )
        else:
            now[0] = next_finish
            _t, _tie, handle = heapq.heappop(ex.events)
            app_id = ex.finish(handle)
            done += 1
            fs.on_event(
                types.SimpleNamespace(
                    scheduler="local",
                    app_id=app_id,
                    terminal=True,
                    state=types.SimpleNamespace(name="SUCCEEDED"),
                )
            )

    waits: dict[str, list[float]] = {k: [] for k in CLASS_MIX}
    unplaced = 0
    for j in trace:
        if j["job"] in placed_at:
            waits[j["klass"]].append(placed_at[j["job"]] - j["arrival"])
        else:
            unplaced += 1
    makespan = max(now[0], 1e-9)
    return {
        "mode": "fleet",
        "completed": done,
        "unplaced": unplaced,
        "makespan_s": round(makespan, 1),
        "utilization": round(
            ex.busy_integral / (slices * makespan), 4
        ),
        "kills": fs.kills,
        "reshapes": fs.reshapes,
        "growbacks": fs.grows,
        "wait_by_class": {k: _quantiles(v) for k, v in waits.items()},
    }


# ---------------------------------------------------------------------------


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--hours", type=float, default=2.0)
    parser.add_argument("--slices", type=int, default=16)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--out", default=None, help="write results JSON here")
    args = parser.parse_args()

    import os

    os.environ.setdefault("TPX_EVENT_DESTINATION", "null")
    trace = make_trace(args.hours, args.seed)
    by_class = {
        k: sum(1 for j in trace if j["klass"] == k) for k in CLASS_MIX
    }
    print(
        f"bench_fleet: {len(trace)} gangs over {args.hours}h virtual"
        f" onto {args.slices} slices ({by_class})"
    )

    fifo = bench_fifo(trace, args.slices)
    print(
        f"  fifo:  serve p99 wait {fifo['wait_by_class']['serve']['p99_s']}s,"
        f" util {fifo['utilization']:.0%}, kills {fifo['kills']}"
    )
    state_dir = tempfile.mkdtemp(prefix="tpx-bench-fleet-")
    fleet = bench_fleet(trace, args.slices, state_dir)
    print(
        f"  fleet: serve p99 wait {fleet['wait_by_class']['serve']['p99_s']}s,"
        f" util {fleet['utilization']:.0%}, kills {fleet['kills']},"
        f" {fleet['reshapes']} preemptions taken as shrinks"
        f" ({fleet['growbacks']} grow-backs)"
    )
    result = {
        "bench": "fleet_scheduler",
        "hours": args.hours,
        "slices": args.slices,
        "seed": args.seed,
        "gangs": len(trace),
        "gangs_by_class": by_class,
        "fifo": fifo,
        "fleet": fleet,
    }
    for klass in ("serve", "interactive"):
        f99 = fifo["wait_by_class"][klass]["p99_s"]
        s99 = fleet["wait_by_class"][klass]["p99_s"]
        if f99 and s99 is not None:
            result[f"{klass}_p99_wait_speedup"] = round(
                f99 / max(s99, 0.01), 1
            )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  wrote {args.out}")


if __name__ == "__main__":
    main()
