#!/usr/bin/env python
"""Federation failover benchmark: two live cells, one dies mid-trace.

The headline robustness question: when a regional cell drains (planned)
or its daemon is killed outright (unplanned), does the federation router
degrade gracefully — zero dropped requests, bounded failover p99 — or
does the loss surface to callers?

Three phases, one artifact (``BENCH_FED_r01.json``):

* **drain** — two local ``tpx control`` daemons as cells under a
  phase-shifted synthetic diurnal request trace. Mid-trace, cell A is
  drained via its ``/v1/cell/drain`` verb; the router must route every
  subsequent request to the survivor. After the uncordon, traffic
  returns. Reported: request count, dropped count (target **zero**),
  TTFT p99 before/during/after the drain window, per-cell counts.
* **kill** — same topology, but cell A's daemon gets SIGKILL with no
  warning. The router's per-cell circuit breaker must absorb the dial
  failures: no request errors surface while the survivor has capacity,
  and the first post-kill success lands within one breaker window.
* **sim** — the deterministic twin: the bundled ``federation-two-cell``
  scenario run twice at the same seed through
  :class:`~torchx_tpu.federation.sim.FederationSimHarness`; journal
  sha256s must be byte-identical, drops must be zero.

Usage:
    python scripts/bench_federation.py [--ticks 30] [--per-tick 10]
        [--out BENCH_FED_r01.json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import signal
import subprocess
import sys
import tempfile
import time


def _p99(samples: list[float]) -> float | None:
    if not samples:
        return None
    xs = sorted(samples)
    return round(xs[min(len(xs) - 1, math.ceil(0.99 * len(xs)) - 1)], 6)


def _boot_cell(name: str, state_dir: str) -> tuple[subprocess.Popen, dict]:
    """Start one `tpx control --cell NAME` daemon; return (proc, discovery)."""
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "torchx_tpu.cli.main",
            "control",
            "--cell",
            name,
            "--state-dir",
            state_dir,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    discovery = os.path.join(state_dir, "control.json")
    deadline = time.monotonic() + 60
    while not os.path.exists(discovery):
        if proc.poll() is not None:
            raise RuntimeError(f"cell {name} died: {proc.stdout.read()}")
        if time.monotonic() > deadline:
            raise RuntimeError(f"cell {name} never wrote its discovery file")
        time.sleep(0.1)
    with open(discovery) as f:
        return proc, json.load(f)


def _router_for(docs: dict):
    from torchx_tpu.federation import CellHandle, CellRegistry, FederationRouter

    registry = CellRegistry()
    for name, doc in docs.items():
        registry.add(name, doc["addr"], doc["token"])
    handles = []
    for spec in registry.cells():
        h = CellHandle(spec)
        # home-region affinity: each cell advertises its own digest space
        h.update_prefix_digests([f"{spec.name}:blk{i}" for i in range(8)])
        handles.append(h)
    return FederationRouter(handles, probe_ttl_s=0.25)


def _diurnal(frac: float, phase_h: float, per_tick: int) -> int:
    day = frac + phase_h / 24.0
    return max(1, round(per_tick * (0.65 + 0.35 * math.sin(2 * math.pi * (day - 0.25)))))


def _drive(
    router,
    ticks: int,
    per_tick: int,
    on_tick=None,
    phase_of=None,
    submit_dir: str | None = None,
    tick_s: float = 0.25,
) -> dict:
    """Dispatch a phase-shifted diurnal request trace through the router.

    Each tick lasts ``tick_s`` of wall time (so probe TTLs actually
    expire mid-trace, as they would in production). The bulk traffic is
    routed daemon round-trips (``list``) stamped with the home region's
    prefix chain so affinity keeps steady-state traffic local; one REAL
    job submit rides every tick to exercise the drain-503 spill path.
    Returns per-phase latency samples + outcome counts."""
    from torchx_tpu.federation import FederationError

    regions = {"us-east1": 0.0, "eu-west4": 8.0}
    stats: dict = {
        "requests": 0,
        "dropped": 0,
        "per_cell": {},
        "submits_per_cell": {"pre": {}, "during": {}, "post": {}},
        "samples": {"pre": [], "during": [], "post": []},
        "errors": [],
    }
    for tick in range(ticks):
        t_tick = time.perf_counter()
        if on_tick is not None:
            on_tick(tick)
        phase = phase_of(tick) if phase_of is not None else "pre"
        for region, phase_h in regions.items():
            n = _diurnal(tick / ticks, phase_h, per_tick)
            chain = [f"{region}:blk{i}" for i in range(8)]
            for _ in range(n):
                stats["requests"] += 1
                t0 = time.perf_counter()
                try:
                    cell, _ = router.dispatch(
                        lambda c: c.list(), chain=chain
                    )
                except FederationError as e:
                    stats["dropped"] += 1
                    stats["errors"].append(str(e))
                    continue
                stats["samples"][phase].append(time.perf_counter() - t0)
                stats["per_cell"][cell] = stats["per_cell"].get(cell, 0) + 1
        if submit_dir is not None:
            stats["requests"] += 1
            # alternate the submit's home region so both cells see their
            # share when healthy (and the uncordoned cell's return shows)
            home = list(regions)[tick % len(regions)]
            t0 = time.perf_counter()
            try:
                cell, _ = router.submit(
                    "utils.echo",
                    ["--msg", f"bench-{tick}"],
                    "local",
                    chain=[f"{home}:blk{i}" for i in range(8)],
                    cfg={"log_dir": os.path.join(submit_dir, str(tick))},
                )
            except FederationError as e:
                stats["dropped"] += 1
                stats["errors"].append(str(e))
            else:
                stats["samples"][phase].append(time.perf_counter() - t0)
                per = stats["submits_per_cell"][phase]
                per[cell] = per.get(cell, 0) + 1
        remaining = tick_s - (time.perf_counter() - t_tick)
        if remaining > 0:
            time.sleep(remaining)
    return stats


def _finish(stats: dict) -> dict:
    samples = stats.pop("samples")
    all_samples = [s for xs in samples.values() for s in xs]
    stats["ttft_p99_s"] = _p99(all_samples)
    stats["ttft_p99_pre_s"] = _p99(samples["pre"])
    stats["ttft_p99_during_s"] = _p99(samples["during"])
    stats["ttft_p99_post_s"] = _p99(samples["post"])
    stats["errors"] = stats["errors"][:5]  # samples, not the full flood
    return stats


def bench_drain(base: str, ticks: int, per_tick: int) -> dict:
    """Planned failover: drain cell A mid-trace, uncordon near the end."""
    from torchx_tpu.control.client import ControlClient

    drain_at, uncordon_at = ticks // 3, (2 * ticks) // 3
    procs, docs = {}, {}
    try:
        for name in ("us-east1", "eu-west4"):
            procs[name], docs[name] = _boot_cell(
                name, os.path.join(base, "drain", name)
            )
        router = _router_for(docs)
        victim = ControlClient(
            docs["us-east1"]["addr"], docs["us-east1"]["token"]
        )

        def on_tick(tick: int) -> None:
            if tick == drain_at:
                victim.cell_drain()
            elif tick == uncordon_at:
                victim.cell_uncordon()

        def phase_of(tick: int) -> str:
            if tick < drain_at:
                return "pre"
            return "during" if tick < uncordon_at else "post"

        stats = _drive(
            router,
            ticks,
            per_tick,
            on_tick=on_tick,
            phase_of=phase_of,
            submit_dir=os.path.join(base, "drain", "logs"),
        )
        stats["drained_cell"] = "us-east1"
        return _finish(stats)
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            p.wait(timeout=10)


def bench_kill(base: str, ticks: int, per_tick: int) -> dict:
    """Unplanned failover: SIGKILL cell A's daemon mid-trace."""
    from torchx_tpu import settings

    kill_at = ticks // 2
    procs, docs = {}, {}
    killed_at_s: list[float] = []
    recovered_at_s: list[float] = []
    try:
        for name in ("us-east1", "eu-west4"):
            procs[name], docs[name] = _boot_cell(
                name, os.path.join(base, "kill", name)
            )
        router = _router_for(docs)

        def on_tick(tick: int) -> None:
            if tick == kill_at:
                procs["us-east1"].send_signal(signal.SIGKILL)
                killed_at_s.append(time.perf_counter())

        def phase_of(tick: int) -> str:
            return "pre" if tick < kill_at else "during"

        stats = _drive(
            router,
            ticks,
            per_tick,
            on_tick=on_tick,
            phase_of=phase_of,
            submit_dir=os.path.join(base, "kill", "logs"),
        )
        # first successful dispatch after the kill bounds the blackout
        post = stats["samples"]["during"]
        if killed_at_s and post:
            recovered_at_s.append(killed_at_s[0] + post[0])
        stats["killed_cell"] = "us-east1"
        stats["breaker_window_s"] = settings.FEDERATION_BREAKER_COOLDOWN_SECONDS
        stats["spillover_within_breaker_window"] = bool(
            post and post[0] <= settings.FEDERATION_BREAKER_COOLDOWN_SECONDS
        )
        return _finish(stats)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            p.wait(timeout=10)


def bench_sim(base: str, seed: int = 11) -> dict:
    """The deterministic twin: same-seed runs must be byte-identical."""
    from torchx_tpu.federation.sim import FederationSimHarness
    from torchx_tpu.sim.scenarios import get_scenario

    reports = []
    for tag in ("a", "b"):
        scenario = get_scenario("federation-two-cell")
        harness = FederationSimHarness(
            scenario, seed=seed, state_dir=os.path.join(base, "sim", tag)
        )
        reports.append(harness.run())
    a, b = reports
    return {
        "scenario": "federation-two-cell",
        "seed": seed,
        "journal_sha256": a.journal_sha256,
        "deterministic": a.journal_sha256 == b.journal_sha256,
        "stats": a.stats,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--ticks", type=int, default=30)
    parser.add_argument("--per-tick", type=int, default=10)
    parser.add_argument("--out", default="BENCH_FED_r01.json")
    args = parser.parse_args(argv)

    base = tempfile.mkdtemp(prefix="tpx_bench_fed_")
    os.environ.setdefault("TPX_OBS_DIR", os.path.join(base, "obs"))
    os.environ["TPX_FEDERATION_DIR"] = os.path.join(base, "fed")
    os.environ.setdefault("TPX_WATCH_INTERVAL", "0.1")

    drain = bench_drain(os.path.join(base, "d"), args.ticks, args.per_tick)
    # fresh registry root per phase: the kill run re-registers its cells
    os.environ["TPX_FEDERATION_DIR"] = os.path.join(base, "fed-kill")
    kill = bench_kill(os.path.join(base, "k"), args.ticks, args.per_tick)
    sim = bench_sim(base)

    report = {
        "bench": "federation_failover",
        "ticks": args.ticks,
        "per_tick": args.per_tick,
        "drain": drain,
        "kill": kill,
        "sim": sim,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report, indent=1, sort_keys=True))

    ok = (
        drain["dropped"] == 0
        and kill["dropped"] == 0
        and kill["spillover_within_breaker_window"]
        and sim["deterministic"]
        and sim["stats"]["dropped"] == 0
        # while a cell is down, every submit lands on the survivor
        and set(drain["submits_per_cell"]["during"]) == {"eu-west4"}
        and set(kill["submits_per_cell"]["during"]) == {"eu-west4"}
    )
    print(f"federation failover acceptance: {'PASS' if ok else 'FAIL'}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
