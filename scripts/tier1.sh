#!/usr/bin/env bash
# Tier-1 verify gate: the exact command from ROADMAP.md, wrapped so every
# contributor (and CI) runs the same thing. Excludes tests marked `slow`
# (registered in pyproject.toml); prints DOTS_PASSED and exits with
# pytest's status.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# Observability smoke: run a local app under tracing and assert the JSONL
# trace is written, parseable, and renderable by `tpx trace`.
obs_dir=$(mktemp -d /tmp/tpx_obs_smoke.XXXXXX)
if timeout -k 10 120 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$obs_dir" \
    python - <<'EOF'
import glob, json, os, sys
from torchx_tpu.cli.main import main
from torchx_tpu.obs import timeline

main(["run", "-s", "local", "--wait", "utils.echo", "--msg", "obs-smoke"])
paths = glob.glob(os.path.join(os.environ["TPX_OBS_DIR"], "*", "trace.jsonl"))
assert paths, "no trace.jsonl written"
records = [json.loads(l) for p in paths for l in open(p) if l.strip()]
spans = [r for r in records if timeline.is_span(r)]
assert any(s["name"] == "runner.run_component" for s in spans), spans
app_ids = {s["attrs"]["app_id"] for s in spans if "app_id" in s.get("attrs", {})}
assert app_ids, "no span carries an app_id"
main(["trace", app_ids.pop(), "--metrics"])
EOF
then echo "OBS_SMOKE=ok"; else echo "OBS_SMOKE=FAILED"; rc=1; fi
rm -rf "$obs_dir"

# Lint smoke: `tpx lint` must pass a known-good AppDef (exit 0), refuse a
# deliberately broken one (exit 1, >= 3 distinct codes), and emit stable
# machine-readable --json.
if timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json, subprocess, sys, tempfile
from torchx_tpu.specs.api import AppDef, BindMount, Resource, Role, TpuSlice
from torchx_tpu.specs.serialize import appdef_to_dict

def dump(app):
    f = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
    json.dump(appdef_to_dict(app), f)
    f.close()
    return f.name

good = dump(AppDef(name="good", roles=[Role(name="echo", image="/", entrypoint="echo", args=["hi"])]))
bad = dump(AppDef(name="bad", roles=[Role(
    name="trainer", image="img", entrypoint="python",
    env={"TPX_REPLICA_ID": "0"},
    mounts=[BindMount(src_path="/a", dst_path="/x"), BindMount(src_path="/b", dst_path="/x")],
    resource=Resource(tpu=TpuSlice("v5e", 16, "2x2x4")))]))

tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "lint"]
r = subprocess.run(tpx + ["-s", "local", good], capture_output=True, text=True)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
r = subprocess.run(tpx + ["-s", "tpu_vm", bad], capture_output=True, text=True)
assert r.returncode == 1, (r.returncode, r.stdout, r.stderr)
r = subprocess.run(tpx + ["-s", "tpu_vm", "--json", bad], capture_output=True, text=True)
assert r.returncode == 1, (r.returncode, r.stdout, r.stderr)
doc = json.loads(r.stdout)
assert doc["version"] == 1 and doc["summary"]["error"] >= 3, doc
assert len({d["code"] for d in doc["diagnostics"]}) >= 3, doc
EOF
then echo "LINT_SMOKE=ok"; else echo "LINT_SMOKE=FAILED"; rc=1; fi

# Self-lint: the legacy entry point (now a shim over the selfcheck pass
# engine) keeps its contract — jax-free layers, scheduler subprocess
# seam, sim-hosted wall-clock discipline; "SELF_LINT: clean" + exit 0.
if timeout -k 10 60 python scripts/lint_internal.py
then echo "SELF_LINT=ok"; else echo "SELF_LINT=FAILED"; rc=1; fi

# Selfcheck: the whole-program invariant analyzer must run clean (zero
# unsuppressed TPX9xx findings against the checked-in triaged baseline),
# its --json report must be stable/parseable, and `tpx selfcheck --help`
# must never import jax (the analyzer rides the CLI fast path).
if timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json, subprocess, sys

tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "selfcheck"]
r = subprocess.run(tpx, capture_output=True, text=True, timeout=90)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
r = subprocess.run(tpx + ["--json"], capture_output=True, text=True, timeout=90)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
doc = json.loads(r.stdout)
assert doc["version"] == 1 and doc["diagnostics"] == [], doc
assert doc["suppressed"] >= 0, doc

# the selfcheck verb rides the lazy dispatcher: help never imports jax
probe = (
    "import sys\n"
    "from torchx_tpu.cli.main import main\n"
    "try: main(['selfcheck', '--help'])\n"
    "except SystemExit: pass\n"
    "assert 'jax' not in sys.modules, 'tpx selfcheck --help imported jax'\n"
)
r = subprocess.run([sys.executable, "-c", probe], capture_output=True,
                   text=True, timeout=60)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "SELFCHECK=ok"; else echo "SELFCHECK=FAILED"; rc=1; fi

# Explain smoke: `tpx explain` on a builtin component must statically
# report the MoE-mesh resharding boundary (the involuntary-full-remat
# shape behind the MULTICHIP r03/r04 warning -> TPX700 ERROR, exit 1) and
# an HBM fit verdict — without the analyzer importing jax.
if timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json, subprocess, sys

tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "explain"]
argv = ["dist.spmd", "-j", "1x8", "-m", "my.custom_trainer", "--",
        "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
        "--batch", "8", "--seq", "128"]
r = subprocess.run(tpx + ["--json"] + argv, capture_output=True, text=True)
assert r.returncode == 1, (r.returncode, r.stdout, r.stderr)
doc = json.loads(r.stdout)
assert doc["version"] == 1, doc
role = doc["roles"][0]
kinds = {b["kind"] for b in role["sharding"]["boundaries"]}
assert "full_remat" in kinds, role["sharding"]
assert role["hbm"]["verdict"] in ("fits", "exceeds"), role["hbm"]
codes = {d["code"] for d in role["diagnostics"]}
assert "TPX700" in codes, codes

# same mesh, stock trainer: propagation proves it safe (exit 0)
r = subprocess.run(
    tpx + ["dist.spmd", "-j", "1x8", "-m", "torchx_tpu.examples.train_llama",
           "--", "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1"],
    capture_output=True, text=True)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
assert "FITS" in r.stdout or "EXCEEDS" in r.stdout, r.stdout

# the analyzer itself must never touch jax
probe = (
    "import sys\n"
    "from torchx_tpu.cli.main import main\n"
    "try: main(['explain', 'dist.spmd', '-j', '1x8', '-m', 'x.y', '--',\n"
    "           '--config', 'moe_tiny', '--mesh', 'ep=2,fsdp=-1'])\n"
    "except SystemExit: pass\n"
    "assert 'jax' not in sys.modules, 'tpx explain imported jax'\n"
)
r = subprocess.run([sys.executable, "-c", probe], capture_output=True, text=True)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "EXPLAIN_SMOKE=ok"; else echo "EXPLAIN_SMOKE=FAILED"; rc=1; fi

# Resilience smoke: a fault-injected local run must succeed anyway —
# the injected transient describe failures are absorbed by in-seam
# retries (retry metric non-zero), never surfacing to the user.
res_dir=$(mktemp -d /tmp/tpx_res_smoke.XXXXXX)
if timeout -k 10 120 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$res_dir" \
    TPX_FAULT_PLAN='[{"backend": "local", "op": "describe", "nth": 1, "times": 2, "mode": "transient", "message": "injected 503"}]' \
    python - <<'EOF'
from torchx_tpu.cli.main import main
from torchx_tpu.obs import metrics as obs_metrics

main(["run", "-s", "local", "--wait", "utils.echo", "--msg", "res-smoke"])
retries = obs_metrics.CONTROL_PLANE_RETRIES.value(
    backend="local", op="describe", kind="UNAVAILABLE"
)
assert retries >= 2, f"expected >= 2 in-seam retries, saw {retries}"
EOF
then echo "RESILIENCE_SMOKE=ok"; else echo "RESILIENCE_SMOKE=FAILED"; rc=1; fi
rm -rf "$res_dir"

# Remat smoke: the MoE/expert-parallel dryrun leg (the r03 gather shape
# that used to trip GSPMD's replicate+reslice fallback) must compile with
# zero involuntary-full-rematerialization warnings and, where Shardy is
# available, without the GSPMD sharding-propagation deprecation warning.
remat_log=$(mktemp /tmp/tpx_remat_smoke.XXXXXX)
if timeout -k 10 420 env _TPX_DRYRUN_LEGS=moe \
    python -c 'import __graft_entry__ as g; g.dryrun_multichip(8)' \
    >"$remat_log" 2>&1 \
  && ! grep -q "Involuntary full rematerialization" "$remat_log" \
  && ! { grep -q "shardy=on" "$remat_log" \
         && grep -q "GSPMD sharding propagation is going to be deprecated" "$remat_log"; }
then echo "REMAT_SMOKE=ok"; else echo "REMAT_SMOKE=FAILED"; rc=1; cat "$remat_log"; fi
rm -f "$remat_log"

# CLI fast-path smoke: the lazy dispatcher must keep `tpx --help` and
# `tpx list` off the heavy import path — jax (and the run-path command
# modules) must never enter sys.modules, and help must render inside a
# tight wall budget (the whole point of the warm-launch fast path).
if timeout -k 10 20 env JAX_PLATFORMS=cpu python - <<'EOF'
import sys
from torchx_tpu.cli.main import main

try:
    main(["--help"])
except SystemExit:
    pass
forbidden = ["jax", "numpy", "torchx_tpu.cli.cmd_run", "torchx_tpu.cli.cmd_lint"]
leaked = [m for m in forbidden if m in sys.modules]
assert not leaked, f"tpx --help imported {leaked}"

try:
    main(["list", "-s", "local"])
except SystemExit:
    pass
leaked = [m for m in ("jax", "torchx_tpu.cli.cmd_run") if m in sys.modules]
assert not leaked, f"tpx list imported {leaked}"
EOF
then echo "CLI_SMOKE=ok"; else echo "CLI_SMOKE=FAILED"; rc=1; fi

# Gang smoke: a local-scheduler preemption drill supervised with elastic
# reshape — the first attempt is "preempted" (drill exit code), and the
# resubmitted attempt must land on a shrunken-mesh dryrun ($TPX_MESH),
# asserted from the durable attempt ledger.
gang_dir=$(mktemp -d /tmp/tpx_gang_smoke.XXXXXX)
if timeout -k 10 120 env JAX_PLATFORMS=cpu \
    TPX_OBS_DIR="$gang_dir/obs" TPX_SUPERVISOR_DIR="$gang_dir/sup" \
    python - <<'EOF'
import os
from torchx_tpu.runner.api import Runner
from torchx_tpu.schedulers.local_scheduler import LocalScheduler
from torchx_tpu.specs.api import AppDef, Role
from torchx_tpu.supervisor import SupervisorPolicy
from torchx_tpu.supervisor.ledger import AttemptLedger

# exits with the drill code until the supervisor resubmits with a
# degraded $TPX_MESH; the reshaped attempt then succeeds
script = 'if [ -n "$TPX_MESH" ]; then exit 0; fi; exit 67'
app = AppDef(name="gang-drill", roles=[Role(
    name="w", image="", entrypoint="sh", args=["-c", script],
    env={"TPX_SIMULATE_PREEMPTION_EXIT": "67"},
)])
sched = LocalScheduler(session_name="gang-smoke", cache_size=10)
runner = Runner("gang-smoke", {"local": lambda session_name, **kw: sched})
with runner:
    info = runner.dryrun(
        app, "local", cfg={"log_dir": os.environ["TPX_OBS_DIR"] + "/logs"}
    )
    result = runner.supervise(info, SupervisorPolicy(
        max_preemptions=2, backoff_seconds=0.01, jitter=0.0,
        poll_interval=0.05, elastic_reshape=True, mesh="fsdp=-1",
        devices_per_replica=8,
    ), session="gang-smoke")
assert result.succeeded, result.status
assert result.attempts == 2, result.attempts
submitted = [
    e for e in AttemptLedger("gang-smoke").entries()
    if e.get("transition") == "submitted"
]
assert len(submitted) == 2, submitted
assert submitted[0].get("mesh") is None, submitted[0]
assert submitted[1]["mesh"] == "pp=1,dp=1,fsdp=4,ep=1,tp=1,sp=1", submitted[1]
EOF
then echo "GANG_SMOKE=ok"; else echo "GANG_SMOKE=FAILED"; rc=1; fi
rm -rf "$gang_dir"

# Control smoke: boot the `tpx control` daemon, submit + wait through the
# proxying CLI (TPX_CONTROL_ADDR), assert the journaled job reached
# terminal and the daemon's /metricz exports control-plane ops, and keep
# `tpx --help` jax-free with the control command registered.
ctl_dir=$(mktemp -d /tmp/tpx_ctl_smoke.XXXXXX)
if timeout -k 10 180 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$ctl_dir/obs" \
    TPX_CONTROL_DIR="$ctl_dir/control" TPX_WATCH_INTERVAL=0.1 \
    python - <<'EOF'
import json, os, subprocess, sys, time, urllib.request

ctl = os.environ["TPX_CONTROL_DIR"]
daemon = subprocess.Popen(
    [sys.executable, "-m", "torchx_tpu.cli.main", "control"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
try:
    discovery = os.path.join(ctl, "control.json")
    deadline = time.monotonic() + 60
    while not os.path.exists(discovery):
        assert daemon.poll() is None, daemon.stdout.read()
        assert time.monotonic() < deadline, "daemon never wrote discovery"
        time.sleep(0.1)
    doc = json.load(open(discovery))
    addr = doc["addr"]

    env = dict(os.environ, TPX_CONTROL_ADDR=addr)
    tpx = [sys.executable, "-m", "torchx_tpu.cli.main"]
    r = subprocess.run(
        tpx + ["run", "-s", "local", "--wait", "utils.echo", "--msg", "ctl-smoke"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    handle = r.stdout.splitlines()[0].strip()
    assert handle.startswith("local://"), r.stdout

    r = subprocess.run(
        tpx + ["status", handle], capture_output=True, text=True, env=env,
        timeout=60,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "SUCCEEDED" in r.stdout, r.stdout

    with urllib.request.urlopen(f"{addr}/metricz", timeout=10) as resp:
        metrics = resp.read().decode()
    assert "tpx_control_requests_total" in metrics, metrics[:2000]
    assert 'op="submit"' in metrics and 'op="status"' in metrics, metrics[:2000]
    assert "tpx_watch_events_total" in metrics, metrics[:2000]
finally:
    daemon.terminate()
    daemon.wait(timeout=10)

# the proxying layer must not drag the control (or jax) modules into the
# help fast path — only the lazy dispatcher's table may know about them
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['--help'])\n"
        "except SystemExit: pass\n"
        "leaked = [m for m in ('jax', 'numpy', 'torchx_tpu.control',"
        " 'torchx_tpu.cli.cmd_control') if m in sys.modules]\n"
        "assert not leaked, f'tpx --help imported {leaked}'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
assert "control" in r.stdout, r.stdout
EOF
then echo "CONTROL_SMOKE=ok"; else echo "CONTROL_SMOKE=FAILED"; rc=1; fi
rm -rf "$ctl_dir"

# Serving smoke: boot generate_server on the tiny config (CPU, continuous
# engine, ephemeral port), answer /healthz, decode one /v1/generate, assert
# the continuous-batching occupancy gauge is exported on /metricz, repeat
# the same prompt and assert it hit the radix prefix cache, and check the
# serve-pool CLI's disaggregation flags stay jax-free.
serve_dir=$(mktemp -d /tmp/tpx_serve_smoke.XXXXXX)
if timeout -k 10 300 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$serve_dir" \
    python - <<'EOF'
import json, subprocess, sys, threading, urllib.request
from torchx_tpu.apps.generate_server import serve

ready = threading.Event()
server = serve("tiny", port=0, ready_event=ready, engine="continuous", max_batch=4)
assert ready.wait(120), "server never became ready"
threading.Thread(target=server.serve_forever, daemon=True).start()
base = f"http://127.0.0.1:{server.server_address[1]}"
try:
    with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
        health = json.loads(r.read())
    assert health["status"] == "ok" and health["engine"] == "continuous", health
    assert "occupancy" in health and "queue_depth" in health, health
    assert health["serve_role"] == "unified", health
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"tokens": [[1, 2, 3]], "max_new_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        body = json.loads(r.read())
    (seq,) = body["tokens"]
    assert seq[:3] == [1, 2, 3] and len(seq) == 7, body
    # repeated prompt long enough to span a full cache block (> block_size
    # tokens at the default block_size=16): the second pass must hit the
    # radix prefix cache and both must decode identical tokens
    prompt = list(range(1, 21))
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"tokens": [prompt], "max_new_tokens": 4}).encode(),
        headers={"Content-Type": "application/json"},
    )
    outs = []
    for _ in range(2):
        with urllib.request.urlopen(req, timeout=120) as r:
            outs.append(json.loads(r.read())["tokens"][0])
    assert outs[0] == outs[1], outs
    with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
        health = json.loads(r.read())
    assert health["prefix_summary"], health
    with urllib.request.urlopen(f"{base}/metricz", timeout=10) as r:
        metrics = r.read().decode()
    assert "tpx_serve_slot_occupancy" in metrics, metrics[:2000]
    assert "tpx_serve_tokens_total" in metrics, metrics[:2000]
    hits = [
        line for line in metrics.splitlines()
        if line.startswith("tpx_serve_prefix_hits_total")
    ]
    assert hits and float(hits[0].split()[-1]) > 0, metrics[:2000]
finally:
    server.shutdown()
    server.service.close()

# the disaggregation flags ride the help fast path: `tpx serve-pool
# --help` must show them without importing jax
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['serve-pool', '--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx serve-pool --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
for flag in ("--disaggregate", "--kv-transfer", "--prefix-cache-reserve"):
    assert flag in r.stdout, (flag, r.stdout)
EOF
then echo "SERVE_SMOKE=ok"; else echo "SERVE_SMOKE=FAILED"; rc=1; fi
rm -rf "$serve_dir"

# Fleet smoke: boot `tpx control --fleet`, fill the modeled fleet with a
# serve gang, queue a batch then an interactive gang, and assert `tpx
# queue` orders interactive first, /metricz exports the tpx_fleet_*
# gauges, and `tpx --help` stays jax- AND fleet-free.
fleet_dir=$(mktemp -d /tmp/tpx_fleet_smoke.XXXXXX)
if timeout -k 10 180 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$fleet_dir/obs" \
    TPX_CONTROL_DIR="$fleet_dir/control" TPX_WATCH_INTERVAL=0.1 \
    python - <<'EOF'
import json, os, subprocess, sys, time, urllib.request

ctl = os.environ["TPX_CONTROL_DIR"]
daemon = subprocess.Popen(
    [sys.executable, "-m", "torchx_tpu.cli.main", "control",
     "--fleet", "sim:v5e-1x4"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
try:
    discovery = os.path.join(ctl, "control.json")
    deadline = time.monotonic() + 60
    while not os.path.exists(discovery):
        assert daemon.poll() is None, daemon.stdout.read()
        assert time.monotonic() < deadline, "daemon never wrote discovery"
        time.sleep(0.1)
    doc = json.load(open(discovery))
    addr, token = doc["addr"], doc["token"]

    from torchx_tpu.control.client import ControlClient
    client = ControlClient(addr, token)
    log = os.path.join(os.environ["TPX_OBS_DIR"], "logs")
    filler = client.submit_job(
        "utils.sh", ["sleep", "30"], "local", cfg={"log_dir": log},
        priority="serve", replicas=4,
    )
    assert filler.get("handle", "").startswith("local://"), filler
    batch = client.submit_job(
        "utils.sh", ["sleep", "1"], "local", cfg={"log_dir": log},
        priority="batch",
    )
    inter = client.submit_job(
        "utils.sh", ["sleep", "1"], "local", cfg={"log_dir": log},
        priority="interactive",
    )
    assert batch.get("queued") and inter.get("queued"), (batch, inter)

    env = dict(os.environ, TPX_CONTROL_ADDR=addr)
    r = subprocess.run(
        [sys.executable, "-m", "torchx_tpu.cli.main", "queue"],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "queued (2):" in r.stdout, r.stdout
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("#")]
    assert "interactive" in lines[0] and "batch" in lines[1], r.stdout

    with urllib.request.urlopen(f"{addr}/metricz", timeout=10) as resp:
        metrics = resp.read().decode()
    assert 'tpx_fleet_queue_depth{klass="interactive"} 1' in metrics, metrics[:2000]
    assert 'tpx_fleet_chips{state="free"} 0' in metrics, metrics[:2000]
    assert 'tpx_fleet_placements_total{klass="serve"} 1' in metrics, metrics[:2000]
finally:
    daemon.terminate()
    daemon.wait(timeout=10)

# the queue verb must ride the same lazy dispatcher: no fleet (or jax)
# modules on the help fast path
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['--help'])\n"
        "except SystemExit: pass\n"
        "leaked = [m for m in ('jax', 'numpy', 'torchx_tpu.fleet',"
        " 'torchx_tpu.control', 'torchx_tpu.cli.cmd_queue') if m in sys.modules]\n"
        "assert not leaked, f'tpx --help imported {leaked}'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
assert "queue" in r.stdout, r.stdout
EOF
then echo "FLEET_SMOKE=ok"; else echo "FLEET_SMOKE=FAILED"; rc=1; fi
rm -rf "$fleet_dir"

# Tune smoke: `tpx tune` over the tiny builtin space on CPU — static
# pruning must kill candidates with a journaled TPX7xx verdict at zero
# device seconds, the winner's plan artifact must be emitted and then
# ACCEPTED by the submit gate (and a drifted config refused, TPX706),
# and `tpx tune --help` must stay jax-free.
tune_dir=$(mktemp -d /tmp/tpx_tune_smoke.XXXXXX)
if timeout -k 10 300 env JAX_PLATFORMS=cpu TPX_TUNE_DIR="$tune_dir" \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'EOF'
import json, os, subprocess, sys

tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "tune"]
r = subprocess.run(
    tpx + ["--space", "tiny-smoke", "--devices", "8", "--top-k", "1",
           "--no-aot", "--json"],
    capture_output=True, text=True, timeout=240,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
doc = json.loads(r.stdout)
report = doc["report"]
assert report["pruned_static"] >= 1, report
assert any(c.startswith("TPX7") for c in report["pruned_by_code"]), report
assert report["device_seconds_pruning"] == 0.0, report
assert report["measured"] >= 1, report
art = doc["artifact"]
assert art and os.path.exists(art), art
assert json.load(open(art))["digest"], art

# the emitted artifact pins the submit gate: the tuned config passes...
from torchx_tpu.analyze import analyze
from torchx_tpu.components import dist

win = doc["winner"]["candidate"]
def app_for(batch, policy):
    return dist.spmd(
        "--config", win["config"], "--mesh", win["mesh_spec"],
        "--batch", str(batch), "--seq", str(win["seq"]),
        "--remat-policy", policy,
        m="torchx_tpu.examples.train_llama", j="1x8",
    )
os.environ["TPX_PLAN_ARTIFACT"] = art
codes = {d.code for d in analyze(app_for(win["batch"], win["remat_policy"])).diagnostics}
assert "TPX706" not in codes and "TPX707" not in codes, codes
# ... and a config that drifted from the tuned plan is refused
codes = {d.code for d in analyze(app_for(win["batch"] * 2, win["remat_policy"])).diagnostics}
assert "TPX706" in codes, codes

# the tune verb rides the lazy dispatcher: help never imports jax
probe = (
    "import sys\n"
    "from torchx_tpu.cli.main import main\n"
    "try: main(['tune', '--help'])\n"
    "except SystemExit: pass\n"
    "assert 'jax' not in sys.modules, 'tpx tune --help imported jax'\n"
)
r = subprocess.run([sys.executable, "-c", probe], capture_output=True, text=True)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "TUNE_SMOKE=ok"; else echo "TUNE_SMOKE=FAILED"; rc=1; fi
rm -rf "$tune_dir"

# Top smoke: boot `tpx control` with an SLO spec, render one `tpx top
# --once` frame against it (header + slo line + metrics section), check
# the --json snapshot parses, and keep the verb off the help fast path.
top_dir=$(mktemp -d /tmp/tpx_top_smoke.XXXXXX)
if timeout -k 10 120 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$top_dir/obs" \
    TPX_CONTROL_DIR="$top_dir/control" \
    python - <<'EOF'
import json, os, subprocess, sys, time

ctl = os.environ["TPX_CONTROL_DIR"]
daemon = subprocess.Popen(
    [sys.executable, "-m", "torchx_tpu.cli.main", "control",
     "--slo", "p99-ttft", "--scrape-interval", "0.2"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
try:
    discovery = os.path.join(ctl, "control.json")
    deadline = time.monotonic() + 60
    while not os.path.exists(discovery):
        assert daemon.poll() is None, daemon.stdout.read()
        assert time.monotonic() < deadline, "daemon never wrote discovery"
        time.sleep(0.1)
    addr = json.load(open(discovery))["addr"]
    env = dict(os.environ, TPX_CONTROL_ADDR=addr)
    tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "top"]
    r = subprocess.run(tpx + ["--once"], capture_output=True, text=True,
                       env=env, timeout=60)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert r.stdout.startswith("tpx top —"), r.stdout
    assert "slo:" in r.stdout, r.stdout
    r = subprocess.run(tpx + ["--json"], capture_output=True, text=True,
                       env=env, timeout=60)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    snap = json.loads(r.stdout)
    assert snap["alerts"]["enabled"] and "p99-ttft" in snap["alerts"]["slos"], snap
finally:
    daemon.terminate()
    daemon.wait(timeout=10)

# the top verb rides the lazy dispatcher: help never imports it (or jax)
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['--help'])\n"
        "except SystemExit: pass\n"
        "leaked = [m for m in ('jax', 'torchx_tpu.cli.cmd_top')"
        " if m in sys.modules]\n"
        "assert not leaked, f'tpx --help imported {leaked}'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
assert "top" in r.stdout, r.stdout
EOF
then echo "TOP_SMOKE=ok"; else echo "TOP_SMOKE=FAILED"; rc=1; fi
rm -rf "$top_dir"

# Pipeline smoke: a tiny train→eval→promote DAG through `tpx control` on
# the real local scheduler must reach PROMOTED, its journaled stages must
# be visible via `tpx pipeline status`, and the verb rides the lazy
# dispatcher (`tpx pipeline --help` never imports jax).
pl_dir=$(mktemp -d /tmp/tpx_pipeline_smoke.XXXXXX)
if timeout -k 10 180 env JAX_PLATFORMS=cpu TPX_OBS_DIR="$pl_dir/obs" \
    TPX_CONTROL_DIR="$pl_dir/control" TPX_WATCH_INTERVAL=0.1 \
    PL_DIR="$pl_dir" \
    python - <<'EOF'
import json, os, subprocess, sys, time

base = os.environ["PL_DIR"]
ckpt = os.path.join(base, "ckpt")
score = os.path.join(base, "score.json")
logs = os.path.join(base, "logs")
# the train stage writes a checkpoint payload + MANIFEST.json with the
# same sha256 relpath+bytes digest recipe the checkpoint writer uses
train_code = (
    "import hashlib,json,os\n"
    f"ckpt={ckpt!r}\n"
    "p=os.path.join(ckpt,'1'); os.makedirs(p,exist_ok=True)\n"
    "open(os.path.join(p,'w.bin'),'wb').write(b'weights-v1')\n"
    "h=hashlib.sha256()\n"
    "fp=os.path.join(p,'w.bin')\n"
    "h.update(os.path.relpath(fp,p).encode()); h.update(open(fp,'rb').read())\n"
    "json.dump({'latest_step':1,'steps':{'1':{'digest':h.hexdigest()}}},"
    "open(os.path.join(ckpt,'MANIFEST.json'),'w'))\n"
)
spec = {
    "name": "smoke",
    "stages": [
        {"name": "train", "kind": "train", "component": "utils.python",
         "args": ["-c", train_code], "ckpt_dir": ckpt,
         "cfg": {"log_dir": logs}},
        {"name": "eval", "kind": "eval", "component": "utils.python",
         "args": ["-m", "torchx_tpu.apps.eval_main", "--",
                  "--ckpt", "{train.path}", "--out", score,
                  "--score", "0.9"],
         "depends_on": ["train"], "score_file": score, "threshold": 0.5,
         "cfg": {"log_dir": logs}},
        {"name": "promote", "kind": "promote", "depends_on": ["eval"],
         "observe_s": 0.1},
    ],
}
spec_file = os.path.join(base, "spec.json")
json.dump(spec, open(spec_file, "w"))

ctl = os.environ["TPX_CONTROL_DIR"]
daemon = subprocess.Popen(
    [sys.executable, "-m", "torchx_tpu.cli.main", "control"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
)
try:
    discovery = os.path.join(ctl, "control.json")
    deadline = time.monotonic() + 60
    while not os.path.exists(discovery):
        assert daemon.poll() is None, daemon.stdout.read()
        assert time.monotonic() < deadline, "daemon never wrote discovery"
        time.sleep(0.1)
    addr = json.load(open(discovery))["addr"]
    env = dict(os.environ, TPX_CONTROL_ADDR=addr)
    tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "pipeline"]
    r = subprocess.run(tpx + ["submit", "--file", spec_file],
                       capture_output=True, text=True, env=env, timeout=60)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    pid = r.stdout.strip()
    assert pid.startswith("pl_"), r.stdout
    deadline = time.monotonic() + 120
    doc = {}
    while time.monotonic() < deadline:
        r = subprocess.run(tpx + ["status", pid, "--json"],
                           capture_output=True, text=True, env=env,
                           timeout=60)
        assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
        doc = json.loads(r.stdout)
        if doc["state"] in ("PROMOTED", "SUCCEEDED", "FAILED",
                            "ROLLED_BACK", "CANCELLED"):
            break
        time.sleep(0.2)
    assert doc.get("state") == "PROMOTED", doc
    states = {s["name"]: s["state"] for s in doc["stages"]}
    assert states == {"train": "SUCCEEDED", "eval": "SUCCEEDED",
                      "promote": "SUCCEEDED"}, states
    assert doc["incumbent"]["ckpt"] == ckpt, doc["incumbent"]
    # the journal backs the status view: every stage decision is on disk
    kinds = set()
    with open(os.path.join(ctl, "pipelines.jsonl")) as f:
        for line in f:
            kinds.add(json.loads(line).get("kind"))
    assert {"submit", "stage_submit", "stage_done", "gate",
            "promote_step", "incumbent"} <= kinds, kinds
finally:
    daemon.terminate()
    daemon.wait(timeout=10)

# the pipeline verb rides the lazy dispatcher: its help never imports jax
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['pipeline', '--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx pipeline --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "PIPELINE_SMOKE=ok"; else echo "PIPELINE_SMOKE=FAILED"; rc=1; fi
rm -rf "$pl_dir"

# Sim smoke: two same-seed `tpx sim run` invocations of the bundled
# smoke scenario must produce byte-identical journals (the determinism
# contract), the journal must land on disk, and `tpx sim --help` must
# stay jax-free (the whole sim subsystem rides the CLI fast path).
sim_dir=$(mktemp -d /tmp/tpx_sim_smoke.XXXXXX)
if timeout -k 10 180 env JAX_PLATFORMS=cpu SIM_DIR="$sim_dir" \
    python - <<'EOF'
import hashlib, json, os, subprocess, sys

base = os.environ["SIM_DIR"]
tpx = [sys.executable, "-m", "torchx_tpu.cli.main", "sim"]
reports = []
for i in (1, 2):
    out = os.path.join(base, f"run{i}")
    r = subprocess.run(
        tpx + ["run", "--scenario", "smoke-tiny", "--seed", "7",
               "--out", out, "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    reports.append(json.loads(r.stdout))
a, b = reports
assert os.path.exists(a["journal"]), a
raw = open(a["journal"], "rb").read()
assert raw and hashlib.sha256(raw).hexdigest() == a["journal_sha256"], a
assert a["journal_sha256"] == b["journal_sha256"], (a, b)
assert a["stats"]["submitted"] > 0, a
assert a["stats"]["faults"] == 2, a

# the sim verb rides the lazy dispatcher: its help never imports jax
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['sim', '--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx sim --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "SIM_SMOKE=ok"; else echo "SIM_SMOKE=FAILED"; rc=1; fi
rm -rf "$sim_dir"

# Profile smoke: a tiny profiled CPU train run (TPX_PROFILE=1) must leave
# one profile.jsonl whose `tpx profile --json` summary has every core
# phase nonzero, MFU in (0, 1], phases summing to the measured wall time
# (the 5% attribution acceptance bound), and a calibration table whose
# collective_scale moved off 1.0 (the measured-overlap feedback loop).
# `tpx profile --help` must stay jax-free (lint JAX_FREE covers the
# module; this covers the CLI dispatch path).
prof_dir=$(mktemp -d /tmp/tpx_profile_smoke.XXXXXX)
if timeout -k 10 300 env JAX_PLATFORMS=cpu PROF_DIR="$prof_dir" \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'EOF'
import glob, json, os, subprocess, sys

base = os.environ["PROF_DIR"]
os.environ["TPX_OBS_DIR"] = os.path.join(base, "obs")
os.environ["TPX_TUNE_DIR"] = os.path.join(base, "tune")
os.environ["TPX_PROFILE"] = "1"  # the env switch, not the --profile flag

from torchx_tpu.examples.train_llama import main as train_main

train_main(["--config", "tiny", "--mesh", "fsdp=-1", "--batch", "8",
            "--seq", "128", "--steps", "8"])

journals = glob.glob(os.path.join(base, "obs", "*", "profile.jsonl"))
assert len(journals) == 1, journals
r = subprocess.run(
    [sys.executable, "-m", "torchx_tpu.cli.main", "profile",
     journals[0], "--json"],
    capture_output=True, text=True, timeout=120,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
s = json.loads(r.stdout)
assert s["v"] == 1 and s["steps"] > 0, s
for ph in ("data_wait", "forward_backward", "optimizer", "host"):
    assert s["phase_seconds"].get(ph, 0) > 0, (ph, s["phase_seconds"])
assert 0 < s["mfu"] <= 1, s["mfu"]
total = sum(s["phase_seconds"].values()) + sum(s["grad_sync_seconds"].values())
assert abs(total - s["wall_s"]) / s["wall_s"] < 0.05, (total, s["wall_s"])

# the measured-residual loop closed: one profiled run moved the scale
from torchx_tpu.tune.calibrate import CalibrationTable

scale = CalibrationTable.load_default().scales_for("cpu-sim").collective_scale
assert scale != 1.0, scale

# the profile verb rides the lazy dispatcher: its help never imports jax
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['profile', '--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx profile --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "PROFILE_SMOKE=ok"; else echo "PROFILE_SMOKE=FAILED"; rc=1; fi
rm -rf "$prof_dir"

# Overlap smoke: the step-time knobs end to end on the CPU sim. A
# profiled train through the CLI flags (--grad-bucket-mb auto +
# reference kernels) must surface a measured overlap_frac in
# `tpx profile --json`; an unprofiled bucketed run must produce a loss
# BITWISE identical to the single-sync run (bucket boundaries are value
# identities); and `tpx --help` must stay jax-free with the new knobs
# in the tree.
ov_dir=$(mktemp -d /tmp/tpx_overlap_smoke.XXXXXX)
if timeout -k 10 420 env JAX_PLATFORMS=cpu OV_DIR="$ov_dir" \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python - <<'EOF'
import glob, json, os, subprocess, sys, time

base = os.environ["OV_DIR"]
os.environ["TPX_OBS_DIR"] = os.path.join(base, "obs")
os.environ["TPX_TUNE_DIR"] = os.path.join(base, "tune")
os.environ["TPX_PROFILE"] = "1"

from torchx_tpu.examples.train_llama import main as train_main
from torchx_tpu.examples.train_llama import parse_mesh_arg, train
from torchx_tpu.models import llama

train_main(["--config", "tiny", "--mesh", "fsdp=-1", "--batch", "8",
            "--seq", "128", "--steps", "8",
            "--grad-bucket-mb", "auto", "--kernels", "reference"])

journals = glob.glob(os.path.join(base, "obs", "*", "profile.jsonl"))
assert len(journals) == 1, journals
r = subprocess.run(
    [sys.executable, "-m", "torchx_tpu.cli.main", "profile",
     journals[0], "--json"],
    capture_output=True, text=True, timeout=120,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
s = json.loads(r.stdout)
assert s["overlap_frac"] is not None, s
assert 0.0 <= s["overlap_frac"] <= 1.0, s["overlap_frac"]

# bitwise loss parity: bucketed vs single-sync, unprofiled
del os.environ["TPX_PROFILE"]
cfg = llama.llama_tiny()
mesh = parse_mesh_arg("fsdp=-1")
a = train(cfg, mesh, batch=8, seq=128, steps=8,
          launch_anchor=time.monotonic())
b = train(cfg, mesh, batch=8, seq=128, steps=8, grad_bucket_mb="auto",
          launch_anchor=time.monotonic())
assert b["grad_buckets"] >= 1 and b["grad_bucket_mb"] > 0, b
assert a["loss"] == b["loss"], (a["loss"], b["loss"])

# the launcher CLI stays jax-free with the step-time knobs in the tree
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "OVERLAP_SMOKE=ok"; else echo "OVERLAP_SMOKE=FAILED"; rc=1; fi
rm -rf "$ov_dir"

# Federation smoke: boot two `tpx control` daemons as cells, register
# them with `tpx cell add`, submit through the federation router, drain
# one cell mid-stream with `tpx cell drain`, and assert every subsequent
# request lands on the survivor with ZERO request errors. `tpx cell list
# --json` must report the drained lifecycle state, and `tpx cell --help`
# must stay jax-free on the lazy dispatch path.
fed_dir=$(mktemp -d /tmp/tpx_fed_smoke.XXXXXX)
if timeout -k 10 300 env JAX_PLATFORMS=cpu FED_DIR="$fed_dir" \
    TPX_OBS_DIR="$fed_dir/obs" TPX_FEDERATION_DIR="$fed_dir/fed" \
    TPX_WATCH_INTERVAL=0.1 \
    python - <<'EOF'
import json, os, subprocess, sys, time

base = os.environ["FED_DIR"]
tpx = [sys.executable, "-m", "torchx_tpu.cli.main"]
cells = {"us-east1": None, "eu-west4": None}
daemons = []
try:
    for name in cells:
        state = os.path.join(base, name)
        p = subprocess.Popen(
            tpx + ["control", "--cell", name, "--state-dir", state],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        daemons.append(p)
        discovery = os.path.join(state, "control.json")
        deadline = time.monotonic() + 60
        while not os.path.exists(discovery):
            assert p.poll() is None, p.stdout.read()
            assert time.monotonic() < deadline, f"{name} never wrote discovery"
            time.sleep(0.1)
        cells[name] = json.load(open(discovery))

    for name, doc in cells.items():
        r = subprocess.run(
            tpx + ["cell", "add", name, "--addr", doc["addr"],
                   "--token", doc["token"]],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)

    from torchx_tpu.federation import CellHandle, CellRegistry, FederationRouter

    registry = CellRegistry()
    assert len(registry) == 2, registry.cells()
    router = FederationRouter(
        [CellHandle(spec) for spec in registry.cells()], probe_ttl_s=0.0
    )
    log_dir = os.path.join(base, "logs")

    def submit(i):
        return router.submit(
            "utils.echo", ["--msg", f"fed-{i}"], "local",
            cfg={"log_dir": os.path.join(log_dir, str(i))},
        )

    pre = [submit(i) for i in range(4)]
    assert all(reply.get("handle") for _, reply in pre), pre

    # drain one cell through the CLI; the router must route away from it
    r = subprocess.run(
        tpx + ["cell", "drain", "us-east1", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert json.loads(r.stdout)["draining"] is True, r.stdout

    post = [submit(i) for i in range(4, 10)]  # zero errors: all spill over
    assert all(cell == "eu-west4" for cell, _ in post), post
    assert all(reply.get("handle") for _, reply in post), post

    r = subprocess.run(
        tpx + ["cell", "list", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    listed = json.loads(r.stdout)["cells"]
    assert listed["us-east1"]["state"] in ("DRAINING", "DRAINED"), listed
    assert listed["eu-west4"]["state"] == "HEALTHY", listed
finally:
    for p in daemons:
        p.terminate()
    for p in daemons:
        p.wait(timeout=10)

# the cell verb rides the lazy dispatcher: its help never imports jax
r = subprocess.run(
    [sys.executable, "-c", (
        "import sys\n"
        "from torchx_tpu.cli.main import main\n"
        "try: main(['cell', '--help'])\n"
        "except SystemExit: pass\n"
        "assert 'jax' not in sys.modules, 'tpx cell --help imported jax'\n"
    )],
    capture_output=True, text=True, timeout=60,
)
assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
EOF
then echo "FED_SMOKE=ok"; else echo "FED_SMOKE=FAILED"; rc=1; fi
rm -rf "$fed_dir"
exit $rc
