"""AOT-compile the flagship train step for a v5p slice and prove the HBM fit.

The north-star deliverable (BASELINE.md) is Llama-3-8B at >= 45% MFU on a
TPU v5p-32 slice (16 chips, 95 GB HBM each). No v5p hardware is needed to
know whether a config *fits*: this compiles the exact training step for
the v5p topology and prints the compiler's per-device memory table —
see torchx_tpu/parallel/aot_fit.py for the machinery and
tests/test_aot_fit.py for the CI gate (CPU-backend upper bound).

Run::

    python scripts/aot_memory_fit.py                        # v5p-32 table
    python scripts/aot_memory_fit.py --topology v5p:2x4x4   # v5p-64
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from torchx_tpu.parallel.aot_fit import (
    DEFAULT_HEADROOM,
    GIB,
    V5P_HBM_BYTES,
    compile_fit,
    north_star_cfg,
    tpu_topology_mesh,
)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--topology", default="v5p:2x2x4", help="TPU topology (v5p-32 default)"
    )
    parser.add_argument(
        "--mesh", default="fsdp=8,tp=2", help="axis sizes, e.g. fsdp=8,tp=2"
    )
    parser.add_argument("--config", default="llama3_8b")
    parser.add_argument(
        "--cases",
        default="8:8192:dots,16:8192:dots,32:8192:dots,16:8192:full,8:32768:dots",
        help="comma list of batch:seq:remat_policy",
    )
    parser.add_argument("--headroom", type=float, default=DEFAULT_HEADROOM)
    args = parser.parse_args(argv)

    from torchx_tpu.examples.train_llama import parse_mesh_arg

    mesh = tpu_topology_mesh(args.topology, parse_mesh_arg(args.mesh))
    n = mesh.devices.size
    print(
        f"topology {args.topology}: {n} devices"
        f" ({getattr(mesh.devices.flat[0], 'device_kind', '?')}),"
        f" mesh {dict(mesh.shape)}"
    )
    print(f"HBM budget: {V5P_HBM_BYTES / GIB:.0f} GiB x {args.headroom} headroom")

    base = north_star_cfg()
    if args.config != "llama3_8b":
        from torchx_tpu.examples.train_llama import all_configs

        base = all_configs()[args.config]()

    print(
        "\n| batch | seq | remat | args GiB/dev | temps GiB/dev |"
        " peak GiB/dev | fits |"
    )
    print("|---|---|---|---|---|---|---|")
    ok = True
    for case in args.cases.split(","):
        b, s, pol = case.strip().split(":")
        cfg = dataclasses.replace(base, remat_policy=pol)
        try:
            r = compile_fit(cfg, mesh, int(b), int(s), headroom=args.headroom)
        except Exception as e:  # XLA OOM-at-compile raises ResourceExhausted
            print(f"| {b} | {s} | {pol} | - | - | compile failed: {e} | NO |")
            ok = False
            continue
        print(r.row())
        ok = ok and r.fits
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
