#!/usr/bin/env python
"""Run the component integration suite against a scheduler.

Reference analog: torchx/scripts/component_integration_tests.py (drives the
slurm/k8s/batch e2e CI workflows). Locally::

    python scripts/component_integration_tests.py --scheduler local

Against a cluster::

    python scripts/component_integration_tests.py \
        --scheduler gke -cfg namespace=ml --image us-docker.pkg.dev/p/r/img:1
"""

from __future__ import annotations

import argparse
import sys


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--scheduler", default="local")
    parser.add_argument("--image", default="")
    parser.add_argument(
        "-cfg", "--scheduler_args", default="", help="k=v,k2=v2 scheduler cfg"
    )
    args = parser.parse_args()

    from torchx_tpu.components.integration_tests import IntegComponentTest
    from torchx_tpu.runner.api import get_runner

    with get_runner() as runner:
        cfg = runner.scheduler_run_opts(args.scheduler).cfg_from_str(
            args.scheduler_args
        )
    suite = IntegComponentTest(scheduler=args.scheduler, image=args.image, cfg=cfg)
    results = suite.run_components()
    failed = False
    for r in results:
        mark = "PASS" if r.ok else "FAIL"
        print(f"[{mark}] {r.provider}: state={r.state} handle={r.handle} {r.error or ''}")
        failed = failed or not r.ok
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
