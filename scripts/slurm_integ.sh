#!/bin/bash
# Slurm integration test: runs INSIDE a slurm cluster node (the CI workflow
# launches a dockerized slurmctld cluster and docker-execs this script in a
# compute node). Everything here exercises the REAL control plane — sbatch
# admits the generated script, srun runs the gang, sacct reports it — which
# catches drift that canned-fixture unit tests cannot (sbatch rejecting an
# option, het-group syntax changes, log files landing elsewhere).
#
# Usage: slurm_integ.sh <wheel-or-checkout-path> <venv-path>
set -eux -o pipefail

SRC="$(realpath "$1")"
VENV="$(realpath "$2")"
BASE_DIR="$(mktemp -d /data/tpx-integ-XXXXXX 2>/dev/null || mktemp -d)"
JOB_DIR="$BASE_DIR/job"
mkdir -p "$JOB_DIR"
cd "$BASE_DIR"

# slurm env (slurm-docker-cluster exposes binaries via /opt/slurm)
SLURM_SH=/opt/slurm/etc/slurm.sh
[ -e "$SLURM_SH" ] && source "$SLURM_SH"
sbatch --version

source "$VENV/bin/activate"
pip install "$SRC"
# the spmd bootstrap needs CPU jax on the compute nodes
pip install "jax[cpu]"

PARTITION="$(sinfo --format=%R --noheader | head -n 1)"
cat <<EOT > .tpxconfig
[slurm]
partition = $PARTITION
time = 10
job_dir = $JOB_DIR
EOT

# --- 1. single-replica echo through the full lifecycle ------------------
cat <<'EOT' > main.py
import jax

print(f"integ process={jax.process_index()}/{jax.process_count()}"
      f" devices={jax.device_count()}", flush=True)
EOT

APP_ID="$(tpx run --wait -s slurm utils.sh echo hello-from-slurm | head -n1)"
tpx status "$APP_ID"
tpx describe "$APP_ID"
tpx log "$APP_ID" | grep -q "hello-from-slurm"

# log WINDOWS against real slurm-written files: the wrapper stamps lines,
# a future --since must exclude them, a past --since must include them
FUTURE="$(( $(date +%s) + 3600 ))"
if tpx log --since "$FUTURE" "$APP_ID" | grep -q "hello-from-slurm"; then
  echo "FAIL: --since in the future returned stamped lines" >&2
  exit 1
fi
tpx log --since 7d "$APP_ID" | grep -q "hello-from-slurm"
if tpx log --until 2000-01-01T00:00:00 "$APP_ID" | grep -q "hello-from-slurm"; then
  echo "FAIL: --until in the distant past returned lines" >&2
  exit 1
fi

# --- 2. a 2-process jax gang as het groups ------------------------------
SPMD_ID="$(tpx run --wait -s slurm dist.spmd -j 2 --cpu 1 --script main.py | head -n1)"
tpx status "$SPMD_ID"
sacct -j "$(basename "$SPMD_ID")" --format=JobID,JobName,State
LINES="$(tpx log "$SPMD_ID" | grep -c 'integ process=')"
if [ "$LINES" -ne 2 ]; then
  echo "FAIL: expected 2 gang log lines, got $LINES" >&2
  tpx log "$SPMD_ID" >&2
  exit 1
fi

# --- 3. listing ---------------------------------------------------------
tpx list -s slurm | grep -q "$(basename "$SPMD_ID")"

echo "slurm integration: OK"
