#!/usr/bin/env python
"""Docs build check: the CI gate for docs/.

1. scheduler pages are in sync with the live runopts schemas
   (scripts/gen_scheduler_docs.py --check);
2. every relative markdown link in docs/ resolves to a real file;
3. every page renders with python-markdown (catches broken fences/tables).

Exit 0 = docs are buildable and current.
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs"

LINK_RE = re.compile(r"\[[^\]]*\]\(([^)#\s]+)(#[^)]*)?\)")


def check_generated() -> list[str]:
    errors = []
    for script in ("gen_scheduler_docs.py", "gen_api_docs.py"):
        proc = subprocess.run(
            [sys.executable, str(REPO / "scripts" / script), "--check"],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            errors.append(
                f"{script} --check failed (stale pages or public symbols"
                f" missing docstrings):\n{proc.stderr.strip()}"
            )
    return errors


def check_links() -> list[str]:
    errors = []
    pages = sorted(DOCS.rglob("*.md")) + [REPO / "README.md"]
    for page in pages:
        for m in LINK_RE.finditer(page.read_text()):
            target = m.group(1)
            if "://" in target or target.startswith("mailto:"):
                continue
            resolved = (page.parent / target).resolve()
            if not resolved.exists():
                errors.append(
                    f"{page.relative_to(REPO)}: broken link -> {target}"
                )
    return errors


def check_render() -> list[str]:
    try:
        import markdown
    except ImportError:
        return []  # renderer not available in this env; links+drift still gate
    errors = []
    for page in sorted(DOCS.rglob("*.md")):
        try:
            markdown.markdown(
                page.read_text(), extensions=["tables", "fenced_code"]
            )
        except Exception as e:  # noqa: BLE001 - any render error fails CI
            errors.append(f"{page.relative_to(REPO)}: render error: {e}")
    return errors


def main() -> int:
    errors = check_generated() + check_links() + check_render()
    for e in errors:
        print(f"DOCS ERROR: {e}", file=sys.stderr)
    pages = len(list(DOCS.rglob("*.md")))
    if not errors:
        print(f"docs ok: {pages} pages, links resolve, runopts tables current")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
