#!/usr/bin/env python
"""Sweep flash-attention tile sizes on real hardware.

Profiling (docs/performance.md) showed the pallas flash kernels consume
~57% of llama3_1b step time at head_dim 64 with the default 128-blocks.
This sweeps (attn_block_q, attn_block_kv) candidates through the full
trainer and prints a ranked table — run on a healthy TPU (the pallas
kernels this tunes do not lower on CPU):

    python scripts/tune_attention_blocks.py --config llama3_1b --batch 2

The winner feeds LlamaConfig.attn_block_q/attn_block_kv (and the bench
candidate list in bench.py).
"""

from __future__ import annotations

import argparse
import itertools


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default="llama3_1b")
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--seq", type=int, default=2048)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument(
        "--blocks",
        default="0,128,256,512",
        help="comma list of candidate block sizes (0 = kernel default)",
    )
    parser.add_argument("--remat-policy", default="dots")
    parser.add_argument(
        "--impl",
        default="splash",
        choices=["pallas", "splash"],
        help="attention kernel to sweep (splash won the v5e sweep)",
    )
    args = parser.parse_args()

    from torchx_tpu.examples.train_llama import all_configs, train
    from torchx_tpu.parallel.mesh import MeshConfig

    candidates = [int(b) for b in args.blocks.split(",")]
    mesh = MeshConfig(dp=1, fsdp=-1, tp=1, sp=1)
    results = []
    for bq, bkv in itertools.product(candidates, candidates):
        cfg = all_configs()[args.config](
            remat_policy=args.remat_policy,
            attn_impl=args.impl,
            attn_block_q=bq,
            attn_block_kv=bkv,
        )
        try:
            m = train(
                cfg,
                mesh,
                batch=args.batch,
                seq=args.seq,
                steps=args.steps,
                log_every=args.steps,
            )
            results.append((m["mfu"], bq, bkv, m["tokens_per_sec_per_chip"]))
            print(
                f"block_q={bq or 'def'} block_kv={bkv or 'def'}:"
                f" MFU={m['mfu']:.1%} tps/chip={m['tokens_per_sec_per_chip']:,.0f}"
            )
        except Exception as e:  # noqa: BLE001 - a bad tiling must not end the sweep
            print(f"block_q={bq} block_kv={bkv}: FAILED {str(e)[:90]}")

    if results:
        results.sort(reverse=True)
        print("\nbest configurations:")
        for mfu, bq, bkv, tps in results[:5]:
            print(
                f"  attn_block_q={bq} attn_block_kv={bkv}:"
                f" MFU={mfu:.1%} tokens/sec/chip={tps:,.0f}"
            )


if __name__ == "__main__":
    main()
