#!/usr/bin/env python
"""Measure the int8-training crossover on the batch (row) axis.

End-to-end int8 training at batch 2 is net-negative on v5e (the dynamic
quant/dequant elementwise passes outweigh the 1.94x int8 MXU speedup —
docs/performance.md), and this environment's tunnel cannot compile the
full model at batch >= 3. What CAN be measured as far as the tunnel
allows is the per-layer matmul itself across the row axis: this
slope-times the llama3_1b FFN dot ([M, 2048] x [2048, 8192]) as bf16 vs
the AQT int8 training dot (dynamic per-tensor scales, the exact
configuration ``LlamaConfig.int8_matmuls`` uses) for growing M = the
batch x seq rows a training step feeds it.

Timing protocol per the tunnel's measurement traps: chained data
dependence (each iteration consumes the previous output, so remote
transports cannot elide repeat dispatches) and slope timing (t(long) -
t(short) cancels the fixed dispatch/fetch overhead).

Prints one JSON line per M with the bf16/int8 ratio; ratio > 1 means
int8 wins at that shape.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp


def _chain(matmul, x0, w, n):  # noqa: ANN001
    """n dependent matmuls; EVERY output column feeds the carry (a slice
    would let XLA dead-code-eliminate the unused columns — observed as a
    7x-over-peak bf16 "measurement" with the naive y = out[:, :k] chain).
    """

    def body(_, y):  # noqa: ANN001
        out = matmul(y, w)
        m, k = y.shape
        folded = out.reshape(m, out.shape[1] // k, k).sum(axis=1)
        # renormalize to a data-dependent O(1) fixed point so the chain
        # neither underflows to zeros (which would hand AQT a degenerate
        # abs-max=0 scale and un-time the real quant cost) nor overflows;
        # the reduction's cost is identical for both candidates so the
        # slope difference still isolates the matmul
        norm = jnp.maximum(jnp.mean(jnp.abs(folded)), 1e-6)
        return (folded / norm).astype(y.dtype)

    return jax.lax.fori_loop(0, n, body, x0)


def time_chain(matmul, m: int, k: int, n: int, peak: float = 190e12) -> float:
    """-> seconds per matmul via slope timing; chain lengths scale with
    the shape so the slope dwarfs the tunnel's ~60 ms fetch RTT."""
    x = jnp.ones((m, k), jnp.bfloat16)
    w = jnp.ones((k, n), jnp.bfloat16) * 0.01
    t_est = 2 * m * k * n / peak
    short = 8
    long = short + min(400, max(40, int(0.2 / t_est)))
    fn = jax.jit(lambda x0, w, steps: _chain(matmul, x0, w, steps), static_argnums=2)
    jax.device_get(fn(x, w, short)[0, 0])  # compile + warm both lengths
    jax.device_get(fn(x, w, long)[0, 0])

    def run(steps: int) -> float:
        t0 = time.monotonic()
        jax.device_get(fn(x, w, steps)[0, 0])
        return time.monotonic() - t0

    best = min((run(long) - run(short)) for _ in range(2))
    return max(best, 1e-9) / (long - short)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--k", type=int, default=2048)
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--rows", default="2048,4096,8192,16384,32768")
    args = ap.parse_args()

    from torchx_tpu.ops.quant import aqt_dot_general

    dims = (((1,), (0,)), ((), ()))

    def bf16_mm(x, w):  # noqa: ANN001
        return jax.lax.dot_general(x, w, dims, preferred_element_type=jnp.float32)

    aqt = aqt_dot_general()

    def int8_mm(x, w):  # noqa: ANN001
        return aqt(x, w, dims)

    for m in [int(r) for r in args.rows.split(",")]:
        t_bf16 = time_chain(bf16_mm, m, args.k, args.n)
        t_int8 = time_chain(int8_mm, m, args.k, args.n)
        flops = 2 * m * args.k * args.n
        print(
            json.dumps(
                {
                    "rows": m,
                    "bf16_us": round(t_bf16 * 1e6, 1),
                    "int8_us": round(t_int8 * 1e6, 1),
                    "bf16_tflops": round(flops / t_bf16 / 1e12, 1),
                    "int8_tops": round(flops / t_int8 / 1e12, 1),
                    "int8_speedup": round(t_bf16 / t_int8, 3),
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
