#!/usr/bin/env python
"""Legacy self-lint entry point — now a thin shim over ``tpx selfcheck``.

The three original rules (module-level jax imports in jax-free layers,
raw subprocess in ``schedulers/``, raw wall-clock calls in sim-hosted
modules) live in the whole-program analyzer
(:mod:`torchx_tpu.analyze.selfcheck`) as the ``jax-free`` /
``subprocess`` / ``clock`` passes, upgraded with an import graph: the
jax-free proof is now *transitive* (a chain of module-level imports
reaching jax is flagged even when no single file imports it directly)
and the sim-hosted set is *derived* by reachability from
``sim/harness.py`` instead of hand-maintained here.

This script keeps the old contract for callers and tests:

* ``python scripts/lint_internal.py`` prints one line per violation,
  ``SELF_LINT: N violation(s)`` to stderr and exits 1 — or prints
  ``SELF_LINT: clean`` and exits 0;
* :func:`check_jax_free` / :func:`check_scheduler_subprocess` /
  :func:`check_wall_clock` stay importable single-file checkers (the
  unit tests drive them on synthetic files) with the old message
  formats, now backed by the selfcheck pass primitives.

Prefer ``tpx selfcheck`` directly: it runs all six passes, applies the
triaged baseline, and emits coded TPX9xx diagnostics with ``--json``.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from torchx_tpu.analyze.selfcheck import clock as _clock  # noqa: E402
from torchx_tpu.analyze.selfcheck import jaxfree as _jaxfree  # noqa: E402
from torchx_tpu.analyze.selfcheck import subproc as _subproc  # noqa: E402

#: kept for importers of the old module-level constants
SUBPROCESS_SEAM_FUNCS = ("_run_cmd", "_popen")
WALL_CLOCK_CALLS = _clock.WALL_CLOCK_CALLS


def _parse(path: str) -> ast.Module:
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def check_jax_free(path: str) -> list[str]:
    """Module-level jax import sites in one file, old message format."""
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: module-level jax import in a jax-free layer"
        f" ({stmt}); import inside the function that needs it"
        for line, stmt in _jaxfree.module_level_jax_imports(_parse(path))
    ]


def check_scheduler_subprocess(path: str) -> list[str]:
    """Raw subprocess sites outside the seam in one file, old format."""
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: raw {call} in schedulers/ outside the"
        f" {'/'.join(SUBPROCESS_SEAM_FUNCS)} seam; route it through the"
        " backend's resilient _run_cmd"
        for line, call in _subproc.raw_subprocess_sites(
            _parse(path), SUBPROCESS_SEAM_FUNCS
        )
    ]


def check_wall_clock(path: str) -> list[str]:
    """Raw wall-clock *call* sites in one file, old message format
    (``ast.Call`` only — ``clock=time.time`` default-arg references are
    the injection idiom and stay legal)."""
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: raw time.{attr}() in a sim-hosted module; go"
        " through the injected clock seam (sim/clock.py) so virtual time"
        " stays deterministic"
        for line, attr in _clock.wall_clock_sites(_parse(path))
    ]


def main() -> int:
    from torchx_tpu.analyze.selfcheck import (
        BASELINE_FILENAME,
        Baseline,
        LEGACY_PASSES,
        SelfCheckConfig,
        run_selfcheck,
    )

    config = SelfCheckConfig.for_repo(REPO)
    raw = run_selfcheck(config, passes=LEGACY_PASSES)
    baseline = Baseline.load(os.path.join(REPO, BASELINE_FILENAME))
    kept, _suppressed = baseline.apply(raw)
    violations = [
        f"{d.field}: [{d.code}] {d.message}" for d in kept.diagnostics
    ]
    for v in violations:
        print(v)
    if violations:
        print(f"SELF_LINT: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("SELF_LINT: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
