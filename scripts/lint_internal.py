#!/usr/bin/env python
"""AST-based self-lint enforcing the repo's own layering invariants.

Two rules, both load-bearing for the launcher's design:

1. **jax-free layers stay jax-free.** ``cli/``, ``supervisor/``,
   ``control/``, ``analyze/`` and ``parallel/mesh_config.py`` must never
   import ``jax`` (or ``jax.*``) at module level: the client-side
   supervisor, the preflight analyzer and ``tpx --help`` all run on
   machines without an accelerator runtime, and a single eager import
   regresses CLI latency by seconds. Function-local (lazy) imports are
   allowed — that is the sanctioned escape hatch (``tpx explain --aot``).

2. **scheduler subprocess calls go through the resilient seam.** Raw
   ``subprocess.run/Popen/check_*/call`` in ``schedulers/`` bypasses the
   retry/circuit-breaker wrapper; the only sanctioned call sites are the
   ``_run_cmd`` methods (the seam each backend funnels through) and the
   local scheduler's ``_popen`` (data-plane replica spawn, not a
   control-plane call).

3. **sim-hosted modules never read the wall clock directly.** Every
   module the virtual-time simulator hosts (``fleet/``, ``control/``,
   ``obs/``, ``pipelines/``, ``supervisor/``, the serve control plane,
   ``sim/`` itself) must call ``time.time``/``time.sleep``/
   ``time.monotonic`` only through its injected clock seam — one raw
   call site breaks virtual-time determinism silently (the sim keeps
   running, the journal stops being a pure function of the seed).
   ``sim/clock.py`` is the seam and is exempt; ``time.perf_counter`` is
   allowed everywhere (wall-cost measurement, never scheduling).

Run directly (``python scripts/lint_internal.py``) or via the tier1.sh
SELF_LINT step. Exit 0 clean, 1 violations (one line each).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "torchx_tpu")

#: packages/modules (relative to torchx_tpu/) that must not import jax at
#: module level
JAX_FREE = (
    "cli",
    "supervisor",
    "control",
    "analyze",
    "fleet",
    "tune",
    "pipelines",
    os.path.join("parallel", "mesh_config.py"),
    # the telemetry plane runs inside the daemon and `tpx top`
    os.path.join("obs", "telemetry.py"),
    os.path.join("obs", "slo.py"),
    os.path.join("obs", "stitch.py"),
    # the step profiler backs `tpx profile` and the analyzers' attribution
    os.path.join("obs", "profile.py"),
    "sim",
)

#: functions inside schedulers/ allowed to call subprocess directly
SUBPROCESS_SEAM_FUNCS = ("_run_cmd", "_popen")

SUBPROCESS_CALLS = ("run", "Popen", "check_call", "check_output", "call")

#: packages/modules (relative to torchx_tpu/) the virtual-time simulator
#: hosts: raw wall-clock calls here break sim determinism
SIM_HOSTED = (
    "fleet",
    "control",
    "obs",
    "pipelines",
    "supervisor",
    "sim",
    os.path.join("serve", "pool.py"),
    os.path.join("serve", "engine.py"),
    os.path.join("serve", "kv_transfer.py"),
)

#: the clock seam itself — the one sanctioned home of raw time calls
SIM_CLOCK_EXEMPT = os.path.join("sim", "clock.py")

#: time attributes that schedule or stamp (perf_counter measures wall
#: cost and is deliberately NOT listed)
WALL_CLOCK_CALLS = ("time", "sleep", "monotonic")


def _py_files(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    for root, _dirs, files in os.walk(path):
        out.extend(
            os.path.join(root, f) for f in files if f.endswith(".py")
        )
    return sorted(out)


def _is_jax(name: str) -> bool:
    return name == "jax" or name.startswith("jax.")


def check_jax_free(path: str) -> list[str]:
    """Module-level ``import jax`` / ``from jax ...`` statements in one
    file (imports nested in functions are lazy and fine; class bodies and
    ``if TYPE_CHECKING`` don't occur for jax here and stay flagged to keep
    the rule simple)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    bad = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.depth = 0

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Import(self, node: ast.Import) -> None:
            if self.depth == 0:
                for alias in node.names:
                    if _is_jax(alias.name):
                        bad.append((node.lineno, f"import {alias.name}"))

        def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
            if self.depth == 0 and node.module and _is_jax(node.module):
                bad.append((node.lineno, f"from {node.module} import ..."))

    V().visit(tree)
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: module-level jax import in a jax-free layer"
        f" ({stmt}); import inside the function that needs it"
        for line, stmt in bad
    ]


def check_scheduler_subprocess(path: str) -> list[str]:
    """Raw ``subprocess.<call>`` sites in one schedulers/ file outside the
    sanctioned seam functions."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    bad = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.func_stack: list[str] = []

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            self.func_stack.append(node.name)
            self.generic_visit(node)
            self.func_stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Call(self, node: ast.Call) -> None:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "subprocess"
                and fn.attr in SUBPROCESS_CALLS
                and not any(
                    f in SUBPROCESS_SEAM_FUNCS for f in self.func_stack
                )
            ):
                bad.append((node.lineno, f"subprocess.{fn.attr}"))
            self.generic_visit(node)

    V().visit(tree)
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: raw {call} in schedulers/ outside the"
        f" {'/'.join(SUBPROCESS_SEAM_FUNCS)} seam; route it through the"
        " backend's resilient _run_cmd"
        for line, call in bad
    ]


def check_wall_clock(path: str) -> list[str]:
    """Raw ``time.time()``/``time.sleep()``/``time.monotonic()`` *call*
    sites in one sim-hosted file. Only ``ast.Call`` nodes are flagged:
    ``clock: Callable[[], float] = time.time`` default-arg references are
    the injection idiom itself and must stay legal."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    bad = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
                and fn.attr in WALL_CLOCK_CALLS
            ):
                bad.append((node.lineno, f"time.{fn.attr}()"))
            self.generic_visit(node)

    V().visit(tree)
    rel = os.path.relpath(path, REPO)
    return [
        f"{rel}:{line}: raw {call} in a sim-hosted module; go through"
        " the injected clock seam (sim/clock.py) so virtual time stays"
        " deterministic"
        for line, call in bad
    ]


def main() -> int:
    violations: list[str] = []
    for target in JAX_FREE:
        for path in _py_files(os.path.join(PKG, target)):
            violations.extend(check_jax_free(path))
    for path in _py_files(os.path.join(PKG, "schedulers")):
        violations.extend(check_scheduler_subprocess(path))
    exempt = os.path.join(PKG, SIM_CLOCK_EXEMPT)
    for target in SIM_HOSTED:
        for path in _py_files(os.path.join(PKG, target)):
            if path == exempt:
                continue
            violations.extend(check_wall_clock(path))
    for v in violations:
        print(v)
    if violations:
        print(f"SELF_LINT: {len(violations)} violation(s)", file=sys.stderr)
        return 1
    print("SELF_LINT: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
