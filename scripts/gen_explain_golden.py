#!/usr/bin/env python
"""Regenerate the ``tpx explain --json`` golden file.

``tests/test_explain.py::test_explain_report_schema_golden`` pins the
schema (version 1) and every byte of the deterministic cost-model output
for one fixed plan. When the schema or the cost model changes *on
purpose*, rerun this and commit the diff — the test failing otherwise is
the point.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
GOLDEN = os.path.join(REPO, "tests", "fixtures", "explain_golden.json")


def main() -> int:
    os.environ.setdefault("TPX_EVENT_DESTINATION", "null")
    from torchx_tpu.analyze.explain import explain
    from torchx_tpu.components import dist

    app = dist.spmd(
        "--config", "moe_tiny", "--mesh", "ep=2,fsdp=-1",
        "--batch", "8", "--seq", "128",
        m="my.custom_trainer", j="1x8",
    )
    report = explain(app, gate="test")
    with open(GOLDEN, "w") as f:
        json.dump(report.to_dict(), f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {GOLDEN}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
