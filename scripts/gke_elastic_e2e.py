#!/usr/bin/env python
"""Elastic shrink against a REAL control plane (gke_integ.sh §3).

Submits an elastic 2-"slice" app with ``elastic_controller=true`` to the
kind cluster, lets slice 1 fail for real, and asserts that the
IN-CLUSTER controller Job — not this harness, which never calls
watch/resize — shrinks the JobSet to 1 replica and the app then runs to
completion. This is the end-to-end proof for the round-3/4 requirement
that elasticity survives operator disconnect: the only actor after
submission is the controller pod.

The role carries a TPU slice resource so it materializes as one child
Job per slice (the granularity ``plan_elastic_shrink`` operates on); a
role overlay strips the TPU node selectors/tolerations/limits so the
pods schedule on kind's CPU nodes — exactly what overlays exist for.

Usage: gke_elastic_e2e.py <image> [namespace]
"""

from __future__ import annotations

import subprocess
import sys
import time

from torchx_tpu.runner import get_runner
from torchx_tpu.specs import overlays
from torchx_tpu.specs.api import AppDef, Resource, Role, TpuSlice

# slice 1 fails once (after the gang is visibly running); slice 0 would
# finish in 40s — after the shrink, the recreated 1-slice gang re-runs
# slice 0 only, which completes and takes the app to SUCCEEDED
APP_SCRIPT = (
    'if [ "$TPX_SLICE_ID" = "1" ]; then'
    '  echo "slice 1 failing deliberately"; sleep 5; exit 1; '
    "fi; "
    'echo "slice $TPX_SLICE_ID running"; sleep 40; '
    'echo "slice $TPX_SLICE_ID done"'
)

STRIP_TPU_SCHEDULING = {
    "spec": {
        overlays.JOIN("replicatedJobs"): [
            {
                "name": "trainer",
                "template": {
                    "spec": {
                        "template": {
                            "spec": {
                                overlays.DEL("nodeSelector"): None,
                                overlays.DEL("tolerations"): None,
                                overlays.JOIN("containers"): [
                                    {
                                        "name": "trainer",
                                        overlays.PUT("resources"): {},
                                    }
                                ],
                            }
                        }
                    }
                },
            }
        ],
    },
}


def kubectl(*args: str) -> str:
    return subprocess.run(
        ["kubectl", *args], check=True, capture_output=True, text=True
    ).stdout


def main() -> int:
    image = sys.argv[1]
    namespace = sys.argv[2] if len(sys.argv) > 2 else "default"

    role = Role(
        name="trainer",
        image=image,
        entrypoint="sh",
        args=["-c", APP_SCRIPT],
        num_replicas=2,
        min_replicas=1,
        max_retries=0,  # a failed slice stays failed -> shrink, not retry
        resource=Resource(cpu=1, memMB=256, tpu=TpuSlice("v5e", 4)),
    )
    overlays.set_overlay(role, "gke", STRIP_TPU_SCHEDULING)
    app = AppDef(name="elastic-shrink-e2e", roles=[role])

    runner = get_runner()
    handle = runner.run(
        app,
        "gke",
        cfg={
            "namespace": namespace,
            "elastic_controller": True,
            "service_account": "tpx-controller",
        },
        workspace=None,
    )
    print("submitted:", handle, flush=True)
    name = handle.rsplit("/", 1)[-1].split(":", 1)[1]

    # From here on the ONLY actor is the in-cluster controller Job.
    # The shrink under test DELETES the JobSet (foreground, waiting for
    # pod GC) before recreating it at the smaller size, so transient
    # not-found states are expected mid-test — only a PERSISTENTLY gone
    # JobSet is a failure.
    deadline = time.monotonic() + 360
    final = None
    gone_since = None
    while time.monotonic() < deadline:
        status = runner.status(handle)
        state = status.state.name if status else "GONE"
        try:
            replicas = kubectl(
                "get",
                "jobset",
                name,
                "-n",
                namespace,
                "-o",
                "jsonpath={.spec.replicatedJobs[0].replicas}",
            )
        except subprocess.CalledProcessError:
            replicas = "<resizing>"
        print(f"state={state} replicas={replicas}", flush=True)
        if state == "SUCCEEDED":
            final = replicas
            break
        if state == "CANCELLED":
            print("FAIL: app was cancelled", file=sys.stderr)
            return 1
        if state == "GONE":
            gone_since = gone_since or time.monotonic()
            if time.monotonic() - gone_since > 90:
                print(
                    "FAIL: JobSet gone for >90s (a resize delete+recreate"
                    " takes seconds)",
                    file=sys.stderr,
                )
                return 1
        else:
            gone_since = None
        time.sleep(5)
    else:
        print("FAIL: app did not finish in time", file=sys.stderr)
        print(kubectl("get", "jobsets", "-A", "-o", "yaml"), file=sys.stderr)
        return 1

    if final != "1":
        print(
            f"FAIL: expected the controller to shrink to 1 replica,"
            f" jobset has {final!r}",
            file=sys.stderr,
        )
        return 1

    # the shrink must have been performed by the controller POD
    controller_logs = kubectl(
        "logs",
        "-n",
        namespace,
        "-l",
        f"tpx.sh/controller-for={name}",
        "--tail=200",
    )
    if "shrinking to 1" not in controller_logs:
        print(
            "FAIL: controller logs do not show the shrink:\n"
            + controller_logs,
            file=sys.stderr,
        )
        return 1
    print("controller-performed shrink verified; app SUCCEEDED")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
