#!/bin/bash
# GKE/JobSet integration test against a REAL kubernetes control plane (kind
# + the JobSet controller, stood up by the CI workflow). Two layers:
#
#  1. schema admission — server-side dry-run of the TPU JobSet the gke
#     scheduler materializes (node selectors, completions, tpu resources):
#     the apiserver validates it against the installed JobSet CRD, catching
#     field drift that fixture-based unit tests cannot;
#  2. CPU end-to-end — a real utils.echo app scheduled as a JobSet, admitted
#     by the controller, run to completion on kind nodes, observed through
#     `tpx status/log`.
#
# Requires: kubectl context pointing at a cluster with the JobSet CRD,
# `pip install -e .[kubernetes]` done by the workflow.
set -eux -o pipefail

command -v kubectl
kubectl get crd jobsets.jobset.x-k8s.io

WORK="$(mktemp -d)"
trap 'rm -rf "$WORK"' EXIT

# --- 1. TPU JobSet schema admission (server-side dry-run) ----------------
tpx run -s gke --dryrun dist.spmd --tpu v5litepod-16 -m mypkg.train \
  | python -c '
import json, re, sys

text = sys.stdin.read()
start = text.index("{", text.index("=== SCHEDULER REQUEST ==="))
body = json.loads(text[start:])
jobset = body.get("jobset", body)  # elastic apps wrap {jobset, controller}
json.dump(jobset, sys.stdout)
' > "$WORK/tpu-jobset.json"
kubectl apply --dry-run=server -f "$WORK/tpu-jobset.json"
echo "TPU JobSet admitted by the apiserver schema"

# elastic variant (min floor annotations + in-cluster controller Job)
tpx run -s gke -cfg elastic_controller=true --dryrun \
    dist.spmd -j 1:2 --tpu v5litepod-16 -m mypkg.train \
  | python -c '
import json, sys

text = sys.stdin.read()
start = text.index("{", text.index("=== SCHEDULER REQUEST ==="))
body = json.loads(text[start:])
json.dump(body["jobset"], open(sys.argv[1] + "/elastic-jobset.json", "w"))
json.dump(body["controller"], open(sys.argv[1] + "/controller-job.json", "w"))
' "$WORK"
kubectl apply --dry-run=server -f "$WORK/elastic-jobset.json"
kubectl apply --dry-run=server -f "$WORK/controller-job.json"
echo "elastic JobSet + controller Job admitted"

# --- 2. CPU end-to-end through the real controller -----------------------
# busybox has a real `echo`; no workspace (nothing to patch in CI)
APP_ID="$(tpx run -s gke --workspace "" utils.echo --msg hello-from-kind --image busybox:stable | head -n1)"

for _ in $(seq 1 60); do
  STATE="$(tpx status "$APP_ID" | head -n1 || true)"
  case "$STATE" in
    *SUCCEEDED*) break ;;
    *FAILED*|*CANCELLED*)
      echo "FAIL: $STATE" >&2
      kubectl get jobsets -A -o yaml >&2
      kubectl get pods -A >&2
      exit 1 ;;
  esac
  sleep 5
done
tpx status "$APP_ID" | grep -q SUCCEEDED

tpx describe "$APP_ID"
tpx log "$APP_ID" | grep -q "hello-from-kind"
tpx list -s gke | grep -q "$(basename "$APP_ID" | cut -d: -f2)"
tpx delete "$APP_ID"

# --- 3. elastic shrink performed by the IN-CLUSTER controller ------------
# Requires an image with torchx_tpu installed loaded into the cluster
# (the workflow builds docker/e2e/Dockerfile and `kind load`s it); skipped
# when TPX_E2E_IMAGE is unset so the first two sections stay runnable
# against any JobSet cluster.
if [ -n "${TPX_E2E_IMAGE:-}" ]; then
  # RBAC for the controller pod: watch reads the jobset + controller
  # cleanup, resize deletes + recreates it
  kubectl apply -f - <<'EOT'
apiVersion: v1
kind: ServiceAccount
metadata:
  name: tpx-controller
  namespace: default
---
apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: tpx-controller
  namespace: default
rules:
  - apiGroups: ["jobset.x-k8s.io"]
    resources: ["jobsets"]
    verbs: ["get", "list", "create", "delete", "patch"]
  - apiGroups: ["batch"]
    resources: ["jobs"]
    verbs: ["get", "list", "delete"]
  - apiGroups: [""]
    resources: ["pods", "pods/log"]
    verbs: ["get", "list"]
---
apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: tpx-controller
  namespace: default
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: tpx-controller
subjects:
  - kind: ServiceAccount
    name: tpx-controller
    namespace: default
EOT
  python scripts/gke_elastic_e2e.py "$TPX_E2E_IMAGE" default
else
  echo "TPX_E2E_IMAGE unset; skipping the elastic-shrink e2e section"
fi

echo "gke integration: OK"
