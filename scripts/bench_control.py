#!/usr/bin/env python
"""Control-plane benchmark: daemon+watch vs per-caller direct polling.

The fleet-scale question: with K concurrent jobs and K waiters, how many
backend control-plane calls does "everyone polls for themselves" cost
versus "everyone asks the ``tpx control`` daemon, which owns ONE watch
stream per backend"?

Two phases over the same workload (K local ``sleep`` jobs, one poller
per job at a fixed interval):

* **direct** — the pre-daemon world: each waiter drives its own
  ``Runner.status(fresh=True)`` poll loop (what K independent CLIs do),
  so every poll is a real backend describe.
* **daemon** — the same client behavior pointed at a ControlDaemon:
  every poll is an HTTP ``/v1/status``; the daemon's reconciler rides
  the local scheduler's sidecar watch stream and its shared describe
  cache answers the polls, so backend describes collapse to roughly one
  confirm per state transition (plus TTL refreshes of live entries).

Reported per phase: control-plane ops/sec (client-visible status calls),
status-latency p50/p99, the backend describe-call count over the phase
(``tpx_control_plane_calls_total{backend=local,op=describe}`` delta),
and describes-per-job — the *scheduler-call amplification*. The headline
number is ``amplification_reduction`` = direct describes / daemon
describes, which must be > 1 at fleet width.

Usage:
    python scripts/bench_control.py [--jobs 32] [--job-seconds 3]
        [--poll-interval 0.25] [--out BENCH_CONTROL_r01.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import tempfile
import threading
import time


def _quantiles(samples: list[float]) -> dict:
    if not samples:
        return {"p50_ms": None, "p99_ms": None}
    qs = statistics.quantiles(samples, n=100, method="inclusive")
    return {
        "p50_ms": round(qs[49] * 1000, 3),
        "p99_ms": round(qs[98] * 1000, 3),
    }


def _describe_calls() -> float:
    """Backend describes issued so far (all outcome labels)."""
    from torchx_tpu.obs import metrics as obs_metrics

    return sum(
        obs_metrics.CONTROL_PLANE_CALLS.value(
            backend="local", op="describe", status=status
        )
        for status in ("ok", "error", "rejected")
    )


def _watch_events() -> float:
    from torchx_tpu.obs import metrics as obs_metrics

    return sum(
        obs_metrics.WATCH_EVENTS.value(scheduler="local", source=source)
        for source in ("sidecar", "poll", "kubectl", "daemon")
    )


def _submit_jobs(submit, jobs: int, job_seconds: float, root: str) -> list[str]:
    handles = []
    for i in range(jobs):
        handles.append(submit(i, os.path.join(root, f"job{i:03d}")))
    return handles


def _poll_until_terminal(
    poll, handles: list[str], interval: float
) -> tuple[list[float], int]:
    """K waiter threads, each polling its job to terminal. Returns
    (per-call latencies, total status ops)."""
    latencies: list[float] = []
    ops = [0]
    lock = threading.Lock()

    def wait_one(handle: str) -> None:
        local: list[float] = []
        n = 0
        while True:
            t0 = time.perf_counter()
            terminal = poll(handle)
            local.append(time.perf_counter() - t0)
            n += 1
            if terminal:
                break
            time.sleep(interval)
        with lock:
            latencies.extend(local)
            ops[0] += n

    threads = [
        threading.Thread(target=wait_one, args=(h,), daemon=True)
        for h in handles
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    return latencies, ops[0]


def bench_direct(jobs: int, job_seconds: float, interval: float, root: str) -> dict:
    """Phase A: every waiter runs its own fresh-describe poll loop."""
    from torchx_tpu.runner.api import get_runner

    with get_runner("bench-direct") as runner:
        def submit(i: int, log_dir: str) -> str:
            return runner.run_component(
                "utils.sh",
                ["sleep", str(job_seconds)],
                "local",
                {"log_dir": log_dir},
            )

        def poll(handle: str) -> bool:
            status = runner.status(handle, fresh=True)
            return status is None or status.is_terminal()

        calls0 = _describe_calls()
        t0 = time.perf_counter()
        handles = _submit_jobs(submit, jobs, job_seconds, root)
        latencies, ops = _poll_until_terminal(poll, handles, interval)
        wall = time.perf_counter() - t0
        describes = _describe_calls() - calls0
    return {
        "mode": "direct",
        "wall_s": round(wall, 3),
        "status_ops": ops,
        "ops_per_sec": round(ops / wall, 2),
        "status_latency": _quantiles(latencies),
        "scheduler_describe_calls": int(describes),
        "describes_per_job": round(describes / jobs, 2),
    }


def bench_daemon(jobs: int, job_seconds: float, interval: float, root: str) -> dict:
    """Phase B: the same pollers, through the control daemon."""
    from torchx_tpu.control.client import ControlClient
    from torchx_tpu.control.daemon import ControlDaemon
    from torchx_tpu.runner.api import get_runner

    runner = get_runner("bench-daemon")
    daemon = ControlDaemon(
        runner=runner, state_dir=os.path.join(root, "control")
    ).start()
    try:
        client = ControlClient(daemon.addr, daemon.root_token)

        def submit(i: int, log_dir: str) -> str:
            return client.submit(
                "utils.sh",
                ["sleep", str(job_seconds)],
                "local",
                cfg={"log_dir": log_dir},
            )

        def poll(handle: str) -> bool:
            return bool(client.status(handle)["terminal"])

        calls0 = _describe_calls()
        events0 = _watch_events()
        t0 = time.perf_counter()
        handles = _submit_jobs(submit, jobs, job_seconds, root)
        latencies, ops = _poll_until_terminal(poll, handles, interval)
        wall = time.perf_counter() - t0
        describes = _describe_calls() - calls0
        events = _watch_events() - events0
    finally:
        daemon.close()
        runner.close()
    return {
        "mode": "daemon",
        "wall_s": round(wall, 3),
        "status_ops": ops,
        "ops_per_sec": round(ops / wall, 2),
        "status_latency": _quantiles(latencies),
        "scheduler_describe_calls": int(describes),
        "describes_per_job": round(describes / jobs, 2),
        "watch_events": int(events),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--jobs", type=int, default=32)
    parser.add_argument("--job-seconds", type=float, default=3.0)
    parser.add_argument("--poll-interval", type=float, default=0.25)
    parser.add_argument("--out", default=None, help="write results JSON here")
    args = parser.parse_args()

    root = tempfile.mkdtemp(prefix="tpx-bench-control-")
    os.environ.setdefault("TPX_OBS_DIR", os.path.join(root, "obs"))
    os.environ.setdefault("TPX_EVENT_DESTINATION", "null")
    os.environ.setdefault("TPX_WATCH_INTERVAL", str(args.poll_interval))

    print(
        f"bench_control: {args.jobs} jobs x {args.job_seconds}s,"
        f" poll every {args.poll_interval}s"
    )
    direct = bench_direct(args.jobs, args.job_seconds, args.poll_interval, root)
    print(
        f"  direct: {direct['scheduler_describe_calls']} backend describes"
        f" ({direct['describes_per_job']}/job),"
        f" {direct['ops_per_sec']} status ops/s,"
        f" p99 {direct['status_latency']['p99_ms']}ms"
    )
    daemon = bench_daemon(args.jobs, args.job_seconds, args.poll_interval, root)
    print(
        f"  daemon: {daemon['scheduler_describe_calls']} backend describes"
        f" ({daemon['describes_per_job']}/job),"
        f" {daemon['ops_per_sec']} status ops/s,"
        f" p99 {daemon['status_latency']['p99_ms']}ms,"
        f" {daemon['watch_events']} watch events"
    )
    reduction = (
        direct["scheduler_describe_calls"]
        / max(1, daemon["scheduler_describe_calls"])
    )
    print(f"  scheduler-call amplification reduction: {reduction:.1f}x")
    result = {
        "bench": "control_plane",
        "jobs": args.jobs,
        "job_seconds": args.job_seconds,
        "poll_interval_s": args.poll_interval,
        "direct": direct,
        "daemon": daemon,
        "amplification_reduction": round(reduction, 2),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"  wrote {args.out}")


if __name__ == "__main__":
    main()
