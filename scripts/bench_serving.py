#!/usr/bin/env python
"""Serving benchmark: KV-cache decode throughput for the generation stack.

Two halves:

* raw decode (``bench_decode``): steady-state decode tokens/sec for
  llama3_1b, bf16 vs int8 weight-only, across batch sizes — decode at
  batch b is HBM-bandwidth-bound, so the ceiling is roughly
  ``b * HBM_BW / (param_bytes + kv_bytes_per_row * b)``.

* serving under load (``bench_poisson``, the ``--poisson`` mode): an
  OPEN-LOOP Poisson load generator drives the real serving stack —
  arrivals follow seeded exponential gaps and are submitted on schedule
  regardless of completions, so queueing delay is measured instead of
  hidden (a closed loop self-throttles when the server falls behind).
  The same deterministic workload trace (same seed → identical prompts,
  arrival times and sampling seeds) is replayed against both engines at
  equal ``--max-batch``:

    - ``continuous``: the :mod:`torchx_tpu.serve.engine` slot-array
      engine (admit-on-free-slot, paged KV, per-step batching)
    - ``coalesce``: the legacy batch-to-completion coalescing batcher

  reporting decode tokens/sec, TTFT/TPOT p50/p99, and goodput (the
  fraction of requests whose TTFT meets ``--slo-ttft-ms``). For the
  coalescing baseline all tokens arrive when the batch completes, so its
  TTFT *is* its total latency — that asymmetry is the point of the
  comparison. ``--out`` writes the paired result (plus the paged-KV
  :meth:`~torchx_tpu.serve.kv_pool.PoolPlan.occupancy_report`) as one
  JSON document (see BENCH_SERVE_r01.json).

* shared-prefix serving (``--shared-prefix``): every prompt opens with
  the same system prompt (+ an exponential long-prompt tail) under the
  same open-loop Poisson arrivals, replayed against two topologies at
  equal per-engine HBM: TWO unified continuous engines with the prefix
  cache off (round-robin) vs ONE prefill engine (radix prefix cache on)
  streaming KV to ONE decode engine. Reports prefix-hit rate, TTFT
  p50/p99, decode tokens/sec, and cached-block occupancy (see
  BENCH_SERVE_r02.json).

Usage:
    python scripts/bench_serving.py [--steps 128] [--batches 1,4,8]
    python scripts/bench_serving.py --poisson [--rate 8] [--requests 48] \
        [--max-batch 4] [--out BENCH_SERVE_r01.json]
    python scripts/bench_serving.py --shared-prefix [--shared-len 48] \
        [--out BENCH_SERVE_r02.json]
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time

import jax
import jax.numpy as jnp


def bench_decode(params, cfg, batch: int, steps: int, prompt_len: int = 32):
    """-> steady-state decode tokens/sec for one (params, batch)."""
    from torchx_tpu.models import generate as gen

    total = prompt_len + steps
    prompt = jnp.ones((batch, prompt_len), jnp.int32)
    rng = jax.random.PRNGKey(0)

    # reuse the server's own cached jitted fns (prefill + chunked decode)
    prefill, decode_chunk = gen._stream_fns(cfg, total, 0.0, chunk=steps)
    cache, tok, rng2 = prefill(params, prompt, rng)
    # warm decode compile
    cache, tok, rng2, toks = decode_chunk(params, cache, tok, rng2, prompt_len)
    jax.block_until_ready(toks)
    # time with the carry CHAINED through reps: feeding each rep's cache/
    # tok into the next forces real execution (repeat-identical dispatches
    # can be elided/cached by remote-device transports — measured 960k
    # "tokens/sec" without this, 5x over the HBM roofline)
    t0 = time.monotonic()
    reps = 3
    for _ in range(reps):
        cache, tok, rng2, toks = decode_chunk(
            params, cache, tok, rng2, prompt_len
        )
    # device_get, not block_until_ready: remote transports can treat the
    # latter as a metadata-ready check; fetching a VALUE from the end of
    # the chained carry forces the whole timed chain to have executed
    jax.device_get(toks[:, -1])
    dt = (time.monotonic() - t0) / reps
    return batch * steps / dt


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _p99_label(sorted_vals: list) -> str:
    """Honesty label for the nearest-rank p99: below ~100 samples the
    nearest-rank 99th percentile IS the sample maximum, so say so
    (``p99~max(n=40)``) instead of implying tail resolution the sample
    cannot provide. Emitted next to every p99 in the BENCH JSON."""
    n = len(sorted_vals)
    if n and round(0.99 * (n - 1)) >= n - 1:
        return f"p99~max(n={n})"
    return f"p99(n={n})"


def bench_server(
    cfg_name: str, int8: bool, steps: int, clients: int, rounds: int = 5
):
    """Aggregate tokens/sec + per-request latency percentiles through the
    REAL HTTP server under concurrent load: `clients` threads each POST
    one /v1/generate per round; the batcher coalesces them into shared
    device batches.

    Deterministic protocol (the round-4 bf16 row measured 280-490 tok/s
    run-to-run because arrival jitter split dispatch groups differently
    each time): a timed round only COUNTS when its `clients` requests
    coalesced into exactly one device batch — split rounds are discarded
    and retried (up to 5x per round), so every reported number measures
    the same work. `rounds` >= 3 timed rounds are aggregated with their
    relative spread; per-request `timing` fields from the server give
    p50/p99 end-to-end latency, queue wait, and per-token latency.
    """
    import threading
    import urllib.request

    from torchx_tpu.apps import generate_server

    # A huge coalescing window makes grouping deterministic BY
    # CONSTRUCTION at no timing cost: the batcher dispatches the moment
    # the max_batch-th (== clients-th) request arrives, so the window
    # only ever waits for stragglers — it never pads a full round.
    server = generate_server.serve(
        cfg_name, port=0, int8=int8, batch_window_ms=5000.0, max_batch=clients
    )
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        body = json.dumps(
            {"tokens": [[1] * 16], "max_new_tokens": steps}
        ).encode()

        def one(errors: list, timings: list) -> None:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/generate",
                    data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=600) as r:
                    payload = json.loads(r.read())
                if "tokens" not in payload:
                    raise RuntimeError(f"bad response: {payload}")
                timings.append(payload.get("timing") or {})
            except Exception as e:  # noqa: BLE001 - collected, fails the run
                errors.append(e)

        def round_trip() -> tuple[float, list]:
            errors: list = []
            timings: list = []
            t0 = time.monotonic()
            threads = [
                threading.Thread(target=one, args=(errors, timings))
                for _ in range(clients)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            if errors:
                # a failed round must not masquerade as a throughput number
                raise RuntimeError(
                    f"{len(errors)} request(s) failed: {errors[0]}"
                )
            return time.monotonic() - t0, timings

        round_trip()  # warm: compiles the coalesced batch-`clients` shape
        svc = server.service
        rates: list = []
        all_timings: list = []
        discarded = 0
        for _ in range(rounds):
            for _attempt in range(5):
                batches_before = svc.batches
                dt, timings = round_trip()
                if svc.batches - batches_before == 1:
                    rates.append(clients * steps / dt)
                    all_timings.extend(timings)
                    break
                discarded += 1  # split group: not the measured protocol
            else:
                raise RuntimeError(
                    "could not coalesce a clean single-batch round in 5"
                    " attempts; raise batch_window_ms"
                )
        totals = sorted(t["total_ms"] for t in all_timings if "total_ms" in t)
        queues = sorted(t["queue_ms"] for t in all_timings if "queue_ms" in t)
        mean_rate = sum(rates) / len(rates)
        spread = (max(rates) - min(rates)) / mean_rate if mean_rate else 0.0
        return {
            "metric": f"server aggregate decode tokens/sec ({cfg_name},"
            f" {'int8' if int8 else 'bf16'}, {clients} concurrent clients)",
            "value": round(mean_rate, 1),
            "unit": "tokens/sec",
            "rounds": len(rates),
            "spread_pct": round(spread * 100, 1),
            "discarded_split_rounds": discarded,
            "latency_ms": {
                "p50_total": round(_percentile(totals, 0.50), 1),
                "p99_total": round(_percentile(totals, 0.99), 1),
                "p50_queue": round(_percentile(queues, 0.50), 1),
                "p99_queue": round(_percentile(queues, 0.99), 1),
                "p99_label": _p99_label(totals),
                "p50_per_token": round(
                    _percentile(totals, 0.50) / steps, 2
                ),
            },
            "batched_sequences": svc.batched_sequences,
        }
    finally:
        server.shutdown()
        server.server_close()
        server.service.close()


def bench_stream_ttft(cfg_name: str, int8: bool, steps: int, samples: int = 8):
    """Real time-to-first-token via the streaming endpoint (batch 1; the
    non-streaming batched path delivers all tokens at once, so its
    'TTFT' IS the total latency — this measures the latency-optimized
    path the server trades coalescing away for)."""
    import threading
    import urllib.request

    from torchx_tpu.apps import generate_server

    server = generate_server.serve(cfg_name, port=0, int8=int8)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        body = json.dumps(
            {
                "tokens": [[1] * 16],
                "max_new_tokens": steps,
                "stream": True,
                "stream_chunk": 1,
            }
        ).encode()

        def one() -> tuple[float, float]:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=body,
                headers={"Content-Type": "application/json"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=600) as r:
                first = None
                for line in r:
                    if line.strip():
                        if first is None:
                            first = time.monotonic() - t0
                return first, time.monotonic() - t0

        one()  # warm compile
        ttfts, totals = [], []
        for _ in range(samples):
            first, total = one()
            ttfts.append(first * 1e3)
            totals.append(total * 1e3)
        ttfts.sort()
        totals.sort()
        return {
            "metric": f"stream TTFT ms ({cfg_name},"
            f" {'int8' if int8 else 'bf16'}, batch 1)",
            "p50_ttft_ms": round(_percentile(ttfts, 0.50), 1),
            "p99_ttft_ms": round(_percentile(ttfts, 0.99), 1),
            "p99_label": _p99_label(ttfts),
            "p50_per_token_ms": round(
                _percentile(totals, 0.50) / steps, 2
            ),
            "samples": samples,
        }
    finally:
        server.shutdown()
        server.server_close()
        server.service.close()


def make_workload(
    *,
    num_requests: int,
    rate_rps: float,
    max_new: int,
    prompt_lens: tuple[int, ...],
    seed: int,
    vocab: int,
) -> list[dict]:
    """Deterministic open-loop trace: one dict per request with its
    arrival offset (cumulative seeded exponential gaps — a Poisson
    process), prompt, and per-request sampling seed. Replaying the same
    seed against both engines makes the comparison apples-to-apples."""
    rng = random.Random(seed)
    trace = []
    t = 0.0
    for i in range(num_requests):
        t += rng.expovariate(rate_rps)
        plen = rng.choice(prompt_lens)
        trace.append(
            {
                "arrival_s": t,
                "prompt": [rng.randrange(1, vocab) for _ in range(plen)],
                "max_new": max_new,
                "seed": seed * 1000 + i,
            }
        )
    return trace


def make_shared_prefix_workload(
    *,
    num_requests: int,
    rate_rps: float,
    max_new: int,
    shared_len: int,
    mean_tail: int,
    max_tail: int,
    seed: int,
    vocab: int,
) -> list[dict]:
    """Deterministic shared-prefix trace: every prompt opens with the SAME
    ``shared_len``-token system prompt, followed by a per-request tail
    whose length is exponentially distributed (a long-prompt tail) —
    the workload shape that motivates prefix caching. Arrivals are the
    same seeded Poisson process :func:`make_workload` uses."""
    rng = random.Random(seed)
    shared = [rng.randrange(1, vocab) for _ in range(shared_len)]
    trace = []
    t = 0.0
    for i in range(num_requests):
        t += rng.expovariate(rate_rps)
        tail_len = min(max_tail, 1 + int(rng.expovariate(1.0 / mean_tail)))
        trace.append(
            {
                "arrival_s": t,
                "prompt": shared
                + [rng.randrange(1, vocab) for _ in range(tail_len)],
                "max_new": max_new,
                "seed": seed * 1000 + i,
            }
        )
    return trace


def bench_shared_prefix(
    cfg_name: str,
    mode: str,
    trace: list[dict],
    *,
    max_batch: int,
    slo_ttft_ms: float,
    block_size: int = 16,
    num_blocks: int | None = None,
    temperature: float = 0.7,
) -> dict:
    """Replay one shared-prefix trace against one serving topology at a
    fixed per-engine HBM budget (same ``max_batch`` / ``num_blocks``):

    * ``unified``: TWO unified continuous engines, prefix cache OFF,
      round-robin — the pre-disaggregation baseline at equal chip count;
    * ``disagg``: ONE prefill engine (radix prefix cache ON) streaming
      KV to ONE decode engine over an in-process transfer — same two
      chips, split by phase.

    -> scorecard: decode tokens/sec, TTFT p50/p99, prefix-hit rate, and
    cached-block occupancy."""
    from torchx_tpu.apps.generate_server import GenerateService
    from torchx_tpu.serve.kv_transfer import LocalTransfer

    services: list[GenerateService] = []
    try:
        if mode == "unified":
            services = [
                GenerateService(
                    cfg_name,
                    engine="continuous",
                    max_batch=max_batch,
                    block_size=block_size,
                    num_blocks=num_blocks,
                    enable_prefix_cache=False,
                )
                for _ in range(2)
            ]

            def submit(i: int, req: dict):
                return services[i % 2].generate_timed(
                    [req["prompt"]],
                    req["max_new"],
                    temperature=temperature,
                    seed=req["seed"],
                )

            cache_engine = None
        elif mode == "disagg":
            dec = GenerateService(
                cfg_name,
                engine="continuous",
                serve_role="decode",
                max_batch=max_batch,
                block_size=block_size,
                num_blocks=num_blocks,
            )
            pre = GenerateService(
                cfg_name,
                engine="continuous",
                serve_role="prefill",
                kv_transfer="local",
                max_batch=max_batch,
                block_size=block_size,
                num_blocks=num_blocks,
            )
            pre._transfer = LocalTransfer({"decode": dec.handle_kv_payload})
            services = [pre, dec]

            def submit(i: int, req: dict):
                return pre.generate_timed(
                    [req["prompt"]],
                    req["max_new"],
                    temperature=temperature,
                    seed=req["seed"],
                )

            cache_engine = pre._engine
        else:
            raise ValueError(f"unknown mode {mode!r}")

        # warm every observed prompt length twice outside the timed
        # window: the first pass compiles the cold bucket (and seeds the
        # shared prefix into the cache where enabled), the second
        # compiles the cached-suffix bucket the steady state runs in
        for plen in sorted({len(r["prompt"]) for r in trace}):
            warm = trace[0]["prompt"][:plen]
            for _ in range(2):
                for i in range(len(services) if mode == "unified" else 1):
                    submit(i, {
                        "prompt": warm,
                        "max_new": trace[0]["max_new"],
                        "seed": 0,
                    })
        hits0 = misses0 = 0
        if cache_engine is not None:
            st0 = cache_engine.stats()["prefix_cache"]
            hits0, misses0 = st0["hits"], st0["misses"]

        results: list[dict] = [None] * len(trace)  # type: ignore[list-item]

        def one(i: int, req: dict) -> None:
            try:
                seqs, timing = submit(i, req)
                results[i] = {
                    "ok": True,
                    "generated": len(seqs[0]) - len(req["prompt"]),
                    "done_at": time.monotonic(),
                    **timing,
                }
            except Exception as e:  # noqa: BLE001 - scored as a miss
                results[i] = {"ok": False, "error": str(e)[:200]}

        t0 = time.monotonic()
        workers = []
        for i, req in enumerate(trace):
            delay = t0 + req["arrival_s"] - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one, args=(i, req), daemon=True)
            th.start()
            workers.append(th)
        for th in workers:
            th.join(timeout=600)
        done = [r for r in results if r and r.get("ok")]
        failed = len(trace) - len(done)
        if not done:
            raise RuntimeError(f"all {len(trace)} requests failed")
        duration = max(r["done_at"] for r in done) - t0
        total_tokens = sum(r["generated"] for r in done)
        ttfts = sorted(r["ttft_ms"] for r in done)
        good = sum(1 for r in done if r["ttft_ms"] <= slo_ttft_ms)
        out = {
            "mode": mode,
            "requests": len(trace),
            "failed": failed,
            "duration_s": round(duration, 2),
            "decode_tokens_per_sec": round(total_tokens / duration, 1),
            "ttft_ms": {
                "p50": round(_percentile(ttfts, 0.50), 1),
                "p99": round(_percentile(ttfts, 0.99), 1),
                "p99_label": _p99_label(ttfts),
            },
            "goodput": round(good / len(trace), 3),
            "slo_ttft_ms": slo_ttft_ms,
        }
        if cache_engine is not None:
            st = cache_engine.stats()
            pc = st["prefix_cache"]
            hits, misses = pc["hits"] - hits0, pc["misses"] - misses0
            out["prefix_cache"] = {
                "hit_rate": round(hits / max(1, hits + misses), 3),
                "hits": hits,
                "misses": misses,
                "token_hit_rate": pc["token_hit_rate"],
                "cached_blocks": pc["cached_blocks"],
                "cached_block_occupancy": round(
                    pc["cached_blocks"]
                    / max(1, st["kv_blocks_used"] + st["kv_blocks_free"]),
                    3,
                ),
                "evictions": pc["evictions"],
            }
        return out
    finally:
        for s in services:
            s.close()


def run_shared_prefix_comparison(args) -> dict:
    """Unified (2 engines, no cache) vs disaggregated+cache (prefill +
    decode) on one shared-prefix trace at equal per-engine HBM — the
    --shared-prefix mode, one JSON document (BENCH_SERVE_r02.json)."""
    from torchx_tpu.models import llama
    from torchx_tpu.serve.kv_pool import plan_pool

    platform = jax.devices()[0].platform
    cfg_name = args.config if platform == "tpu" else "tiny"
    cfg = llama.CONFIGS[cfg_name]()
    max_new = min(args.steps, cfg.max_seq // 8)
    shared_len = min(args.shared_len, cfg.max_seq // 2)
    max_tail = max(4, cfg.max_seq - shared_len - max_new - 1)
    trace = make_shared_prefix_workload(
        num_requests=args.requests,
        rate_rps=args.rate,
        max_new=max_new,
        shared_len=shared_len,
        mean_tail=min(12, max_tail),
        max_tail=max_tail,
        seed=args.seed,
        vocab=cfg.vocab_size,
    )
    doc = {
        "bench": "shared-prefix serving: unified vs disaggregated+cache"
        " at equal per-engine HBM (2 engines each)",
        "config": cfg_name,
        "platform": platform,
        "workload": {
            "requests": args.requests,
            "rate_rps": args.rate,
            "max_new_tokens": max_new,
            "shared_prefix_len": shared_len,
            "prompt_lens": sorted({len(r["prompt"]) for r in trace}),
            "seed": args.seed,
            "max_batch": args.max_batch,
        },
        "modes": {},
    }
    for mode in ("unified", "disagg"):
        doc["modes"][mode] = bench_shared_prefix(
            cfg_name,
            mode,
            trace,
            max_batch=args.max_batch,
            slo_ttft_ms=args.slo_ttft_ms,
        )
        print(json.dumps(doc["modes"][mode]))
    uni, dis = doc["modes"]["unified"], doc["modes"]["disagg"]
    doc["comparison"] = {
        "p99_ttft_reduction": round(
            1 - dis["ttft_ms"]["p99"] / uni["ttft_ms"]["p99"], 3
        ),
        "decode_tokens_per_sec_ratio": round(
            dis["decode_tokens_per_sec"] / uni["decode_tokens_per_sec"], 2
        ),
        "prefix_hit_rate": dis["prefix_cache"]["hit_rate"],
        "goodput_delta": round(dis["goodput"] - uni["goodput"], 3),
    }
    # paged-vs-dense at the target config (the HBM half of the story),
    # same as the r01 report, plus what the cache held at steady state
    plan_cfg = llama.CONFIGS[args.config]()
    doc["kv_pool_occupancy"] = plan_pool(plan_cfg).occupancy_report()
    print(json.dumps(doc["comparison"]))
    return doc


def bench_poisson(
    cfg_name: str,
    engine: str,
    trace: list[dict],
    *,
    max_batch: int,
    slo_ttft_ms: float,
    block_size: int = 16,
    batch_window_ms: float = 25.0,
    temperature: float = 0.7,
) -> dict:
    """Replay one workload trace open-loop against one engine; -> the
    serving scorecard (tokens/sec, TTFT/TPOT p50/p99, goodput)."""
    from torchx_tpu.apps.generate_server import GenerateService

    svc = GenerateService(
        cfg_name,
        engine=engine,
        max_batch=max_batch,
        batch_window_ms=batch_window_ms,
        block_size=block_size,
    )
    try:
        # warm every (prompt_len, max_new) compile outside the timed window
        for plen in sorted({len(r["prompt"]) for r in trace}):
            svc.generate(
                [list(range(1, plen + 1))],
                trace[0]["max_new"],
                temperature=temperature,
            )

        results: list[dict] = [None] * len(trace)  # type: ignore[list-item]

        def one(i: int, req: dict) -> None:
            try:
                seqs, timing = svc.generate_timed(
                    [req["prompt"]],
                    req["max_new"],
                    temperature=temperature,
                    seed=req["seed"],
                )
                results[i] = {
                    "ok": True,
                    "generated": len(seqs[0]) - len(req["prompt"]),
                    "done_at": time.monotonic(),
                    **timing,
                }
            except Exception as e:  # noqa: BLE001 - scored as a miss
                results[i] = {"ok": False, "error": str(e)[:200]}

        # open loop: submit on the trace's schedule, never waiting for
        # completions — if the server falls behind, the backlog (and the
        # latency it causes) is part of the measurement
        t0 = time.monotonic()
        workers = []
        for i, req in enumerate(trace):
            delay = t0 + req["arrival_s"] - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=one, args=(i, req), daemon=True)
            th.start()
            workers.append(th)
        for th in workers:
            th.join(timeout=600)
        done = [r for r in results if r and r.get("ok")]
        failed = len(trace) - len(done)
        if not done:
            raise RuntimeError(f"all {len(trace)} requests failed")
        duration = max(r["done_at"] for r in done) - t0
        total_tokens = sum(r["generated"] for r in done)
        ttfts = sorted(r["ttft_ms"] for r in done)
        # per-output-token latency after the first token; the coalescing
        # baseline delivers everything at once, so its per-token cost is
        # total/steps — there is no cheaper number to give it
        tpots = sorted(
            (r["total_ms"] - r["ttft_ms"]) / max(1, r["generated"] - 1)
            if r["total_ms"] > r["ttft_ms"]
            else r["total_ms"] / max(1, r["generated"])
            for r in done
        )
        good = sum(1 for r in done if r["ttft_ms"] <= slo_ttft_ms)
        return {
            "engine": engine,
            "requests": len(trace),
            "failed": failed,
            "duration_s": round(duration, 2),
            "decode_tokens_per_sec": round(total_tokens / duration, 1),
            "ttft_ms": {
                "p50": round(_percentile(ttfts, 0.50), 1),
                "p99": round(_percentile(ttfts, 0.99), 1),
                "p99_label": _p99_label(ttfts),
            },
            "tpot_ms": {
                "p50": round(_percentile(tpots, 0.50), 2),
                "p99": round(_percentile(tpots, 0.99), 2),
                "p99_label": _p99_label(tpots),
            },
            "goodput": round(good / len(trace), 3),
            "slo_ttft_ms": slo_ttft_ms,
        }
    finally:
        svc.close()


def run_poisson_comparison(args) -> dict:
    """Both engines, one trace, one JSON document (the --poisson mode)."""
    from torchx_tpu.models import llama
    from torchx_tpu.serve.kv_pool import plan_pool

    platform = jax.devices()[0].platform
    cfg_name = args.config if platform == "tpu" else "tiny"
    cfg = llama.CONFIGS[cfg_name]()
    max_new = min(args.steps, cfg.max_seq // 4)
    prompt_lens = tuple(
        p for p in (4, 8, 12) if p + max_new <= cfg.max_seq
    ) or (4,)
    trace = make_workload(
        num_requests=args.requests,
        rate_rps=args.rate,
        max_new=max_new,
        prompt_lens=prompt_lens,
        seed=args.seed,
        vocab=cfg.vocab_size,
    )
    doc = {
        "bench": "serving under open-loop Poisson load",
        "config": cfg_name,
        "platform": platform,
        "workload": {
            "requests": args.requests,
            "rate_rps": args.rate,
            "max_new_tokens": max_new,
            "prompt_lens": list(prompt_lens),
            "seed": args.seed,
            "max_batch": args.max_batch,
        },
        "engines": {},
    }
    for engine in ("coalesce", "continuous"):
        doc["engines"][engine] = bench_poisson(
            cfg_name,
            engine,
            trace,
            max_batch=args.max_batch,
            slo_ttft_ms=args.slo_ttft_ms,
        )
        print(json.dumps(doc["engines"][engine]))
    cont, coal = doc["engines"]["continuous"], doc["engines"]["coalesce"]
    doc["comparison"] = {
        "decode_tokens_per_sec_speedup": round(
            cont["decode_tokens_per_sec"] / coal["decode_tokens_per_sec"], 2
        ),
        "p99_ttft_reduction": round(
            1 - cont["ttft_ms"]["p99"] / coal["ttft_ms"]["p99"], 3
        ),
        "goodput_delta": round(cont["goodput"] - coal["goodput"], 3),
    }
    # the paged-KV half of the story: concurrency at the same HBM budget
    # (tiny on CPU has no meaningful HBM; report the target-config plan)
    plan_cfg = llama.CONFIGS[args.config]()
    doc["kv_pool_occupancy"] = plan_pool(plan_cfg).occupancy_report()
    print(json.dumps(doc["comparison"]))
    return doc


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=128)
    ap.add_argument("--batches", default="1,4,8")
    ap.add_argument("--config", default="llama3_1b")
    ap.add_argument(
        "--server",
        action="store_true",
        help="also measure aggregate throughput through the HTTP server",
    )
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument(
        "--poisson",
        action="store_true",
        help="open-loop Poisson comparison: continuous engine vs"
        " coalescing baseline at equal --max-batch",
    )
    ap.add_argument(
        "--shared-prefix",
        action="store_true",
        help="shared-prefix comparison: unified continuous engines vs"
        " disaggregated prefill/decode with the radix prefix cache, at"
        " equal per-engine HBM",
    )
    ap.add_argument(
        "--shared-len",
        type=int,
        default=48,
        help="length of the common system prompt in the shared-prefix"
        " workload (tokens)",
    )
    ap.add_argument("--rate", type=float, default=8.0, help="arrivals/sec")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--slo-ttft-ms", type=float, default=1000.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="write the comparison JSON here")
    args = ap.parse_args()

    if args.poisson or args.shared_prefix:
        doc = (
            run_shared_prefix_comparison(args)
            if args.shared_prefix
            else run_poisson_comparison(args)
        )
        if args.out:
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            print(f"wrote {args.out}")
        return

    from torchx_tpu.models import llama
    from torchx_tpu.ops import quant

    platform = jax.devices()[0].platform
    if platform == "tpu":
        cfg_name = args.config
        cfg = llama.CONFIGS[cfg_name](max_seq=512, remat=False)
    else:
        cfg_name = "tiny"  # label what is actually measured
        cfg = llama.llama_tiny()
    # keep the decode window inside the config's declared context
    # (generate_stream enforces the same invariant)
    prompt_len = 32
    args.steps = min(args.steps, cfg.max_seq - prompt_len)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(cfg.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    qparams = quant.quantize_params(params)

    for batch in [int(b) for b in args.batches.split(",")]:
        for name, p in (("bf16", params), ("int8", qparams)):
            try:
                tps = bench_decode(p, cfg, batch, args.steps)
            except Exception as e:  # noqa: BLE001 - report per point
                print(
                    json.dumps(
                        {"point": f"{name}@b{batch}", "error": str(e)[:200]}
                    )
                )
                continue
            print(
                json.dumps(
                    {
                        "metric": f"decode tokens/sec ({cfg_name}, {name},"
                        f" batch={batch}, {platform})",
                        "value": round(tps, 1),
                        "unit": "tokens/sec",
                        "per_row": round(tps / batch, 1),
                    }
                )
            )

    if args.server:
        for int8 in (False, True):
            print(
                json.dumps(
                    bench_server(cfg_name, int8, args.steps, args.clients)
                )
            )
            print(json.dumps(bench_stream_ttft(cfg_name, int8, args.steps)))


if __name__ == "__main__":
    main()
