"""Benchmark: Llama training throughput on the available hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

On a real TPU chip this trains Llama-3.2-1B (bf16, remat, flash attention)
on synthetic data and reports tokens/sec/chip and MFU; ``vs_baseline``
is MFU relative to the 45%-MFU north-star from BASELINE.json (the
reference itself publishes no numbers — it is a launcher; see BASELINE.md).
Also reported: launch-to-first-step (process start -> step-1 done), the
other north-star metric.

On CPU (no TPU) it falls back to the tiny config so the metric stays
runnable anywhere.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_START = time.monotonic()

CORPUS_PATH = "/tmp/tpx_bench_corpus.bin"
CORPUS_TOKENS = 16_000_000


def _ensure_corpus() -> str:
    """Deterministic random-token corpus for the TokenDataset pipeline
    (memmap + per-process shard + double-buffer prefetch — the REAL input
    path, exercised so the bench measures input overlap, not just math).
    Written once, reused across runs."""
    import numpy as np

    want_bytes = CORPUS_TOKENS * 4
    if (
        not os.path.exists(CORPUS_PATH)
        or os.path.getsize(CORPUS_PATH) != want_bytes
    ):
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 128256, size=CORPUS_TOKENS, dtype=np.uint32)
        toks.tofile(CORPUS_PATH)
    return CORPUS_PATH


def _tpu_probe_once(timeout: float) -> str:
    """Probe the TPU in a subprocess: a wedged device tunnel hangs backend
    init forever, which would otherwise hang the whole bench.

    -> "tpu" (usable), "absent" (probe completed cleanly on a non-TPU
    platform — definitive, no point retrying), or "retry" (timeout/crash —
    a wedged tunnel often clears on a fresh process).
    """
    code = (
        "import jax, jax.numpy as jnp;"
        "y = jax.jit(lambda a: a @ a)(jnp.ones((8, 8)));"
        "jax.block_until_ready(y);"
        "print(jax.devices()[0].platform)"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return "retry"
    if proc.returncode != 0:
        return "retry"
    return "tpu" if "tpu" in proc.stdout.lower() else "absent"


def _tpu_usable(budget: float = 420.0) -> bool:
    """Probe with retries across ``budget`` seconds.

    A single-shot probe can lose its whole timeout to one wedged tunnel
    connection attempt (that is exactly what produced round 1's CPU
    fallback); transient tunnel resets often clear on a fresh process, so
    retry with backoff until the budget is spent.
    """
    deadline = time.monotonic() + budget
    timeouts = [90.0, 90.0, 100.0, 120.0]
    for i, t in enumerate(timeouts):
        remaining = deadline - time.monotonic()
        if i > 0 and remaining <= 10.0:
            break
        t = min(t, max(remaining, 30.0))
        t0 = time.monotonic()
        verdict = _tpu_probe_once(timeout=t)
        took = time.monotonic() - t0
        print(
            f"TPU probe attempt {i + 1}/{len(timeouts)}: "
            f"{verdict} ({took:.1f}s)",
            file=sys.stderr,
        )
        if verdict == "tpu":
            return True
        if verdict == "absent":
            return False  # clean non-TPU verdict is definitive
        if i + 1 < len(timeouts):
            time.sleep(
                min(10.0 * (i + 1), max(0.0, deadline - time.monotonic()))
            )
    return False


def main() -> None:
    probe_t0 = time.monotonic()
    tpu_ok = _tpu_usable()
    probe_s = time.monotonic() - probe_t0
    if not tpu_ok:
        # dead/absent accelerator: fall back to CPU (single device, so
        # per-chip numbers stay comparable) with a clearly-labeled line
        print("TPU unusable; benching on CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if not tpu_ok:
        # env var alone suffices normally; the config update additionally
        # overrides sandboxes whose sitecustomize force-picked a platform
        jax.config.update("jax_platforms", "cpu")

    platform = jax.devices()[0].platform

    # bench under a trace id so the trainer emits the launch.breakdown
    # span family + first-step heartbeat into the obs JSONL (inspect with
    # `tpx trace <id>` / the launch-stage histogram)
    from torchx_tpu import settings as tpx_settings
    from torchx_tpu.obs import trace as obs_trace

    os.environ.setdefault(tpx_settings.ENV_TPX_TRACE_ID, obs_trace.new_trace_id())

    from torchx_tpu.examples.train_llama import train
    from torchx_tpu.models import llama

    on_tpu = platform == "tpu"
    if on_tpu:
        # 32 steps, log every 8: each log point is a block_until_ready
        # fence that breaks dispatch pipelining — logging every 4 steps
        # measured ~1.7pp of MFU lower than every 8 (r4, see
        # docs/performance.md)
        seq, steps, log_every = 2048, 32, 8
        # (remat_policy, batch, cfg overrides) in preference order; measured
        # on v5e-1: dots@2 with the splash kernel + 512/512 tiles (the
        # llama3_1b defaults) and whole-sequence CE chunking hits 52.4%
        # mean MFU on the REAL input pipeline; the smaller loss chunk is
        # the fallback when the [batch, seq, vocab] f32 chunk doesn't fit,
        # and batch >= 3 crashes this tunnel's remote-compile helper
        # (see docs/performance.md)
        # "auto" resolves per-launch via compiled.memory_analysis(): it
        # upgrades to dots_attn (no attention recompute in backward) when
        # the activation footprint fits HBM, and the trial compile IS the
        # winner's compile (persistent XLA cache), so launch latency pays
        # only for candidates that did NOT fit
        candidates = [
            ("auto", 2, {"loss_chunk": 2048}),
            ("dots", 2, {"loss_chunk": 2048}),
            ("dots", 2, {}),
            ("full", 8, {}),
            ("full", 4, {}),
            ("full", 2, {}),
            ("full", 1, {}),
        ]
        base_cfg = llama.llama3_1b
    else:
        seq, steps, log_every = 128, 4, 4
        candidates = [("full", 8, {})]
        base_cfg = llama.llama_tiny

    # the REAL input pipeline (memmap TokenDataset + per-process sharding +
    # double-buffer prefetch), not synthetic device-resident data: measured
    # parity within 0.3pp of synthetic (r4), so the bench exercises it
    data_path = _ensure_corpus() if on_tpu else None

    from torchx_tpu.parallel.mesh import MeshConfig

    mesh_cfg = MeshConfig(dp=1, fsdp=-1, tp=1, sp=1)

    def _is_oom(e: Exception) -> bool:
        msg = str(e).lower()
        return any(
            s in msg
            for s in ("resource_exhausted", "out of memory", "hbm", "oom")
        )

    metrics = None
    batch_used = None
    policy_used = None
    overrides_used: dict = {}
    input_used = None
    for policy, batch, overrides in candidates:
        cfg = base_cfg(remat_policy=policy, **overrides)
        # real data first, synthetic as the per-candidate fallback — a
        # candidate-specific data failure must not downgrade LATER
        # candidates (or the int8 secondary) to synthetic
        inputs = [data_path, None] if data_path is not None else [None]
        for attempt, dp in enumerate(inputs):
            try:
                metrics = train(
                    cfg,
                    mesh_cfg,
                    batch=batch,
                    seq=seq,
                    steps=steps,
                    log_every=log_every,
                    data_path=dp,
                )
                batch_used, policy_used, overrides_used = (
                    batch,
                    policy,
                    overrides,
                )
                input_used = dp
                break
            except Exception as e:  # noqa: BLE001 - OOM -> next candidate
                if _is_oom(e):
                    print(f"{policy}@{batch} OOM, trying next", file=sys.stderr)
                    break  # smaller candidate, not a different input
                if attempt + 1 < len(inputs):
                    print(
                        f"real-data run failed ({e}); retrying synthetic",
                        file=sys.stderr,
                    )
                    continue
                raise  # non-OOM failure on the last input: surface it
        if metrics is not None:
            break
    if metrics is None:
        raise RuntimeError("all bench configurations OOMed")

    # secondary: AQT int8 training matmuls on the same config. Scope "ffn"
    # only: r05 measured whole-model int8 BELOW bf16 (12,562 vs 12,912
    # tok/s/chip) — at batch 2 the attention projections are skinny
    # matmuls where AQT's per-call quantize/dequantize (scale reduction +
    # rounding over the [b*s, d] activations) costs more than the int8
    # MXU gain; the FFN matmuls have the arithmetic intensity to win. If
    # int8 still loses, the JSON says so explicitly
    # (int8_slower_than_bf16) instead of leaving a silent regression.
    int8_metrics = None
    int8_scope = "ffn"
    # reuse the RESOLVED policy (post-"auto") so the secondary leg doesn't
    # re-run selection
    resolved_policy = metrics.get("remat_policy", policy_used)
    if on_tpu and policy_used is not None:
        try:
            # re-anchor the leg's launch clock HERE: train()'s own t_call
            # fallback starts after this leg's cfg construction, so the
            # reported launch-to-first-step drifted low by the setup time
            # (and the pre-fastpath bench drifted high by process age)
            int8_anchor = time.monotonic()
            int8_cfg = base_cfg(
                remat_policy=resolved_policy,
                int8_matmuls=True,
                int8_scope=int8_scope,
                **overrides_used,
            )
            int8_metrics = train(
                int8_cfg,
                mesh_cfg,
                batch=batch_used,
                seq=seq,
                steps=steps,
                log_every=log_every,
                data_path=input_used,
                launch_anchor=int8_anchor,
            )
        except Exception as e:  # noqa: BLE001 - secondary is best-effort
            print(f"int8 secondary run failed: {e}", file=sys.stderr)

    # attribution leg: a short PROFILED rerun of the headline config. The
    # profiler fences every step (required for phase boundaries), which
    # perturbs throughput — so the headline number stays unprofiled and
    # the attribution comes from its own few steps.
    prof_summary = None
    try:
        prof_metrics = train(
            base_cfg(remat_policy=resolved_policy, **overrides_used),
            mesh_cfg,
            batch=batch_used,
            seq=seq,
            steps=min(steps, 8),
            log_every=log_every,
            data_path=input_used,
            profile=True,
            launch_anchor=time.monotonic(),
        )
        prof_summary = prof_metrics.get("profile")
    except Exception as e:  # noqa: BLE001 - attribution is best-effort
        print(f"profiled attribution run failed: {e}", file=sys.stderr)

    # overlap leg: the SAME short profiled config with bucketed gradient
    # sync (+ the fused Pallas kernels on TPU). Side-by-side with the
    # baseline attribution above, it shows what the step-time knobs buy:
    # MFU, measured overlap fraction, and the exposed grad-sync seconds.
    # The headline legs above stay unfenced and unbucketed.
    overlap_metrics = None
    overlap_summary = None
    try:
        overlap_metrics = train(
            base_cfg(remat_policy=resolved_policy, **overrides_used),
            mesh_cfg,
            batch=batch_used,
            seq=seq,
            steps=min(steps, 8),
            log_every=log_every,
            data_path=input_used,
            profile=True,
            grad_bucket_mb="auto",
            kernels="pallas" if on_tpu else "reference",
            launch_anchor=time.monotonic(),
        )
        overlap_summary = overlap_metrics.get("profile")
    except Exception as e:  # noqa: BLE001 - overlap leg is best-effort
        print(f"overlap leg failed: {e}", file=sys.stderr)

    input_kind = "tokendataset" if input_used else "synthetic"
    result = {
        "metric": f"llama training tokens/sec/chip ({'llama3_1b' if on_tpu else 'tiny'},"
        f" bf16, seq={seq}, batch={batch_used}, {input_kind}, {platform})",
        "value": round(metrics["tokens_per_sec_per_chip"], 1),
        "unit": "tokens/sec/chip",
        # north star: >=45% MFU (BASELINE.json); reference publishes no
        # numbers (control-plane launcher), so baseline = the MFU target
        "vs_baseline": round(metrics["mfu"] / 0.45, 3),
        "mfu": round(metrics["mfu"], 4),
        "launch_to_first_step_s": round(metrics["launch_to_first_step_s"], 1),
        # device-probe time paid before the trainer process-start stamp
        # (launch_to_first_step_s measures the trainer in-process)
        "probe_s": round(probe_s, 1),
        "loss": round(metrics["loss"], 4),
        "devices": jax.device_count(),
        "platform": platform,
        "input": input_kind,
    }
    if "launch_breakdown" in metrics:
        result["launch_breakdown"] = {
            k: round(v, 2) for k, v in metrics["launch_breakdown"].items()
        }
    # steady-state step-time split (data-wait vs compute) + the remat
    # policy the step actually ran with (post-"auto" resolution)
    if "remat_policy" in metrics:
        result["remat_policy"] = metrics["remat_policy"]
    if "step_time_s" in metrics:
        result["step_time_s"] = round(metrics["step_time_s"], 5)
        result["data_wait_s"] = round(metrics["data_wait_s"], 5)
        result["data_wait_frac"] = round(metrics["data_wait_frac"], 5)
        result["prefetch_depth"] = metrics.get("prefetch_depth")
    if prof_summary is not None:
        # the profiled leg's attribution: per-phase seconds, MFU, and the
        # measured collective overlap — the numbers the MFU push tracks
        # across rounds (obs/profile.py; render with `tpx profile`)
        result["profile"] = {
            "steps": prof_summary.get("steps"),
            "mfu": round(float(prof_summary.get("mfu") or 0.0), 4),
            "data_wait_frac": round(
                float(prof_summary.get("data_wait_frac") or 0.0), 5
            ),
            "overlap_frac": (
                round(float(prof_summary["overlap_frac"]), 4)
                if prof_summary.get("overlap_frac") is not None
                else None
            ),
            "phase_seconds": {
                k: round(float(v), 5)
                for k, v in (prof_summary.get("phase_seconds") or {}).items()
            },
            "grad_sync_seconds": {
                k: round(float(v), 5)
                for k, v in (
                    prof_summary.get("grad_sync_seconds") or {}
                ).items()
            },
        }
        if "calibration" in prof_summary:
            result["profile"]["calibration"] = prof_summary["calibration"][
                "scales"
            ]

    def _overlap_leg(summ: dict, met: dict) -> dict:
        grad_sync = summ.get("grad_sync_seconds") or {}
        return {
            "mfu": round(float(summ.get("mfu") or 0.0), 4),
            "overlap_frac": (
                round(float(summ["overlap_frac"]), 4)
                if summ.get("overlap_frac") is not None
                else None
            ),
            "comm_exposed_s": round(float(summ.get("comm_exposed_s") or 0.0), 5),
            "grad_sync_seconds": {
                k: round(float(v), 5) for k, v in sorted(grad_sync.items())
            },
            "grad_bucket_mb": met.get("grad_bucket_mb", 0),
            "grad_buckets": met.get("grad_buckets", 0),
            "kernels": met.get("kernels", "reference"),
        }

    if overlap_summary is not None:
        # baseline (single fused sync, reference kernels) vs bucketed
        # (+ fused kernels on TPU), both from short profiled reruns of
        # the headline config — the side-by-side the MFU push tracks
        result["overlap"] = {
            "baseline": (
                _overlap_leg(prof_summary, prof_metrics)
                if prof_summary is not None
                else None
            ),
            "bucketed": _overlap_leg(overlap_summary, overlap_metrics),
            "loss_matches_baseline": (
                bool(overlap_metrics["loss"] == prof_metrics["loss"])
                if prof_summary is not None
                and overlap_metrics.get("kernels") == "reference"
                else None  # fused kernels legitimately change rounding
            ),
        }
    if int8_metrics is not None:
        result["int8_mfu"] = round(int8_metrics["mfu"], 4)
        result["int8_tokens_per_sec_per_chip"] = round(
            int8_metrics["tokens_per_sec_per_chip"], 1
        )
        result["int8_scope"] = int8_scope
        # explicit regression gate: int8 must beat (or tie) bf16 on the
        # same config, else the JSON flags it rather than hiding it
        result["int8_slower_than_bf16"] = bool(
            int8_metrics["tokens_per_sec_per_chip"]
            < metrics["tokens_per_sec_per_chip"]
        )
        # the int8 leg's OWN launch latency (per-call reference), not the
        # cumulative process age the pre-fastpath bench reported
        result["int8_launch_to_first_step_s"] = round(
            int8_metrics["launch_to_first_step_s"], 1
        )
    # deep-preflight predictions next to the measured numbers, so the
    # static cost model's error is tracked across bench rounds (the
    # analyzer side of `tpx explain` — jax-free, pure arithmetic)
    _plan = None
    try:
        from torchx_tpu.analyze import costmodel as _cm
        from torchx_tpu.analyze.plan import MODEL_SHAPES, ParallelPlan

        _name = "llama3_1b" if on_tpu else "tiny"
        _plan = ParallelPlan(
            role="bench",
            model=MODEL_SHAPES[_name],
            mesh_spec="fsdp=-1",
            sizes=mesh_cfg.resolve(jax.device_count()),
            batch=int(batch_used),
            seq=int(seq),
            remat_policy=str(result.get("remat_policy", policy_used)),
            devices=jax.device_count(),
            slices=1,
            chips_per_slice=jax.device_count(),
        )
        _fit = _cm.hbm_fit(_plan)
        result["explain_predictions"] = {
            "hbm_total_bytes": _fit.total_bytes,
            "hbm_components": dict(sorted(_fit.components.items())),
            "collective_bytes_per_step": {
                t.axis: t.bytes_per_step
                for t in _cm.collective_traffic(_plan)
            },
        }
    except Exception as e:  # noqa: BLE001 - predictions must not sink a bench
        print(f"explain predictions failed: {e}", file=sys.stderr)
    # the closed loop (`tpx tune`): fold THIS bench's prediction-vs-actual
    # step-time error into the persisted per-generation calibration table
    # (error strictly shrinks: EMA gain 0.5 halves the residual), then run
    # the static tune funnel so the JSON carries the prune report + the
    # winner artifact. Kill switch: TPX_BENCH_TUNE=0.
    if os.environ.get("TPX_BENCH_TUNE", "1").lower() not in ("0", "false"):
        _gen = ""
        try:
            from torchx_tpu.tune import rank as _rank
            from torchx_tpu.tune.calibrate import (
                CalibrationTable,
                generation_key,
            )

            _gen = generation_key(
                getattr(jax.devices()[0], "device_kind", "") if on_tpu else ""
            )
            if _plan is not None and "step_time_s" in metrics:
                _table = CalibrationTable.load_default()
                # predict with the PRE-update scales: the before/after
                # errors below then show this run's calibration gain
                _cost = _rank.predicted_step_cost(
                    _plan,
                    generation=_gen,
                    calibration=_table.scales_for(_gen),
                )
                _obs = _table.observe(
                    _gen,
                    predicted_step_s=_cost.step_s,
                    measured_step_s=float(metrics["step_time_s"]),
                    predicted_collective_s=_cost.collective_s,
                )
                _table.save()
                result["tune_calibration"] = {
                    "generation": _gen,
                    "predicted_step_s": round(_cost.step_s, 6),
                    "measured_step_s": round(
                        float(metrics["step_time_s"]), 6
                    ),
                    "err_before": round(_obs["step_time"]["err_before"], 4),
                    "err_after": round(_obs["step_time"]["err_after"], 4),
                    "scales": _obs["scales"],
                }
        except Exception as e:  # noqa: BLE001 - best-effort closed loop
            print(f"tune calibration failed: {e}", file=sys.stderr)
        try:
            from torchx_tpu.tune.driver import run_tune
            from torchx_tpu.tune.space import (
                bench_1b_space,
                tiny_smoke_space,
            )

            _space = bench_1b_space() if on_tpu else tiny_smoke_space()
            _tuned = run_tune(
                _space,
                devices=jax.device_count(),
                generation=_gen,
                aot=False,  # bench time budget: static funnel only
                measure=False,  # the bench run above IS the measurement
            )
            result["tune_report"] = _tuned.report
            result["tune_artifact"] = _tuned.artifact_path
            if _tuned.winner is not None:
                result["tune_winner"] = _tuned.winner.candidate.to_dict()
        except Exception as e:  # noqa: BLE001 - best-effort closed loop
            print(f"tune report failed: {e}", file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
