#!/usr/bin/env bash
# Role dispatcher for the vendored slurm test cluster: every container
# starts munged (shared baked-in key = cluster auth), then runs the role
# given as the compose command. Waits use bash /dev/tcp so the image needs
# no extra client packages.
set -euo pipefail

mkdir -p /run/munge
chown munge:munge /run/munge
runuser -u munge -- /usr/sbin/munged

wait_tcp() { # host port
  local i
  for i in $(seq 1 60); do
    if (echo > "/dev/tcp/$1/$2") 2>/dev/null; then
      return 0
    fi
    sleep 2
  done
  echo "timed out waiting for $1:$2" >&2
  return 1
}

case "${1:-}" in
  slurmdbd)
    wait_tcp mysql 3306
    exec runuser -u slurm -- /usr/sbin/slurmdbd -D -v
    ;;
  slurmctld)
    wait_tcp slurmdbd 6819
    exec runuser -u slurm -- /usr/sbin/slurmctld -D -v
    ;;
  slurmd)
    wait_tcp slurmctld 6817
    exec /usr/sbin/slurmd -D -v
    ;;
  *)
    exec "$@"
    ;;
esac
