"""AST linter + docstring help extraction for component functions.

Reference analog: torchx/specs/file_linter.py (397 LoC). A *component* is a
plain function returning AppDef; to stay CLI-renderable it must:

* annotate every parameter with a supported type
  (str/int/float/bool/Optional of those/list[...]/dict[...]),
* annotate its return type as AppDef,
* carry a docstring (google style recommended) — the summary becomes the
  component help and the Args: entries become per-flag help.

``validate(path, fn_name)`` returns LinterMessages; ``get_fn_docstring``
parses help text with a built-in minimal google-docstring parser (no
third-party docstring_parser dependency).
"""

from __future__ import annotations

import ast
import inspect
import re
from dataclasses import dataclass
from typing import Callable, Optional

_SUPPORTED_SIMPLE = {"str", "int", "float", "bool"}
_SUPPORTED_CONTAINERS = {"list", "List", "dict", "Dict", "Optional", "Union"}


@dataclass
class LinterMessage:
    """One finding about a component function's source.

    ``code`` is the stable diagnostic code shared with the preflight
    analyzer (:mod:`torchx_tpu.analyze`): TPX001 syntax/not-found, TPX002
    missing annotation, TPX003 unsupported type, TPX004 ``**kwargs``,
    TPX005 return annotation, TPX006 missing docstring (warning).
    """

    name: str
    description: str
    line: int = 0
    char: int = 0
    severity: str = "error"
    code: str = "TPX001"


# =========================================================================
# Docstring parsing (google style)
# =========================================================================

_SECTION_RE = re.compile(r"^\s*(Args|Arguments|Returns|Raises|Example[s]?|Note[s]?):\s*$")
_ARG_RE = re.compile(r"^\s{2,}(\*{0,2}\w+)\s*(?:\([^)]*\))?\s*:\s*(.*)$")


def parse_docstring(docstring: Optional[str]) -> tuple[str, dict[str, str]]:
    """-> (summary, {arg_name: help}). Tolerates missing/empty docstrings."""
    if not docstring:
        return "", {}
    lines = docstring.expandtabs().splitlines()
    summary_lines: list[str] = []
    args: dict[str, str] = {}
    section = None
    current_arg: Optional[str] = None
    for line in lines:
        m = _SECTION_RE.match(line)
        if m:
            section = m.group(1)
            current_arg = None
            continue
        if section is None:
            if line.strip():
                summary_lines.append(line.strip())
            elif summary_lines:
                section = "__post_summary__"
            continue
        if section in ("Args", "Arguments"):
            am = _ARG_RE.match(line)
            if am:
                current_arg = am.group(1).lstrip("*")
                args[current_arg] = am.group(2).strip()
            elif current_arg and line.strip():
                args[current_arg] += " " + line.strip()
    return " ".join(summary_lines), args


def get_fn_docstring(fn: Callable) -> tuple[str, dict[str, str]]:
    """Summary + per-arg help for a component fn; args missing from the
    docstring get a placeholder (reference file_linter.py:60-103)."""
    summary, args = parse_docstring(fn.__doc__)
    if not summary:
        summary = f"{fn.__name__} component"
    for param in inspect.signature(fn).parameters.values():
        args.setdefault(param.name, f"{param.name} (no docstring)")
    return summary, args


# =========================================================================
# AST validation
# =========================================================================


def _annotation_ok(node: Optional[ast.expr]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id in _SUPPORTED_SIMPLE or node.id in _SUPPORTED_CONTAINERS
    if isinstance(node, ast.Attribute):
        return node.attr in _SUPPORTED_SIMPLE | _SUPPORTED_CONTAINERS
    if isinstance(node, ast.Subscript):
        return _annotation_ok(node.value)
    if isinstance(node, ast.Constant):
        if node.value is None:
            return True
        # ``from __future__ import annotations`` style string annotations:
        # "str | None", "Optional[int]", ... — parse and validate the inner
        # expression.
        if isinstance(node.value, str):
            try:
                inner = ast.parse(node.value, mode="eval")
            except SyntaxError:
                return False
            return _annotation_ok(inner.body)
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _annotation_ok(node.left) and _annotation_ok(node.right)
    return False


def _returns_appdef(node: Optional[ast.expr]) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "AppDef"
    if isinstance(node, ast.Attribute):
        return node.attr == "AppDef"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.endswith("AppDef")
    return False


def validate(
    path: str, component_function: str, include_warnings: bool = False
) -> list[LinterMessage]:
    """Parse the file and validate the named component fn is CLI-renderable."""
    with open(path) as f:
        source = f.read()
    return validate_source(source, component_function, path, include_warnings)


def validate_source(
    source: str,
    component_function: str,
    path: str = "<string>",
    include_warnings: bool = False,
) -> list[LinterMessage]:
    """Validate one component fn in ``source``. Returns error-severity
    messages only unless ``include_warnings`` is set (the preflight
    analyzer wants the warnings too)."""
    errors: list[LinterMessage] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            LinterMessage(
                name=component_function,
                description=f"syntax error: {e}",
                line=e.lineno or 0,
                code="TPX001",
            )
        ]
    fn_node: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == component_function:
                fn_node = node  # type: ignore[assignment]
                break
    if fn_node is None:
        return [
            LinterMessage(
                name=component_function,
                description=f"function {component_function!r} not found in {path}",
                code="TPX001",
            )
        ]

    def err(desc: str, node: ast.AST, code: str) -> None:
        errors.append(
            LinterMessage(
                name=component_function,
                description=desc,
                line=getattr(node, "lineno", 0),
                char=getattr(node, "col_offset", 0),
                code=code,
            )
        )

    a = fn_node.args
    all_args = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    for arg in all_args:
        if arg.annotation is None:
            err(
                f"parameter {arg.arg!r} is missing a type annotation",
                arg,
                "TPX002",
            )
        elif not _annotation_ok(arg.annotation):
            err(
                f"parameter {arg.arg!r} has unsupported type"
                f" {ast.unparse(arg.annotation)} (supported:"
                " str/int/float/bool, Optional/list/dict of those)",
                arg,
                "TPX003",
            )
    if a.vararg is not None and a.vararg.annotation is not None:
        if not _annotation_ok(a.vararg.annotation):
            err(f"*{a.vararg.arg} has unsupported annotation", a.vararg, "TPX003")
    if a.kwarg is not None:
        err("**kwargs is not supported in component functions", a.kwarg, "TPX004")
    if fn_node.returns is None or not _returns_appdef(fn_node.returns):
        err(
            "component function must have return annotation -> AppDef",
            fn_node,
            "TPX005",
        )
    if ast.get_docstring(fn_node) is None:
        errors.append(
            LinterMessage(
                name=component_function,
                description=f"{component_function} is missing a docstring",
                line=fn_node.lineno,
                severity="warning",
                code="TPX006",
            )
        )
    if include_warnings:
        return errors
    return [e for e in errors if e.severity == "error"]
