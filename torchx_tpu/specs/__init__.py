"""Public interface of the specs layer.

Reference analog: torchx/specs/__init__.py:32-239 — re-exports the data
model and hosts the named-resource registry with merge order
generic < tpu < custom ($TPX_CUSTOM_NAMED_RESOURCES) < plugins.
"""

from __future__ import annotations

import importlib
import logging
import os
from typing import Callable, Mapping, Optional

from torchx_tpu import settings
from torchx_tpu.specs.api import (  # noqa: F401
    NONE,
    NULL_RESOURCE,
    RESOURCE_UNSET,
    AppDef,
    AppDryRunInfo,
    AppHandle,
    AppState,
    AppStatus,
    AppStatusError,
    BindMount,
    CfgVal,
    DeviceMount,
    FailureClass,
    InvalidRunConfigException,
    MalformedAppHandleException,
    MountType,
    ReplicaStatus,
    Resource,
    RetryPolicy,
    Role,
    RoleStatus,
    TpuSlice,
    VolumeMount,
    Workspace,
    is_started,
    is_terminal,
    macros,
    make_app_handle,
    make_structured_error,
    parse_app_handle,
    parse_mounts,
    runopt,
    runopts,
)
from torchx_tpu.specs.named_resources_gcp import named_resources_gcp
from torchx_tpu.specs.named_resources_generic import named_resources_generic
from torchx_tpu.specs.named_resources_tpu import named_resources_tpu, tpu_slice

logger = logging.getLogger(__name__)

_named_resource_factories: Optional[dict[str, Callable[[], Resource]]] = None


def _load_custom_factories() -> Mapping[str, Callable[[], Resource]]:
    """$TPX_CUSTOM_NAMED_RESOURCES is a comma list of ``module[:fn]`` specs;
    each fn returns a mapping of name -> factory."""
    out: dict[str, Callable[[], Resource]] = {}
    spec = os.environ.get(settings.ENV_TPX_CUSTOM_NAMED_RESOURCES, "")
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        mod_name, _, fn_name = entry.partition(":")
        try:
            mod = importlib.import_module(mod_name)
            fn = getattr(mod, fn_name or "named_resources")
            out.update(fn())
        except Exception as e:  # noqa: BLE001 - custom modules must not kill the CLI
            logger.warning("failed to load custom named resources %r: %s", entry, e)
    return out


def _factories() -> dict[str, Callable[[], Resource]]:
    global _named_resource_factories
    if _named_resource_factories is None:
        merged: dict[str, Callable[[], Resource]] = {}
        merged.update(named_resources_generic())
        merged.update(named_resources_gcp())
        merged.update(named_resources_tpu())
        merged.update(_load_custom_factories())
        try:  # plugins may not be importable during bootstrap
            from torchx_tpu.plugins import get_plugin_named_resources

            merged.update(get_plugin_named_resources())
        except ImportError:
            pass
        _named_resource_factories = merged
    return _named_resource_factories


class _NamedResources(Mapping[str, Resource]):
    """Lazy mapping view: ``named_resources["v5p-32"]`` -> Resource.

    Falls back to parsing unknown keys as accelerator-type strings so any
    slice size works without being pre-registered.
    """

    def __getitem__(self, name: str) -> Resource:
        f = _factories().get(name)
        if f is not None:
            return f()
        try:
            return tpu_slice(name)
        except ValueError:
            raise KeyError(
                f"unknown named resource {name!r}; known: {sorted(_factories())[:20]}..."
            ) from None

    def __contains__(self, name: object) -> bool:
        if name in _factories():
            return True
        try:
            tpu_slice(str(name))
            return True
        except ValueError:
            return False

    def __iter__(self):
        return iter(_factories())

    def __len__(self) -> int:
        return len(_factories())


named_resources: Mapping[str, Resource] = _NamedResources()


def resource(
    cpu: Optional[float] = None,
    memMB: Optional[int] = None,
    tpu: Optional[str] = None,
    h: Optional[str] = None,
) -> Resource:
    """Resource factory used by components.

    ``h`` (named resource, e.g. "v5p-32" or "cpu_small") wins over explicit
    cpu/memMB/tpu, matching the reference's precedence
    (torchx/specs/__init__.py:75-181).
    """
    if h:
        return named_resources[h]
    return Resource(
        cpu=cpu if cpu is not None else 1,
        memMB=memMB if memMB is not None else 1024,
        tpu=TpuSlice.from_type(tpu) if tpu else None,
    )


def get_named_resources() -> Mapping[str, Callable[[], Resource]]:
    """Every registered named resource (generic < gcp < custom env <
    plugins, later wins), keyed by name."""
    return dict(_factories())


def invalidate_named_resources_cache() -> None:
    """Re-merge the registry on next access (called when plugins reload)."""
    global _named_resource_factories
    _named_resource_factories = None
