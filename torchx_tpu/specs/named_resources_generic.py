"""Generic t-shirt-size named resources (CPU-only helper roles).

Reference analog: torchx/specs/named_resources_generic.py:46-61.
"""

from __future__ import annotations

from typing import Callable, Mapping

from torchx_tpu.specs.api import Resource

GiB = 1024


def _mk(name: str, cpu: int, mem_gb: int) -> Callable[[], Resource]:
    def factory() -> Resource:
        return Resource(cpu=cpu, memMB=mem_gb * GiB)

    factory.__name__ = name
    return factory


def named_resources_generic() -> Mapping[str, Callable[[], Resource]]:
    return {
        "cpu_nano": _mk("cpu_nano", 1, 1),
        "cpu_micro": _mk("cpu_micro", 1, 2),
        "cpu_small": _mk("cpu_small", 2, 8),
        "cpu_medium": _mk("cpu_medium", 8, 32),
        "cpu_large": _mk("cpu_large", 16, 64),
        "cpu_xlarge": _mk("cpu_xlarge", 32, 128),
    }
