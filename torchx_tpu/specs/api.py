"""Core data model for the TPU-native job launcher.

This is the foundation layer: everything else in the package imports it and it
imports nothing above it (reference analog: torchx/specs/api.py — AppDef /
Role / Resource / AppStatus / runopts / macros).

The central TPU-first departure from the reference: a :class:`Resource` does
not carry a GPU count; it carries a :class:`TpuSlice` — accelerator
generation, chip count and ICI topology — because TPUs are allocated as whole
pod slices with a fixed interconnect shape, not as per-node device counts
(reference analog it replaces: ``Resource.gpu`` at specs/api.py:97-170).
"""

from __future__ import annotations

import copy
import json
import math
import re
import warnings
from dataclasses import dataclass, field
from datetime import datetime
from enum import Enum
from string import Template
from typing import Any, Callable, Generic, Iterator, Mapping, Optional, TypeVar, Union

# =========================================================================
# TPU slice model
# =========================================================================

# Physical facts per TPU generation. ``cores_per_chip`` matters because v4/v5p
# slice names count TensorCores ("v4-8" = 4 chips) while v5e/v6e names count
# chips ("v5litepod-8" = 8 chips). ``single_host_chips`` is the largest slice
# that fits on ONE TPU-VM host; ``multi_host_vm_chips`` is the chips-per-VM
# for slices bigger than that. The two differ on v5e/v6e: single-host slices
# come as 1/4/8-chip VMs (ct5lp-hightpu-{1,4,8}t / ct6e-standard-{1,4,8}t)
# but multi-host slices are built EXCLUSIVELY from 4-chip VMs
# (ct5lp-hightpu-4t / ct6e-standard-4t) — e.g. v5litepod-16 is 4 hosts x 4
# chips on a 4x4 topology, never 2 hosts x 8.
_GIB = 1024**3

# hbm_bytes: per-chip HBM capacity (the deep-preflight fit budget; v5p is
# the 95 GiB figure parallel/aot_fit.py uses for the north-star gate).
_TPU_GENERATIONS: dict[str, dict[str, Any]] = {
    "v2": {"cores_per_chip": 2, "single_host_chips": 4, "multi_host_vm_chips": 4, "name_counts_cores": True, "hbm_bytes": 8 * _GIB},
    "v3": {"cores_per_chip": 2, "single_host_chips": 4, "multi_host_vm_chips": 4, "name_counts_cores": True, "hbm_bytes": 16 * _GIB},
    "v4": {"cores_per_chip": 2, "single_host_chips": 4, "multi_host_vm_chips": 4, "name_counts_cores": True, "hbm_bytes": 32 * _GIB},
    "v5p": {"cores_per_chip": 2, "single_host_chips": 4, "multi_host_vm_chips": 4, "name_counts_cores": True, "hbm_bytes": 95 * _GIB},
    "v5e": {"cores_per_chip": 1, "single_host_chips": 8, "multi_host_vm_chips": 4, "name_counts_cores": False, "hbm_bytes": 16 * _GIB},
    "v6e": {"cores_per_chip": 1, "single_host_chips": 8, "multi_host_vm_chips": 4, "name_counts_cores": False, "hbm_bytes": 32 * _GIB},
    "v7x": {"cores_per_chip": 2, "single_host_chips": 4, "multi_host_vm_chips": 4, "name_counts_cores": False, "hbm_bytes": 192 * _GIB},
}

# Aliases seen in Cloud TPU accelerator-type strings.
_TPU_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v6litepod": "v6e",
}

_ACCEL_TYPE_RE = re.compile(r"^(?P<gen>[a-z0-9]+)-(?P<count>\d+)$")


def _factor3(chips: int) -> str:
    """Pick a default 3D ICI topology ``AxBxC`` for a chip count.

    Real slices come in specific shapes; for the common power-of-two counts
    this reproduces the standard shapes (e.g. 8 -> 2x2x2, 16 -> 2x2x4,
    32 -> 2x4x4). Callers that care about the exact physical shape should
    pass ``topology`` explicitly.
    """
    dims = [1, 1, 1]
    i = 0
    remaining = chips
    # Greedily split prime factors over the three axes, smallest axis first.
    for p in _prime_factors(remaining):
        dims.sort()
        dims[0] *= p
        i += 1
    dims.sort()
    return "x".join(str(d) for d in dims)


def _prime_factors(n: int) -> list[int]:
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


@dataclass(frozen=True)
class TpuSlice:
    """A TPU pod slice: the unit of accelerator allocation.

    A slice is all-or-nothing — the ICI mesh only exists within a slice, so
    the launcher gang-schedules ``hosts`` workers together, one process per
    TPU-VM host (the canonical JAX process layout).

    Attributes:
        accelerator: generation, e.g. ``"v5p"``, ``"v5e"``, ``"v4"``, ``"v6e"``.
        chips: total chips in the slice.
        topology: ICI topology string like ``"2x2x4"`` (v4/v5p are 3D tori,
            v5e/v6e are 2D meshes like ``"4x8"``). ``None`` means "any shape
            with this chip count" — schedulers that need a concrete shape
            (GKE node selectors) will default it via :meth:`default_topology`.
    """

    accelerator: str
    chips: int
    topology: Optional[str] = None

    def __post_init__(self) -> None:
        gen = _TPU_ALIASES.get(self.accelerator, self.accelerator)
        if gen not in _TPU_GENERATIONS:
            raise ValueError(
                f"unknown TPU generation: {self.accelerator!r};"
                f" known: {sorted(_TPU_GENERATIONS)} (+aliases {sorted(_TPU_ALIASES)})"
            )
        object.__setattr__(self, "accelerator", gen)
        if self.chips <= 0:
            raise ValueError(f"chips must be positive, got {self.chips}")
        if self.topology is not None:
            prod = math.prod(int(d) for d in self.topology.split("x"))
            if prod != self.chips:
                raise ValueError(
                    f"topology {self.topology} has {prod} chips, expected {self.chips}"
                )

    # -- derived facts -----------------------------------------------------

    @property
    def cores_per_chip(self) -> int:
        return _TPU_GENERATIONS[self.accelerator]["cores_per_chip"]

    @property
    def cores(self) -> int:
        return self.chips * self.cores_per_chip

    @property
    def hbm_bytes_per_chip(self) -> int:
        """Per-chip HBM capacity — the deep-preflight memory-fit budget."""
        return _TPU_GENERATIONS[self.accelerator]["hbm_bytes"]

    @property
    def chips_per_host(self) -> int:
        """Chips exposed to each TPU-VM host in this slice.

        Shape-dependent on v5e/v6e: a slice that fits on one host uses that
        host's full chip count (up to 8), but multi-host slices are built
        from 4-chip VMs only (``ct5lp-hightpu-4t`` / ``ct6e-standard-4t``),
        so ``v5litepod-16`` is 4 hosts x 4 chips, not 2 x 8.
        """
        info = _TPU_GENERATIONS[self.accelerator]
        if self.chips <= info["single_host_chips"]:
            return self.chips
        return info["multi_host_vm_chips"]

    @property
    def hosts(self) -> int:
        """Number of TPU-VM hosts (== JAX processes) in the slice."""
        return max(1, math.ceil(self.chips / self.chips_per_host))

    def default_topology(self) -> str:
        """A concrete topology for schedulers that require one.

        v4/v5p use 3D tori; v5e/v6e use 2D meshes.
        """
        if self.topology:
            return self.topology
        if _TPU_GENERATIONS[self.accelerator]["cores_per_chip"] == 2 and self.accelerator in (
            "v4",
            "v5p",
        ):
            return _factor3(self.chips)
        # 2D mesh: as square as possible.
        a = int(math.sqrt(self.chips))
        while a > 1 and self.chips % a:
            a -= 1
        return f"{a}x{self.chips // a}"

    # -- naming ------------------------------------------------------------

    @property
    def accelerator_type(self) -> str:
        """Cloud TPU accelerator-type string, e.g. ``"v5p-32"`` / ``"v5litepod-8"``.

        v2..v5p count TensorCores in the suffix; v5e/v6e count chips
        (this inconsistency is Cloud TPU's, faithfully reproduced).
        """
        info = _TPU_GENERATIONS[self.accelerator]
        if info["name_counts_cores"]:
            return f"{self.accelerator}-{self.cores}"
        name = {"v5e": "v5litepod", "v6e": "v6e"}.get(self.accelerator, self.accelerator)
        return f"{name}-{self.chips}"

    @classmethod
    def from_type(cls, accelerator_type: str, topology: Optional[str] = None) -> "TpuSlice":
        """Parse a Cloud TPU accelerator-type string.

        >>> TpuSlice.from_type("v5p-32").chips
        16
        >>> TpuSlice.from_type("v5litepod-8").chips
        8
        """
        m = _ACCEL_TYPE_RE.match(accelerator_type.strip().lower())
        if not m:
            raise ValueError(f"malformed TPU accelerator type: {accelerator_type!r}")
        gen = _TPU_ALIASES.get(m.group("gen"), m.group("gen"))
        if gen not in _TPU_GENERATIONS:
            raise ValueError(f"unknown TPU generation in {accelerator_type!r}")
        count = int(m.group("count"))
        info = _TPU_GENERATIONS[gen]
        chips = count // info["cores_per_chip"] if info["name_counts_cores"] else count
        if chips <= 0:
            raise ValueError(f"accelerator type {accelerator_type!r} has no chips")
        return cls(accelerator=gen, chips=chips, topology=topology)

    def __str__(self) -> str:
        t = f", topology={self.topology}" if self.topology else ""
        return f"TpuSlice({self.accelerator_type}, chips={self.chips}{t})"


# =========================================================================
# Resource
# =========================================================================


@dataclass
class Resource:
    """Per-replica resource requirements.

    Attributes:
        cpu: logical CPUs (on TPU-VM hosts this is usually the whole host).
        memMB: host RAM in MB.
        tpu: TPU slice this replica's gang occupies, or None for CPU-only.
            NOTE: ``tpu`` describes the *whole slice for the role*; a role
            with a multi-host slice gets ``tpu.hosts`` replicas scheduled by
            TPU-aware backends (one process per host).
        capabilities: scheduler-interpreted extras (machine type, disk, spot).
        devices: named host devices with counts (e.g. ``{"nvidia.com/gpu": 1}``
            for heterogeneous clusters; TPU chips do NOT go here).
        tags: freeform labels propagated to backends that support them.
    """

    cpu: float = -1
    memMB: int = -1
    tpu: Optional[TpuSlice] = None
    capabilities: dict[str, Any] = field(default_factory=dict)
    devices: dict[str, int] = field(default_factory=dict)
    tags: dict[str, str] = field(default_factory=dict)

    @staticmethod
    def copy(original: "Resource", **capabilities: Any) -> "Resource":
        res = copy.deepcopy(original)
        res.capabilities.update(capabilities)
        return res


NULL_RESOURCE = Resource(cpu=-1, memMB=-1)

# Sentinel used by components: "scheduler should fill in the resource".
RESOURCE_UNSET = "__UNSET__"


# =========================================================================
# Mounts
# =========================================================================


class MountType(str, Enum):
    BIND = "bind"
    VOLUME = "volume"
    DEVICE = "device"


@dataclass
class BindMount:
    """Bind-mount a host path into the replica container."""

    src_path: str
    dst_path: str
    read_only: bool = False


@dataclass
class VolumeMount:
    """Mount a named volume (docker volume / k8s PVC / GCS fuse bucket)."""

    src: str
    dst_path: str
    read_only: bool = False


@dataclass
class DeviceMount:
    """Expose a host device node inside the container."""

    src_path: str
    dst_path: str
    permissions: str = "rwm"


def parse_mounts(opts: list[str]) -> list[Union[BindMount, VolumeMount, DeviceMount]]:
    """Parse docker-style mount options into typed mounts.

    Format (repeating)::

        type=<bind|volume|device>,src=<src>,dst=<dst>[,readonly][,perm=<rwm>]

    ``--mount type=bind,src=/host,dst=/job,readonly``

    Reference analog: torchx/specs/builders.py:311-376.
    """
    mounts: list[Union[BindMount, VolumeMount, DeviceMount]] = []
    cur: dict[str, str] = {}
    groups: list[dict[str, str]] = []
    for opt in opts:
        for kv in opt.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if "=" in kv:
                k, _, v = kv.partition("=")
            else:
                k, v = kv, "true"
            k = k.lower()
            if k == "type" and cur:
                groups.append(cur)
                cur = {}
            cur[k] = v
    if cur:
        groups.append(cur)

    for g in groups:
        mtype = g.get("type")
        if mtype is None:
            raise ValueError(f"mount spec missing type=: {g}")
        src = g.get("src") or g.get("source")
        dst = g.get("dst") or g.get("destination") or g.get("target")
        readonly = g.get("readonly", "false").lower() in ("true", "1", "")
        if mtype == MountType.BIND.value:
            if not src or not dst:
                raise ValueError(f"bind mount needs src and dst: {g}")
            mounts.append(BindMount(src_path=src, dst_path=dst, read_only=readonly))
        elif mtype == MountType.VOLUME.value:
            if not src or not dst:
                raise ValueError(f"volume mount needs src and dst: {g}")
            mounts.append(VolumeMount(src=src, dst_path=dst, read_only=readonly))
        elif mtype == MountType.DEVICE.value:
            if not src:
                raise ValueError(f"device mount needs src: {g}")
            mounts.append(
                DeviceMount(
                    src_path=src, dst_path=dst or src, permissions=g.get("perm", "rwm")
                )
            )
        else:
            raise ValueError(f"unknown mount type {mtype!r} in {g}")
    dsts: dict[str, int] = {}
    for i, m in enumerate(mounts):
        if m.dst_path in dsts:
            raise ValueError(
                f"duplicate mount destination {m.dst_path!r}: mounts"
                f" #{dsts[m.dst_path] + 1} and #{i + 1} would shadow each"
                " other (each mount needs a distinct dst)"
            )
        dsts[m.dst_path] = i
    return mounts


# =========================================================================
# Workspace spec
# =========================================================================


@dataclass
class Workspace:
    """Maps local project directories to destination subdirs in the image.

    ``{"./src": "app/src", "./conf": "conf"}`` copies two local trees into
    the built workspace image / job dir (reference analog:
    torchx/specs/api.py:340-411).
    """

    projects: dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_str(cls, spec: str) -> "Workspace":
        """Either a single path ("." / "./proj") or a YAML/JSON-ish mapping
        string ``src1=dst1,src2=dst2``."""
        spec = spec.strip()
        if not spec:
            return cls()
        if "=" not in spec:
            return cls(projects={spec: ""})
        projects = {}
        for pair in spec.split(","):
            src, _, dst = pair.partition("=")
            projects[src.strip()] = dst.strip()
        return cls(projects=projects)

    def merge_into(self, other: "Workspace") -> "Workspace":
        merged = dict(other.projects)
        merged.update(self.projects)
        return Workspace(projects=merged)

    def __bool__(self) -> bool:
        return bool(self.projects)


# =========================================================================
# Macros
# =========================================================================


class macros:
    """Template variables substituted into Role args/env at materialize time.

    Reference analog: torchx/specs/api.py:183-274. The TPU-specific twist:
    ``coordinator_env`` substitutes to the *name* of the scheduler-specific
    env var that holds the coordinator (replica-0) hostname; the value is
    resolved by the shell at runtime — e.g.
    ``--coordinator=$${coordinator_env}:8476`` (the reference's rank0_env
    trick, specs/api.py:216-222).
    """

    img_root = "${img_root}"
    app_id = "${app_id}"
    replica_id = "${replica_id}"
    num_replicas = "${num_replicas}"
    coordinator_env = "${coordinator_env}"

    @dataclass
    class Values:
        img_root: str = ""
        app_id: str = ""
        replica_id: str = ""
        num_replicas: str = ""
        coordinator_env: str = "TPX_COORDINATOR_HOST"

        def apply(self, role: "Role") -> "Role":
            """Return a deep-copied Role with macros substituted in args,
            env values, entrypoint and mount paths."""
            role = copy.deepcopy(role)
            role.entrypoint = self.substitute(role.entrypoint)
            role.args = [self.substitute(a) for a in role.args]
            role.env = {k: self.substitute(v) for k, v in role.env.items()}
            for m in role.mounts:
                if isinstance(m, (BindMount, DeviceMount)):
                    m.src_path = self.substitute(m.src_path)
                    m.dst_path = self.substitute(m.dst_path)
                elif isinstance(m, VolumeMount):
                    m.dst_path = self.substitute(m.dst_path)
            return role

        def substitute(self, arg: str) -> str:
            return Template(arg).safe_substitute(
                img_root=self.img_root,
                app_id=self.app_id,
                replica_id=self.replica_id,
                num_replicas=self.num_replicas,
                coordinator_env=self.coordinator_env,
            )


# =========================================================================
# Role / AppDef
# =========================================================================


class RetryPolicy(str, Enum):
    """What to restart when a replica fails.

    REPLICA: restart only the failed replica (stateless services).
    APPLICATION: restart the whole app (SPMD training — a dead host kills the
        ICI collective, so the whole gang must restart; this is the default
        for TPU roles).
    ROLE: restart all replicas of the failed role.
    """

    REPLICA = "REPLICA"
    APPLICATION = "APPLICATION"
    ROLE = "ROLE"


@dataclass
class Role:
    """A homogeneous gang of replicas (one container/process template).

    For TPU roles, ``num_replicas`` is the number of TPU-VM *hosts*: one JAX
    process per host. :func:`AppDef` validation and TPU-aware schedulers keep
    ``num_replicas == resource.tpu.hosts`` in sync (see
    :meth:`Role.tpu_hosts`).

    Reference analog: torchx/specs/api.py:277-505.
    """

    name: str
    image: str = ""
    min_replicas: Optional[int] = None  # elastic lower bound; None = rigid gang
    entrypoint: str = ""
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    num_replicas: int = 1
    max_retries: int = 0
    retry_policy: RetryPolicy = RetryPolicy.APPLICATION
    resource: Resource = field(default_factory=lambda: copy.deepcopy(NULL_RESOURCE))
    port_map: dict[str, int] = field(default_factory=dict)
    metadata: dict[str, Any] = field(default_factory=dict)
    mounts: list[Union[BindMount, VolumeMount, DeviceMount]] = field(default_factory=list)
    workspace: Optional[Workspace] = None
    # Hook applied to the raw scheduler request during submit_dryrun
    # (reference analog: Role.pre_proc, schedulers/api.py:410-422).
    pre_proc: Optional[Callable[[str, Any], Any]] = None

    def pre_proc_fn(self, scheduler: str, dryrun_info: Any) -> Any:
        if self.pre_proc is None:
            return dryrun_info
        return self.pre_proc(scheduler, dryrun_info)


@dataclass
class AppDef:
    """An application: a named set of roles launched as one job."""

    name: str
    roles: list[Role] = field(default_factory=list)
    metadata: dict[str, str] = field(default_factory=dict)


# =========================================================================
# Status model
# =========================================================================


class AppState(int, Enum):
    """Lifecycle states (reference analog: torchx/specs/api.py:529-560).

    PREEMPTED is the TPU-first addition: spot/queued capacity was reclaimed
    by the provider. It is terminal for the *attempt* (the gang is gone) but
    retryable by policy — the supervisor treats it as its own failure class
    with its own budget (see :mod:`torchx_tpu.supervisor`).
    """

    UNSUBMITTED = 0
    SUBMITTED = 1
    PENDING = 2
    RUNNING = 3
    SUCCEEDED = 4
    FAILED = 5
    CANCELLED = 6
    UNKNOWN = 7
    PREEMPTED = 8

    def __str__(self) -> str:
        return self.name


class FailureClass(str, Enum):
    """Why a terminal attempt failed — the retry-decision signal.

    Schedulers classify failures via :meth:`Scheduler.classify_failure`
    (populated from backend detail: spot-reclamation markers, node
    disruption conditions); the supervisor keeps an independent retry
    budget per class.

    PREEMPTION: the provider took the capacity back (spot reclaim, node
        drain/disruption). Always worth retrying — nothing is wrong with
        the app.
    INFRA: the control plane failed the attempt (stockout, provisioning
        error, scheduler fault). Retryable a few times.
    APP: the application itself exited non-zero. The conservative default
        for unclassifiable failures — retrying a buggy app burns money.
    HANG: the scheduler still reports RUNNING but the gang stopped making
        progress (heartbeats went stale, liveness leases expired — see
        :mod:`torchx_tpu.supervisor.gang`). The supervisor kills the
        attempt itself and synthesizes this class; budgeted separately
        because a hang is usually a wedged collective or a lost replica,
        not an app bug.
    """

    PREEMPTION = "PREEMPTION"
    INFRA = "INFRA"
    APP = "APP"
    HANG = "HANG"

    def __str__(self) -> str:
        return self.value


_TERMINAL_STATES = frozenset(
    (AppState.SUCCEEDED, AppState.FAILED, AppState.CANCELLED, AppState.PREEMPTED)
)
_STARTED_STATES = frozenset(
    (
        AppState.RUNNING,
        AppState.SUCCEEDED,
        AppState.FAILED,
        AppState.CANCELLED,
        AppState.PREEMPTED,
    )
)


def is_terminal(state: AppState) -> bool:
    return state in _TERMINAL_STATES


def is_started(state: AppState) -> bool:
    return state in _STARTED_STATES


NONE: str = "<NONE>"


@dataclass
class ReplicaStatus:
    id: int
    state: AppState
    role: str
    hostname: str = ""
    structured_error_msg: str = NONE


@dataclass
class RoleStatus:
    role: str
    replicas: list[ReplicaStatus] = field(default_factory=list)


@dataclass
class AppStatus:
    """Status of a submitted app, aggregated over roles/replicas.

    ``structured_error_msg`` carries the JSON error file content written by
    the first failed replica (see settings.ENV_TPX_ERROR_FILE); ``format()``
    pretty-prints it (reference analog: specs/api.py:596-778).

    ``failure_class`` is the scheduler's classification of *why* a terminal
    failure happened (:class:`FailureClass`), when known — ``tpx status``
    then shows ``FAILED (preemption)`` instead of a bare FAILED.
    """

    state: AppState
    num_restarts: int = 0
    msg: str = ""
    structured_error_msg: str = NONE
    ui_url: Optional[str] = None
    roles: list[RoleStatus] = field(default_factory=list)
    failure_class: Optional[FailureClass] = None

    def is_terminal(self) -> bool:
        return is_terminal(self.state)

    def _state_str(self) -> str:
        """State plus failure classification when known: ``FAILED (preemption)``."""
        if self.failure_class is not None and self.state in (
            AppState.FAILED,
            AppState.PREEMPTED,
        ):
            return f"{self.state} ({self.failure_class.value.lower()})"
        return str(self.state)

    def raise_for_status(self) -> None:
        if self.state != AppState.SUCCEEDED:
            raise AppStatusError(self, f"job did not succeed: {self}")

    def _error_details(self) -> str:
        if self.structured_error_msg == NONE:
            return ""
        try:
            err = json.loads(self.structured_error_msg)
        except json.JSONDecodeError:
            return self.structured_error_msg
        if not isinstance(err, dict):  # user code may write arbitrary JSON
            return self.structured_error_msg
        msg = err.get("message", {})
        if isinstance(msg, str):
            return msg
        ext = msg.get("extraInfo", {})
        ts = ext.get("timestamp")
        when = (
            datetime.fromtimestamp(int(ts)).isoformat() if ts else "<unknown time>"
        )
        return (
            f"{msg.get('message', '')}\n"
            f"  exitcode: {err.get('exitcode', '<n/a>')}\n"
            f"  hostname: {err.get('hostname', '<n/a>')}\n"
            f"  timestamp: {when}\n"
            f"  python_traceback: {ext.get('py_callstack', '<n/a>')}"
        )

    def format(self, colored: bool = False) -> str:
        def paint(state: AppState) -> str:
            if not colored:
                return str(state)
            from torchx_tpu.util.colors import colored as c, state_color

            return c(state.name, state_color(state.name))

        top = paint(self.state)
        if self.failure_class is not None and self.state in (
            AppState.FAILED,
            AppState.PREEMPTED,
        ):
            top = f"{top} ({self.failure_class.value.lower()})"
        lines = [
            f"AppStatus:",
            f"  state: {top}",
            f"  num_restarts: {self.num_restarts}",
        ]
        if self.msg:
            lines.append(f"  msg: {self.msg}")
        if self.ui_url:
            lines.append(f"  ui_url: {self.ui_url}")
        details = self._error_details()
        if details:
            lines.append("  error:")
            lines.extend("    " + ln for ln in details.splitlines())
        for rs in self.roles:
            lines.append(f"  role: {rs.role}")
            for r in rs.replicas:
                host = f" on {r.hostname}" if r.hostname else ""
                lines.append(f"    [{r.id}] {paint(r.state)}{host}")
        return "\n".join(lines)

    def __str__(self) -> str:
        return (
            f"AppStatus(state={self._state_str()},"
            f" num_restarts={self.num_restarts}, msg={self.msg!r})"
        )


class AppStatusError(Exception):
    def __init__(self, status: AppStatus, message: str) -> None:
        super().__init__(f"{message}\n{status.format()}")
        self.status = status


# =========================================================================
# Dry-run info
# =========================================================================

T = TypeVar("T")


@dataclass
class AppDryRunInfo(Generic[T]):
    """The fully materialized scheduler request, pre-submission.

    This is the single most important testability hook in the design
    (reference analog: schedulers/api.py:410-426): ``submit_dryrun`` returns
    the complete backend payload (Popen argv / sbatch script / JobSet dict)
    without submitting, so tests assert on it with no cluster.
    """

    request: T
    fmt: Callable[[T], str] = str
    # filled in by Scheduler.submit_dryrun:
    _app: Optional[AppDef] = None
    _cfg: Optional[Mapping[str, Any]] = None
    _scheduler: Optional[str] = None

    def __str__(self) -> str:
        return self.fmt(self.request)


# =========================================================================
# runopts — typed scheduler run-config schema
# =========================================================================

CfgVal = Union[str, int, float, bool, list[str], dict[str, str], None]


class InvalidRunConfigException(Exception):
    def __init__(self, reason: str, cfg_key: str, runopts_: "runopts") -> None:
        super().__init__(f"{reason}. Available options:\n{runopts_}")
        self.cfg_key = cfg_key


@dataclass
class runopt:
    default: CfgVal
    opt_type: type
    is_required: bool
    help: str


# (schema-identity, key) pairs already warned about as unknown-
# passthrough: warn once per key PER SCHEMA, not per process — a typo'd
# key on scheduler B must still warn after scheduler A warned about its
# own key of the same name (advisor r4). Schema identity is the frozen
# set of declared opt names, NOT id(self): run_opts() builds a fresh
# runopts per call, so instance identity would re-warn on every submit
# (and GC'd-id reuse would falsely suppress).
_warned_unknown_opts: set[tuple[frozenset, str]] = set()


class runopts:
    """Schema + validator for per-scheduler run configs.

    Reference analog: torchx/specs/api.py:838-1154 (runopts container with
    resolve() validation, string/JSON parsing, camelCase aliasing, merge).
    """

    def __init__(self) -> None:
        self._opts: dict[str, runopt] = {}

    def add(
        self,
        cfg_key: str,
        type_: type,
        help: str,
        default: CfgVal = None,
        required: bool = False,
    ) -> None:
        if required and default is not None:
            raise ValueError(f"required option {cfg_key} must not have a default")
        self._opts[cfg_key] = runopt(default, type_, required, help)

    def get(self, key: str) -> Optional[runopt]:
        return self._opts.get(key)

    def __iter__(self) -> Iterator[tuple[str, runopt]]:
        return iter(self._opts.items())

    def __or__(self, other: "runopts") -> "runopts":
        merged = runopts()
        merged._opts = {**self._opts, **other._opts}
        return merged

    @staticmethod
    def canonical(key: str) -> str:
        """camelCase -> snake_case aliasing so ``imageRepo`` finds ``image_repo``."""
        return re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", key).lower()

    def resolve(self, cfg: Mapping[str, CfgVal]) -> dict[str, CfgVal]:
        """Validate + fill defaults. Unknown keys warn-pass-through (so
        plugins can piggyback), wrong types and missing-required raise."""
        resolved: dict[str, CfgVal] = {}
        seen = set()
        for key, val in cfg.items():
            ckey = key if key in self._opts else self.canonical(key)
            opt = self._opts.get(ckey)
            if opt is None:
                # the passthrough exists for plugin/forward compat, so a
                # legitimate plugin key must not warn on every submit:
                # warn once per key per schema (fresh runopts instances of
                # the same schema share warned-ness; see module note)
                schema_id = frozenset(self._opts)
                if (schema_id, key) not in _warned_unknown_opts:
                    _warned_unknown_opts.add((schema_id, key))
                    warnings.warn(
                        f"unknown runopt {key!r} passed through unvalidated"
                        f" (known: {sorted(self._opts)})",
                        stacklevel=2,
                    )
                resolved[key] = val  # pass through for forward/plugin compat
                continue
            seen.add(ckey)
            if val is None:
                resolved[ckey] = opt.default
                continue
            val = self._coerce(ckey, val, opt)
            resolved[ckey] = val
        for key, opt in self._opts.items():
            if key in seen:
                continue
            if opt.is_required:
                raise InvalidRunConfigException(
                    f"missing required option: {key}", key, self
                )
            resolved[key] = opt.default
        return resolved

    def _coerce(self, key: str, val: CfgVal, opt: runopt) -> CfgVal:
        t = opt.opt_type
        if isinstance(val, str) and t is not str:
            return _decode_cfg_str(val, t, key, self)
        if t is float and isinstance(val, int) and not isinstance(val, bool):
            return float(val)
        if not isinstance(val, t):
            raise InvalidRunConfigException(
                f"option {key} expected {t.__name__},"
                f" got {type(val).__name__} ({val!r})",
                key,
                self,
            )
        return val

    def cfg_from_str(self, cfg_str: str) -> dict[str, CfgVal]:
        """Parse ``k1=v1,k2=v2;k3=v3`` (both ``,`` and ``;`` separate pairs;
        a list-typed value uses ``,`` within — parse is type-directed)."""
        cfg: dict[str, CfgVal] = {}
        if not cfg_str.strip():
            return cfg
        for pair in re.split(r"[;,]", cfg_str):
            pair = pair.strip()
            if not pair:
                continue
            if "=" not in pair:
                # continuation of a previous list/dict value (the value itself
                # contained commas, which also separate cfg pairs)
                last = next(reversed(cfg), None)
                if last is not None and isinstance(cfg[last], list):
                    cfg[last].append(pair)  # type: ignore[union-attr]
                    continue
                if last is not None and isinstance(cfg[last], dict) and ":" in pair:
                    k, _, v = pair.partition(":")
                    cfg[last][k] = v  # type: ignore[index]
                    continue
                raise InvalidRunConfigException(
                    f"malformed cfg pair {pair!r} (expected key=value)", pair, self
                )
            key, _, val = pair.partition("=")
            key = key.strip()
            ckey = key if key in self._opts else self.canonical(key)
            opt = self._opts.get(ckey)
            if opt is not None and opt.opt_type is list:
                cfg[ckey] = val.split(",") if val else []
            elif opt is not None:
                cfg[ckey] = _decode_cfg_str(val, opt.opt_type, ckey, self)
            else:
                cfg[key] = val
        return cfg

    def cfg_from_json_repr(self, json_repr: str) -> dict[str, CfgVal]:
        return {k: v for k, v in json.loads(json_repr).items()}

    def __repr__(self) -> str:
        lines = []
        for key, opt in self._opts.items():
            req = "required" if opt.is_required else f"default: {opt.default!r}"
            lines.append(f"    {key} ({opt.opt_type.__name__}, {req}): {opt.help}")
        return "\n".join(lines) or "    <no options>"

    __str__ = __repr__


def _decode_cfg_str(val: str, t: type, key: str, opts: runopts) -> CfgVal:
    try:
        if t is bool:
            low = val.strip().lower()
            if low in ("true", "1", "yes", "on"):
                return True
            if low in ("false", "0", "no", "off"):
                return False
            raise ValueError(f"not a bool: {val!r}")
        if t is int:
            return int(val)
        if t is float:
            return float(val)
        if t is list:
            return val.split(",") if val else []
        if t is dict:
            return dict(p.split(":", 1) for p in val.split(",") if p)
        return val
    except (ValueError, TypeError) as e:
        raise InvalidRunConfigException(
            f"option {key} could not parse {val!r} as {t.__name__}: {e}", key, opts
        ) from e


# =========================================================================
# App handles
# =========================================================================

AppHandle = str

_HANDLE_RE = re.compile(
    r"^(?P<scheduler>[a-z_\-0-9]+)://(?P<session>[^/]*)/(?P<app_id>.+)$"
)


class MalformedAppHandleException(Exception):
    def __init__(self, app_handle: str) -> None:
        super().__init__(
            f"malformed app handle: {app_handle!r}"
            " (expected scheduler://[session]/app_id)"
        )


def make_app_handle(scheduler_backend: str, session_name: str, app_id: str) -> AppHandle:
    return f"{scheduler_backend}://{session_name}/{app_id}"


def parse_app_handle(app_handle: AppHandle) -> tuple[str, str, str]:
    """-> (scheduler_backend, session_name, app_id)"""
    m = _HANDLE_RE.match(app_handle)
    if not m:
        raise MalformedAppHandleException(app_handle)
    return m.group("scheduler"), m.group("session"), m.group("app_id")


# =========================================================================
# Structured error files (in-job side writes, client side reads)
# =========================================================================


def make_structured_error(message: str, exitcode: int = 1, hostname: str = "") -> str:
    """JSON error payload written to $TPX_ERROR_FILE by failing replicas;
    format mirrors the torchelastic error file the reference consumes
    (specs/api.py:689-719)."""
    import socket
    import time

    return json.dumps(
        {
            "message": {
                "message": message,
                "extraInfo": {"timestamp": int(time.time()), "py_callstack": ""},
            },
            "exitcode": exitcode,
            "hostname": hostname or socket.gethostname(),
        }
    )
