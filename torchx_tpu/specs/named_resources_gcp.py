"""Named resources for heterogeneous GCP fleets: GPU node pools and GCE
machine types.

The GCP analog of the reference's AWS instance-type catalog
(torchx/specs/named_resources_aws.py:1-631, which models EC2 shapes with
GPU counts and EFA device plumbing). TPU slices live in their own catalog
(:mod:`named_resources_tpu`); this module covers the *other* pools of a
mixed cluster:

* **GPU shapes** — ``Resource.devices["nvidia.com/gpu"]`` carries the GPU
  count (the k8s resource limit), ``capabilities["gke.accelerator"]``
  carries the GKE node-pool accelerator label
  (``cloud.google.com/gke-accelerator``), and
  ``capabilities["gce.machine_type"]`` the backing instance type. The GKE
  backend turns these into limits + node selectors + the GPU taint
  toleration; the docker backend maps the devices dict to ``/dev/nvidia*``
  mounts (schedulers/devices.py).
* **GCE machine types** — plain CPU shapes that pin
  ``node.kubernetes.io/instance-type`` on GKE and ``machineType`` on
  gcp_batch/vertex.

Memory carries the same allocatable tax as the TPU catalog (MEM_TAX,
reference named_resources_aws.py:48).
"""

from __future__ import annotations

from typing import Callable, Mapping

from torchx_tpu.specs.api import Resource

MEM_TAX = 0.96
GiB = 1024


def _gpu(
    name: str,
    gpus: int,
    accelerator: str,
    machine_type: str,
    cpu: int,
    mem_gb: int,
) -> Callable[[], Resource]:
    def factory() -> Resource:
        return Resource(
            cpu=cpu,
            memMB=int(mem_gb * GiB * MEM_TAX),
            devices={"nvidia.com/gpu": gpus},
            capabilities={
                "gke.accelerator": accelerator,
                "gce.machine_type": machine_type,
            },
        )

    factory.__name__ = name
    return factory


def _machine(name: str, machine_type: str, cpu: int, mem_gb: int) -> Callable[[], Resource]:
    def factory() -> Resource:
        return Resource(
            cpu=cpu,
            memMB=int(mem_gb * GiB * MEM_TAX),
            capabilities={"gce.machine_type": machine_type},
        )

    factory.__name__ = name
    return factory


# GPU node-pool shapes: (gpus, gke accelerator label, machine type, vCPU, GB)
_GPU_SHAPES: dict[str, tuple[int, str, str, int, int]] = {
    # A100 40GB (a2-highgpu): 12 vCPU / 85 GB per GPU
    "gpu_a100_1": (1, "nvidia-tesla-a100", "a2-highgpu-1g", 12, 85),
    "gpu_a100_2": (2, "nvidia-tesla-a100", "a2-highgpu-2g", 24, 170),
    "gpu_a100_4": (4, "nvidia-tesla-a100", "a2-highgpu-4g", 48, 340),
    "gpu_a100_8": (8, "nvidia-tesla-a100", "a2-highgpu-8g", 96, 680),
    # A100 80GB (a2-ultragpu)
    "gpu_a100_80gb_1": (1, "nvidia-a100-80gb", "a2-ultragpu-1g", 12, 170),
    "gpu_a100_80gb_8": (8, "nvidia-a100-80gb", "a2-ultragpu-8g", 96, 1360),
    # H100 80GB (a3-highgpu): sold as whole 8-GPU hosts
    "gpu_h100_8": (8, "nvidia-h100-80gb", "a3-highgpu-8g", 208, 1872),
    # L4 (g2-standard): 1-8 GPUs
    "gpu_l4_1": (1, "nvidia-l4", "g2-standard-12", 12, 48),
    "gpu_l4_2": (2, "nvidia-l4", "g2-standard-24", 24, 96),
    "gpu_l4_4": (4, "nvidia-l4", "g2-standard-48", 48, 192),
    "gpu_l4_8": (8, "nvidia-l4", "g2-standard-96", 96, 384),
    # T4 / V100 legacy pools (attachable to n1)
    "gpu_t4_1": (1, "nvidia-tesla-t4", "n1-standard-8", 8, 30),
    "gpu_t4_4": (4, "nvidia-tesla-t4", "n1-standard-32", 32, 120),
    "gpu_v100_1": (1, "nvidia-tesla-v100", "n1-standard-8", 8, 30),
    "gpu_v100_8": (8, "nvidia-tesla-v100", "n1-standard-96", 96, 360),
}

# GCE machine types for CPU roles: (machine type, vCPU, GB)
_MACHINE_SHAPES: dict[str, tuple[str, int, int]] = {
    "gce_e2_standard_4": ("e2-standard-4", 4, 16),
    "gce_e2_standard_8": ("e2-standard-8", 8, 32),
    "gce_n2_standard_8": ("n2-standard-8", 8, 32),
    "gce_n2_standard_16": ("n2-standard-16", 16, 64),
    "gce_n2_standard_32": ("n2-standard-32", 32, 128),
    "gce_c3_standard_22": ("c3-standard-22", 22, 88),
    "gce_c3_standard_44": ("c3-standard-44", 44, 176),
    "gce_n2_highmem_16": ("n2-highmem-16", 16, 128),
    "gce_n2_highmem_32": ("n2-highmem-32", 32, 256),
}


def named_resources_gcp() -> Mapping[str, Callable[[], Resource]]:
    out: dict[str, Callable[[], Resource]] = {}
    for name, (gpus, accel, machine, cpu, mem) in _GPU_SHAPES.items():
        out[name] = _gpu(name, gpus, accel, machine, cpu, mem)
    for name, (machine, cpu, mem) in _MACHINE_SHAPES.items():
        out[name] = _machine(name, machine, cpu, mem)
        out[machine] = out[name]  # raw GCE naming ("n2-standard-8") too
    return out
