"""AppDef <-> plain-dict (JSON) serialization.

Powers ``tpx run --stdin`` (reference analog: JSON job-spec mode,
cli/cmd_run.py:366-399) and programmatic job submission from non-Python
clients: an AppDef round-trips through a stable JSON shape. Also home of
the :class:`~torchx_tpu.supervisor.policy.SupervisorPolicy` round-trip
backing ``tpx supervise --policy policy.json``.
"""

from __future__ import annotations

from typing import Any, Mapping

from torchx_tpu.specs.api import (
    AppDef,
    BindMount,
    DeviceMount,
    Resource,
    RetryPolicy,
    Role,
    TpuSlice,
    VolumeMount,
    Workspace,
)


def appdef_to_dict(app: AppDef) -> dict[str, Any]:
    return {
        "name": app.name,
        "metadata": dict(app.metadata),
        "roles": [
            {
                "name": r.name,
                "image": r.image,
                "entrypoint": r.entrypoint,
                "args": list(r.args),
                "env": dict(r.env),
                "num_replicas": r.num_replicas,
                "min_replicas": r.min_replicas,
                "max_retries": r.max_retries,
                "retry_policy": r.retry_policy.value,
                "port_map": dict(r.port_map),
                "metadata": dict(r.metadata),
                "resource": {
                    "cpu": r.resource.cpu,
                    "memMB": r.resource.memMB,
                    "tpu": (
                        {
                            "accelerator": r.resource.tpu.accelerator,
                            "chips": r.resource.tpu.chips,
                            "topology": r.resource.tpu.topology,
                        }
                        if r.resource.tpu
                        else None
                    ),
                    "capabilities": dict(r.resource.capabilities),
                    "devices": dict(r.resource.devices),
                    "tags": dict(r.resource.tags),
                },
                "mounts": [_mount_to_dict(m) for m in r.mounts],
                "workspace": (
                    dict(r.workspace.projects) if r.workspace else None
                ),
            }
            for r in app.roles
        ],
    }


def _mount_to_dict(m: Any) -> dict[str, Any]:
    if isinstance(m, BindMount):
        return {"type": "bind", "src": m.src_path, "dst": m.dst_path, "read_only": m.read_only}
    if isinstance(m, VolumeMount):
        return {"type": "volume", "src": m.src, "dst": m.dst_path, "read_only": m.read_only}
    if isinstance(m, DeviceMount):
        return {"type": "device", "src": m.src_path, "dst": m.dst_path, "permissions": m.permissions}
    raise ValueError(f"unknown mount type: {m!r}")


def _mount_from_dict(d: Mapping[str, Any]) -> Any:
    t = d.get("type")
    if t == "bind":
        return BindMount(src_path=d["src"], dst_path=d["dst"], read_only=bool(d.get("read_only")))
    if t == "volume":
        return VolumeMount(src=d["src"], dst_path=d["dst"], read_only=bool(d.get("read_only")))
    if t == "device":
        return DeviceMount(src_path=d["src"], dst_path=d.get("dst", d["src"]), permissions=d.get("permissions", "rwm"))
    raise ValueError(f"unknown mount type in {d!r}")


def appdef_from_dict(data: Mapping[str, Any]) -> AppDef:
    roles = []
    for rd in data.get("roles", []):
        res = rd.get("resource") or {}
        tpu_d = res.get("tpu")
        resource = Resource(
            cpu=res.get("cpu", -1),
            memMB=res.get("memMB", -1),
            tpu=(
                TpuSlice(
                    accelerator=tpu_d["accelerator"],
                    chips=int(tpu_d["chips"]),
                    topology=tpu_d.get("topology"),
                )
                if tpu_d
                else None
            ),
            capabilities=dict(res.get("capabilities") or {}),
            devices=dict(res.get("devices") or {}),
            tags=dict(res.get("tags") or {}),
        )
        roles.append(
            Role(
                name=rd["name"],
                image=rd.get("image", ""),
                entrypoint=rd.get("entrypoint", ""),
                args=list(rd.get("args") or []),
                env=dict(rd.get("env") or {}),
                num_replicas=int(rd.get("num_replicas", 1)),
                min_replicas=rd.get("min_replicas"),
                max_retries=int(rd.get("max_retries", 0)),
                retry_policy=RetryPolicy(rd.get("retry_policy", "APPLICATION")),
                port_map={k: int(v) for k, v in (rd.get("port_map") or {}).items()},
                metadata=dict(rd.get("metadata") or {}),
                resource=resource,
                mounts=[_mount_from_dict(m) for m in (rd.get("mounts") or [])],
                workspace=(
                    Workspace(projects=dict(rd["workspace"]))
                    if rd.get("workspace")
                    else None
                ),
            )
        )
    if not roles:
        raise ValueError("job spec has no roles")
    return AppDef(
        name=data.get("name", "app"),
        roles=roles,
        metadata=dict(data.get("metadata") or {}),
    )


# =========================================================================
# SupervisorPolicy <-> dict (supervisor imported lazily: specs is the
# foundation layer and must not depend on the supervisor at import time)
# =========================================================================


def supervisor_policy_to_dict(policy: Any) -> dict[str, Any]:
    """-> a JSON-safe dict of every :class:`SupervisorPolicy` field."""
    from dataclasses import asdict

    return asdict(policy)


def supervisor_policy_from_dict(data: Mapping[str, Any]) -> Any:
    """Build a :class:`SupervisorPolicy` from a (possibly partial) dict;
    unknown keys raise so a typo'd policy file fails loudly instead of
    silently running with defaults."""
    from dataclasses import fields

    from torchx_tpu.supervisor.policy import SupervisorPolicy

    known = {f.name for f in fields(SupervisorPolicy)}
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"unknown supervisor policy keys {sorted(unknown)};"
            f" valid keys: {sorted(known)}"
        )
    return SupervisorPolicy(**dict(data))
