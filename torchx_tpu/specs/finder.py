"""Component finder: resolve a component name to a function.

Reference analog: torchx/specs/finder.py (501 LoC). Resolution order:

1. entry-point-registered component modules (``[tpx.components]`` group) —
   organizations replace the builtin namespace wholesale,
2. builtins: recursive walk of ``torchx_tpu.components`` modules,
3. custom file components: ``path/to/file.py:fn_name``.

Every resolved fn is AST-linted (file_linter) so broken components fail
with line-anchored errors rather than deep argparse tracebacks.
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
from dataclasses import dataclass, field
from types import ModuleType
from typing import Callable, Optional

from torchx_tpu.specs.api import AppDef
from torchx_tpu.specs.file_linter import get_fn_docstring, validate

COMPONENT_ENTRYPOINT_GROUP = "tpx.components"


class ComponentNotFoundException(Exception):
    pass


class ComponentValidationException(Exception):
    pass


@dataclass
class _Component:
    name: str  # canonical "module.fn" or "file.py:fn"
    description: str
    fn_name: str
    fn: Callable[..., AppDef]
    validation_errors: list[str] = field(default_factory=list)


# =========================================================================
# Builtins walk
# =========================================================================


def _base_modules() -> list[ModuleType]:
    mods: list[ModuleType] = []
    try:
        from importlib.metadata import entry_points

        for ep in entry_points(group=COMPONENT_ENTRYPOINT_GROUP):
            loaded = ep.load()
            if isinstance(loaded, ModuleType):
                mods.append(loaded)
    except Exception:  # noqa: BLE001
        pass
    if not mods:
        import torchx_tpu.components as builtin

        mods.append(builtin)
    return mods


def _walk_module(module: ModuleType) -> list[ModuleType]:
    """module + all submodules (recursive)."""
    out = [module]
    if hasattr(module, "__path__"):
        for info in pkgutil.walk_packages(module.__path__, module.__name__ + "."):
            if ".test" in info.name or info.name.endswith("_test"):
                continue
            try:
                out.append(importlib.import_module(info.name))
            except ImportError:
                continue
    return out


def _is_component_fn(fn: object) -> bool:
    if not inspect.isfunction(fn):
        return False
    if fn.__name__.startswith("_"):
        return False
    sig = inspect.signature(fn)
    return sig.return_annotation in (AppDef, "AppDef", "specs.AppDef")


_components_cache: Optional[dict[str, _Component]] = None


def get_components(invalidate_cache: bool = False) -> dict[str, _Component]:
    """All discoverable builtin components, keyed by short name
    (``dist.spmd``, ``utils.echo``)."""
    global _components_cache
    if _components_cache is not None and not invalidate_cache:
        return _components_cache
    out: dict[str, _Component] = {}
    for base in _base_modules():
        base_name = base.__name__
        for module in _walk_module(base):
            rel = module.__name__[len(base_name) :].lstrip(".")
            for fn_name, fn in inspect.getmembers(module, _is_component_fn):
                if fn.__module__ != module.__name__:
                    continue  # skip re-exports
                name = f"{rel}.{fn_name}" if rel else fn_name
                summary, _ = get_fn_docstring(fn)
                out[name] = _Component(
                    name=name,
                    description=summary,
                    fn_name=fn_name,
                    fn=fn,
                    validation_errors=_validate_fn(fn),
                )
    _components_cache = out
    return out


def _validate_fn(fn: Callable) -> list[str]:
    try:
        path = inspect.getfile(fn)
    except TypeError:
        return []
    errors = validate(path, fn.__name__)
    return [f"{e.line}:{e.char} {e.description}" for e in errors]


# =========================================================================
# Custom file components
# =========================================================================


def _load_custom_component(path: str, fn_name: str) -> _Component:
    if not os.path.isfile(path):
        raise ComponentNotFoundException(f"component file not found: {path}")
    errors = validate(path, fn_name)
    spec = importlib.util.spec_from_file_location(
        f"tpx_custom_component_{os.path.basename(path).removesuffix('.py')}", path
    )
    assert spec is not None and spec.loader is not None
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    fn = getattr(module, fn_name, None)
    if fn is None:
        raise ComponentNotFoundException(f"{fn_name!r} not found in {path}")
    summary, _ = get_fn_docstring(fn)
    return _Component(
        name=f"{path}:{fn_name}",
        description=summary,
        fn_name=fn_name,
        fn=fn,
        validation_errors=[f"{e.line}:{e.char} {e.description}" for e in errors],
    )


# =========================================================================
# Public resolution API
# =========================================================================


def get_component(name: str) -> _Component:
    """Resolve ``dist.spmd`` (builtin/entrypoint) or ``file.py:fn`` (custom)."""
    if ":" in name:
        path, _, fn_name = name.rpartition(":")
        component = _load_custom_component(path, fn_name)
    else:
        components = get_components()
        if name not in components:
            raise ComponentNotFoundException(
                f"component {name!r} not found; available: {sorted(components)}"
            )
        component = components[name]
    if component.validation_errors:
        raise ComponentValidationException(
            f"component {name} failed validation:\n  "
            + "\n  ".join(component.validation_errors)
        )
    return component


def get_builtin_source(name: str) -> str:
    """Source code of a builtin component fn (``tpx builtins --print``;
    reference finder.py:466-501)."""
    component = get_component(name)
    return inspect.getsource(component.fn)
