"""Component materialization: fn signature -> argparse -> AppDef.

Reference analog: torchx/specs/builders.py (376 LoC). Given a component
function, build an argparse parser from its signature + docstring, decode
the typed values, call the function, and return the AppDef.
"""

from __future__ import annotations

import argparse
import inspect
from typing import Any, Callable, Mapping, Optional

from torchx_tpu.specs.api import AppDef
from torchx_tpu.specs.file_linter import get_fn_docstring
from torchx_tpu.util.types import decode


class ComponentArgumentError(Exception):
    pass


class _NoExitArgumentParser(argparse.ArgumentParser):
    """argparse that raises instead of sys.exit so library callers survive."""

    def error(self, message: str) -> None:  # type: ignore[override]
        raise ComponentArgumentError(f"{self.prog}: {message}\n{self.format_usage()}")


class ComponentHelpFormatter(argparse.HelpFormatter):
    """Marks required flags in help (reference TorchXArgumentHelpFormatter,
    file_linter.py:35-57)."""

    def _get_help_string(self, action: argparse.Action) -> str:
        help_str = action.help or ""
        if action.required:
            return f"{help_str} (required)"
        if action.default is not None and action.default != argparse.SUPPRESS:
            return f"{help_str} (default: {action.default})"
        return help_str


def build_parser(
    fn: Callable[..., AppDef],
    prog: Optional[str] = None,
) -> tuple[argparse.ArgumentParser, dict[str, inspect.Parameter]]:
    """Create the parser for a component fn. VAR_POSITIONAL params become
    trailing positional args (the common ``*script_args`` pattern)."""
    summary, arg_help = get_fn_docstring(fn)
    parser = _NoExitArgumentParser(
        prog=prog or fn.__name__,
        description=summary,
        formatter_class=ComponentHelpFormatter,
        # '-h' belongs to the component ('--help' still works): a component
        # may legitimately define an '-h' named-resource flag
        # (reference builders.py:52-63)
        add_help=False,
    )
    parser.add_argument(
        "--help", action="help", default=argparse.SUPPRESS, help="show this help"
    )
    params: dict[str, inspect.Parameter] = {}
    try:
        sig = inspect.signature(fn, eval_str=True)
    except (NameError, TypeError):
        sig = inspect.signature(fn)
    for name, param in sig.parameters.items():
        params[name] = param
        help_text = arg_help.get(name, "")
        if param.kind == inspect.Parameter.VAR_POSITIONAL:
            parser.add_argument(
                name, nargs=argparse.REMAINDER, help=help_text, default=[]
            )
            continue
        if param.kind == inspect.Parameter.VAR_KEYWORD:
            raise ComponentArgumentError(
                f"component {fn.__name__} uses **{name}; not supported"
            )
        flag = f"--{name}"
        aliases = [flag]
        if len(name) == 1:
            aliases = [f"-{name}", flag]
        if param.default is inspect.Parameter.empty:
            parser.add_argument(*aliases, required=True, help=help_text, type=str)
        else:
            default = param.default
            parser.add_argument(
                *aliases, required=False, help=help_text, type=str, default=default
            )
    return parser, params


def materialize_appdef(
    fn: Callable[..., AppDef],
    cli_args: list[str],
    defaults: Optional[Mapping[str, str]] = None,
) -> AppDef:
    """Parse CLI-style args against the component signature and invoke it.

    ``defaults`` (from .tpxconfig ``[component:<name>]`` sections) fill in
    any flag the CLI didn't pass.
    """
    if defaults:
        cli_args = _apply_defaults(cli_args, defaults)
    parser, params = build_parser(fn)
    parsed = parser.parse_args(cli_args)

    call_args: list[Any] = []
    call_kwargs: dict[str, Any] = {}
    for name, param in params.items():
        value = getattr(parsed, name)
        if param.kind == inspect.Parameter.VAR_POSITIONAL:
            # REMAINDER may capture a leading "--" separator; drop it
            rest = list(value)
            if rest and rest[0] == "--":
                rest = rest[1:]
            ann = (
                param.annotation
                if param.annotation is not inspect.Parameter.empty
                else str
            )
            call_args.extend(decode(v, ann) for v in rest)
            continue
        decoded = (
            decode(value, param.annotation) if isinstance(value, str) else value
        )
        call_kwargs[name] = decoded

    appdef = fn(*call_args, **call_kwargs)
    if not isinstance(appdef, AppDef):
        raise ComponentArgumentError(
            f"component {fn.__name__} returned {type(appdef).__name__}, expected AppDef"
        )
    return appdef


def _apply_defaults(cli_args: list[str], defaults: Mapping[str, str]) -> list[str]:
    """Prepend --k v pairs for defaults not explicitly passed. Must come
    before any VAR_POSITIONAL remainder, hence prepend."""
    present = set()
    for a in cli_args:
        if a.startswith("--"):
            present.add(a[2:].split("=", 1)[0])
        if a == "--":
            break
    extra: list[str] = []
    for k, v in defaults.items():
        if k not in present:
            extra.extend([f"--{k}", v])
    return extra + cli_args


def component_args_from_str(args_str: str) -> list[str]:
    """Split a shell-ish component arg string (reference builders.py:155)."""
    import shlex

    return shlex.split(args_str)
