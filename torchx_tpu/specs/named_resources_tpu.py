"""Named resources for Cloud TPU slices.

The TPU analog of the reference's AWS instance-type catalog
(torchx/specs/named_resources_aws.py, 631 LoC): maps human-readable slice
names ("tpu_v5p_32", or the raw accelerator type "v5p-32") to fully
specified :class:`Resource` objects — host CPU/RAM per TPU-VM worker plus
the :class:`TpuSlice`.

Host shapes below are the documented Cloud TPU VM machine shapes
(per-worker):

==========  ==================  ====== =======
generation  machine type        vCPU   RAM GB
==========  ==================  ====== =======
v2/v3       n1-based            96     340
v4          ct4p-hightpu-4t     240    400
v5e         ct5lp-hightpu-*t    24-224 48-448
v5p         ct5p-hightpu-4t     208    448
v6e         ct6e-standard-*t    44-180 176-720
==========  ==================  ====== =======

A small "RAM tax" (:data:`MEM_TAX`) is applied the way the reference taxes
AWS memory (named_resources_aws.py:48) so requests fit under node allocatable.
"""

from __future__ import annotations

from typing import Callable, Mapping

from torchx_tpu.specs.api import Resource, TpuSlice

MEM_TAX = 0.96
GiB = 1024

# per-host (cpu, memMB) by generation
_HOST_SHAPES: dict[str, tuple[int, int]] = {
    "v2": (96, int(340 * GiB * MEM_TAX)),
    "v3": (96, int(340 * GiB * MEM_TAX)),
    "v4": (240, int(400 * GiB * MEM_TAX)),
    "v5e": (112, int(192 * GiB * MEM_TAX)),
    "v5p": (208, int(448 * GiB * MEM_TAX)),
    "v6e": (180, int(720 * GiB * MEM_TAX)),
    "v7x": (224, int(960 * GiB * MEM_TAX)),
}

# The slice sizes we pre-register by name. Arbitrary sizes remain reachable
# through tpu_slice("v5e-123")-style dynamic lookup below.
_CATALOG_CHIPS: dict[str, list[int]] = {
    "v4": [4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096],
    "v5e": [1, 4, 8, 16, 32, 64, 128, 256],
    "v5p": [4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4608],
    "v6e": [1, 4, 8, 16, 32, 64, 128, 256],
}


def tpu_slice(accelerator_type: str, topology: str | None = None) -> Resource:
    """Resource for an arbitrary accelerator-type string, e.g. "v5p-32"."""
    sl = TpuSlice.from_type(accelerator_type, topology=topology)
    cpu, mem = _HOST_SHAPES[sl.accelerator]
    return Resource(
        cpu=cpu,
        memMB=mem,
        tpu=sl,
        capabilities={"tpu.accelerator_type": sl.accelerator_type},
    )


def _mk(gen: str, chips: int) -> Callable[[], Resource]:
    def factory() -> Resource:
        sl = TpuSlice(accelerator=gen, chips=chips)
        cpu, mem = _HOST_SHAPES[gen]
        return Resource(
            cpu=cpu,
            memMB=mem,
            tpu=sl,
            capabilities={"tpu.accelerator_type": sl.accelerator_type},
        )

    factory.__name__ = f"tpu_{gen}_{chips}"
    return factory


def named_resources_tpu() -> Mapping[str, Callable[[], Resource]]:
    """Registry: both pythonic names (tpu_v5p_32 = 32 chips) and raw
    accelerator-type names (v5p-64 = Cloud naming, 32 chips) resolve."""
    out: dict[str, Callable[[], Resource]] = {}
    for gen, sizes in _CATALOG_CHIPS.items():
        for chips in sizes:
            f = _mk(gen, chips)
            out[f"tpu_{gen}_{chips}"] = f  # chips-count naming
            accel = TpuSlice(accelerator=gen, chips=chips).accelerator_type
            out[accel] = f  # cloud naming ("v5p-64", "v5litepod-8")
    return out
