"""Overlays: deep-merge patches applied to raw scheduler request objects.

Reference analog: torchx/specs/overlays.py (653 LoC). An overlay is a dict
stored in ``role.metadata["overlays"][<scheduler>]`` that the scheduler
deep-merges onto the materialized request at dryrun time (e.g. patching an
arbitrary field of the generated JobSet/Pod spec that the launcher doesn't
model first-class).

Merge semantics per key:

* plain key — recursive strategic merge (dicts merge, scalars replace),
* ``PUT(key)`` — replace the value wholesale (no recursion),
* ``JOIN(key[, merge_key])`` — list merge: items are matched by
  ``merge_key`` (default ``"name"``) and merged; unmatched items append,
* ``DEL(key)`` — remove the key from the target.

Operator keys are encoded as ``"<op>!<key>"`` strings so overlays stay
plain JSON (serializable through .tpxconfig and the CLI).
"""

from __future__ import annotations

import copy
from typing import Any, Mapping, Optional

from torchx_tpu.specs.api import Role

_OP_SEP = "!"
_OPS = ("put", "join", "del")

OVERLAY_METADATA_KEY = "overlays"


def PUT(key: str) -> str:
    """Replace the value at ``key`` wholesale instead of merging."""
    return f"put{_OP_SEP}{key}"


def JOIN(key: str, merge_key: str = "name") -> str:
    """Merge list items by ``merge_key`` instead of replacing the list."""
    return f"join{_OP_SEP}{key}{_OP_SEP}{merge_key}"


def DEL(key: str) -> str:
    """Delete ``key`` from the target."""
    return f"del{_OP_SEP}{key}"


def _parse_key(key: str) -> tuple[str, str, str]:
    """-> (op, plain_key, merge_key)"""
    parts = key.split(_OP_SEP)
    if len(parts) >= 2 and parts[0] in _OPS:
        op = parts[0]
        plain = parts[1]
        merge_key = parts[2] if len(parts) > 2 else "name"
        return op, plain, merge_key
    return "merge", key, "name"


def validate_overlay(overlay: Any, path: str = "$") -> list[str]:
    """Static validation: operator syntax + JSON-representable values."""
    errors: list[str] = []
    if not isinstance(overlay, dict):
        return [f"{path}: overlay must be a dict, got {type(overlay).__name__}"]
    for key, value in overlay.items():
        if not isinstance(key, str):
            errors.append(f"{path}: non-string key {key!r}")
            continue
        op, plain, _ = _parse_key(key)
        if not plain:
            errors.append(f"{path}: operator key {key!r} missing target key")
        if op == "del" and value not in (None, {}, ""):
            errors.append(f"{path}.{plain}: DEL value must be empty/None")
        if isinstance(value, dict):
            errors.extend(validate_overlay(value, f"{path}.{plain}"))
    return errors


def apply_overlay(target: Any, overlay: Mapping[str, Any]) -> Any:
    """Return a new object: overlay strategically merged onto target."""
    target = copy.deepcopy(target)
    return _merge(target, overlay)


def _merge(target: Any, overlay: Mapping[str, Any]) -> Any:
    if not isinstance(target, dict):
        # overlay at a non-dict node replaces it
        return copy.deepcopy({k: v for k, v in overlay.items()})
    for key, value in overlay.items():
        op, plain, merge_key = _parse_key(key)
        if op == "del":
            target.pop(plain, None)
        elif op == "put":
            target[plain] = copy.deepcopy(value)
        elif op == "join":
            target[plain] = _join_lists(target.get(plain), value, merge_key)
        else:  # strategic merge
            existing = target.get(plain)
            if isinstance(existing, dict) and isinstance(value, dict):
                target[plain] = _merge(existing, value)
            else:
                target[plain] = copy.deepcopy(value)
    return target


def _join_lists(existing: Any, patch: Any, merge_key: str) -> list:
    if not isinstance(patch, list):
        raise ValueError(f"JOIN value must be a list, got {type(patch).__name__}")
    out: list = list(copy.deepcopy(existing)) if isinstance(existing, list) else []
    for item in patch:
        if isinstance(item, dict) and merge_key in item:
            match = next(
                (
                    i
                    for i, cur in enumerate(out)
                    if isinstance(cur, dict) and cur.get(merge_key) == item[merge_key]
                ),
                None,
            )
            if match is not None:
                out[match] = _merge(out[match], item)
                continue
        out.append(copy.deepcopy(item))
    return out


# =========================================================================
# Role attachment API
# =========================================================================


def set_overlay(role: Role, scheduler: str, overlay: Mapping[str, Any]) -> None:
    """Attach a validated raw-request patch for ``scheduler`` to the
    role (applied by that backend at dryrun)."""
    errors = validate_overlay(overlay)
    if errors:
        raise ValueError("invalid overlay:\n  " + "\n  ".join(errors))
    role.metadata.setdefault(OVERLAY_METADATA_KEY, {})[scheduler] = dict(overlay)


def get_overlay(role: Role, scheduler: str) -> Optional[dict[str, Any]]:
    """The role's overlay for ``scheduler``, or None."""
    return role.metadata.get(OVERLAY_METADATA_KEY, {}).get(scheduler)
