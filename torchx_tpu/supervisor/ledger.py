"""Crash-safe supervision state: the per-session attempt ledger.

The supervisor loop lives in a client process, and client processes die —
laptops sleep, SSH sessions drop, pods get OOM-killed. Everything the
loop knows (which attempt is live, how many retries each failure class
has consumed, what to resubmit) must therefore be durable *before* it
matters. Each supervised session owns one directory under
``$TPX_SUPERVISOR_DIR`` (default ``~/.torchx_tpu/supervisor``)::

    <root>/<session>/
        meta.json      # scheduler, cfg, AppDef, policy — written once
        ledger.jsonl   # one line per transition, appended as it happens

``meta.json`` holds what a fresh process needs to rebuild the submission
(via :func:`~torchx_tpu.specs.serialize.appdef_from_dict` and the
scheduler's ``materialize_dryrun``); ``ledger.jsonl`` is the transition
history (submitted / resubmitting / finished / ...) that
:meth:`~torchx_tpu.supervisor.api.Supervisor.resume` replays to restore
the attempt and retry counters and find the last live handle. Appends are
line-atomic on POSIX (single small ``write`` on an append-mode fd), so a
crash mid-run costs at most the final line.

All writes are best-effort from the supervisor's point of view: a full
disk degrades resumability, never the run itself.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, Optional

from torchx_tpu import settings
from torchx_tpu.util.times import epoch_usec

META_FILE = "meta.json"
LEDGER_FILE = "ledger.jsonl"


def supervisor_root(root: Optional[str] = None) -> str:
    """The ledger root directory: explicit ``root`` arg, else
    ``$TPX_SUPERVISOR_DIR``, else ``~/.torchx_tpu/supervisor``."""
    return (
        root
        or os.environ.get(settings.ENV_TPX_SUPERVISOR_DIR)
        or os.path.join(os.path.expanduser("~"), ".torchx_tpu", "supervisor")
    )


def list_sessions(root: Optional[str] = None) -> list[str]:
    """Session names with a ``meta.json`` on disk, newest first (by
    meta mtime) — what ``tpx supervise --resume`` can reattach to."""
    base = supervisor_root(root)
    try:
        names = os.listdir(base)
    except OSError:
        return []
    stamped = []
    for name in names:
        meta = os.path.join(base, name, META_FILE)
        try:
            stamped.append((os.path.getmtime(meta), name))
        except OSError:
            continue
    return [name for _, name in sorted(stamped, reverse=True)]


class AttemptLedger:
    """Durable record of one supervised session (see module docstring).

    Constructing the ledger creates nothing; :meth:`write_meta` and
    :meth:`append` create the session directory on first write, and the
    read side (:meth:`read_meta` / :meth:`entries`) works on whatever a
    crashed writer left behind.
    """

    def __init__(self, session: str, root: Optional[str] = None) -> None:
        if not session or "/" in session or session in (".", ".."):
            raise ValueError(f"invalid supervisor session name {session!r}")
        self.session = session
        self.path = os.path.join(supervisor_root(root), session)

    # -- write side (best-effort: never let bookkeeping kill the run) ------

    def write_meta(self, meta: dict[str, Any]) -> None:
        """Persist the session's rebuild recipe (atomic tmp + fsync +
        rename: a reader either sees the whole old doc or the whole new
        one, never a torn meta.json — even through a crash)."""
        try:
            os.makedirs(self.path, exist_ok=True)
            tmp = os.path.join(self.path, META_FILE + ".tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=2, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.path, META_FILE))
        except OSError:
            pass

    def append(
        self, transition: str, app_id: Optional[str], **metadata: object
    ) -> None:
        """Append one transition line; stamped with the wall clock so the
        ledger doubles as a human-readable timeline."""
        entry = {
            "transition": transition,
            "app_id": app_id,
            "time_usec": epoch_usec(),
            **metadata,
        }
        try:
            os.makedirs(self.path, exist_ok=True)
            # one complete line per write on an append-mode fd (atomic on
            # POSIX), fsynced so the transition is durable before the
            # supervisor acts on it — resume must never replay less than
            # what the dead client already did
            with open(os.path.join(self.path, LEDGER_FILE), "a") as f:
                f.write(json.dumps(entry) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except (OSError, TypeError, ValueError):
            pass

    # -- read side (resume) ------------------------------------------------

    def exists(self) -> bool:
        """True when the session has a ``meta.json`` to resume from."""
        return os.path.exists(os.path.join(self.path, META_FILE))

    def read_meta(self) -> dict[str, Any]:
        """The session's rebuild recipe; raises ``FileNotFoundError`` with
        the known sessions listed when there is nothing to resume."""
        try:
            with open(os.path.join(self.path, META_FILE)) as f:
                return json.load(f)
        except FileNotFoundError:
            known = ", ".join(list_sessions(os.path.dirname(self.path))) or "(none)"
            raise FileNotFoundError(
                f"no supervised session {self.session!r} under"
                f" {os.path.dirname(self.path)}; known sessions: {known}"
            ) from None

    def entries(self) -> Iterator[dict[str, Any]]:
        """Transition lines, oldest first; a torn final line (writer died
        mid-append) is skipped rather than fatal."""
        try:
            f = open(os.path.join(self.path, LEDGER_FILE))
        except OSError:
            return
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue
