"""Gang health: hang/straggler/partial-loss detection from heartbeats.

The scheduler's status API answers "does the backend think the job is
running?" — it cannot see a gang wedged inside a collective, a replica
whose host silently died mid-slice, or one straggler holding the other
N-1 replicas hostage. Those failure modes leave status reading RUNNING
forever while no step ever completes.

This module closes that gap from the *client* side, with no new agent on
the workers: training jobs already emit ``job.first_step``/``step.window``
heartbeats into the session's shared ``trace.jsonl`` (see
``examples/train_llama.py``), and may additionally renew small per-replica
liveness leases via :func:`renew_lease`. :class:`GangMonitor` tails both
between status polls and folds them into a :class:`GangVerdict`; the
supervisor turns a ``HANG``/``PARTIAL_LOSS`` verdict into kill + classify
as :attr:`FailureClass.HANG <torchx_tpu.specs.api.FailureClass.HANG>` +
resubmit (optionally onto a reshaped mesh — see
``SupervisorPolicy.elastic_reshape``).

Everything here is jax-free and file-based on purpose: it runs in the
launcher process, works with any scheduler backend, and survives the
supervisor itself crashing (the evidence is durable JSONL, not in-memory
state).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import time
from typing import Callable, Optional

from torchx_tpu import settings
from torchx_tpu.obs import sinks

__all__ = [
    "HEARTBEAT_SPANS",
    "GangState",
    "ReplicaHealth",
    "GangVerdict",
    "GangMonitor",
    "renew_lease",
    "read_leases",
]

#: span names that count as liveness evidence in the trace stream.
HEARTBEAT_SPANS = ("job.first_step", "step.window")

_LEASE_DIR = "leases"


class GangState(str, enum.Enum):
    """What the liveness evidence says about the gang.

    WAITING: no heartbeat/lease seen yet — the job is still compiling or
        warming up; the hang deadline is not armed (a slow first compile
        is indistinguishable from a hang without a first signal). Also
        covers the arming window right after the first evidence, while
        not-yet-seen replicas still have startup-skew grace.
    HEALTHY: every expected replica produced fresh evidence.
    STRAGGLER: all replicas live, but the step spread exceeds the
        configured lag — warn-only, the gang still makes progress.
    PARTIAL_LOSS: some (not all) replicas went stale past the deadline —
        part of the gang is gone while the rest spins in a collective.
    HANG: every replica went stale past the deadline — no progress at
        all while the scheduler still reports RUNNING.
    """

    WAITING = "WAITING"
    HEALTHY = "HEALTHY"
    STRAGGLER = "STRAGGLER"
    PARTIAL_LOSS = "PARTIAL_LOSS"
    HANG = "HANG"

    def __str__(self) -> str:
        return self.value


@dataclasses.dataclass
class ReplicaHealth:
    """Latest liveness evidence for one replica."""

    #: global replica id within the role's gang.
    replica: int
    #: epoch seconds of the freshest heartbeat span seen, 0 if none.
    last_heartbeat: float = 0.0
    #: epoch seconds of the freshest lease renewal seen, 0 if none.
    last_lease: float = 0.0
    #: highest training step the replica reported, -1 if unknown.
    last_step: int = -1

    def last_seen(self) -> float:
        """Freshest evidence from any source (epoch seconds; 0 = never)."""
        return max(self.last_heartbeat, self.last_lease)


@dataclasses.dataclass(frozen=True)
class GangVerdict:
    """One gang-health assessment: state + the evidence behind it."""

    #: the assessment; see :class:`GangState`.
    state: GangState
    #: human-readable one-liner with the numbers behind the verdict.
    detail: str
    #: replicas the gang is supposed to have.
    expected: int
    #: replica ids with fresh evidence.
    live: tuple = ()
    #: replica ids stale past the deadline (or never seen once armed).
    lost: tuple = ()

    @property
    def survivors(self) -> int:
        """How many replicas still show fresh liveness evidence."""
        return len(self.live)

    @property
    def unhealthy(self) -> bool:
        """True for the states the supervisor must act on (kill+retry)."""
        return self.state in (GangState.HANG, GangState.PARTIAL_LOSS)


def _lease_dir(session: Optional[str] = None) -> str:
    return os.path.join(sinks.session_dir(session), _LEASE_DIR)


def renew_lease(
    replica: int, step: int = -1, session: Optional[str] = None
) -> str:
    """Renew a per-replica liveness lease (atomic tiny-JSON write).

    Called from inside the job (alongside the ``step.window`` heartbeat,
    or from a sidecar when the trainer cannot emit spans); the monitor
    treats a lease younger than its TTL as proof of life even when the
    trace stream stalls. Returns the lease file path.
    """
    from torchx_tpu.util.times import epoch_usec

    d = _lease_dir(session)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{int(replica)}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {"replica": int(replica), "step": int(step), "epoch_usec": epoch_usec()},
            f,
        )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_leases(session: Optional[str] = None) -> dict[int, dict]:
    """All current leases for a session, keyed by replica id (torn or
    foreign files are skipped — leases are best-effort evidence)."""
    d = _lease_dir(session)
    out: dict[int, dict] = {}
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
            out[int(rec["replica"])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


class GangMonitor:
    """Tails a session's heartbeats + leases and judges gang health.

    Reads are incremental (byte offset into ``trace.jsonl``) so calling
    :meth:`check` every few seconds stays O(new evidence), not O(run
    length). The monitor is passive — it never writes; acting on a
    verdict (kill, reclassify, resubmit) is the supervisor's job.

    ``clock`` is injectable for tests; it must be comparable with the
    epoch-microsecond stamps heartbeats and leases carry (i.e. epoch
    seconds).

    ``ignore_evidence_before`` (epoch seconds) drops heartbeats and
    leases stamped earlier — the supervisor sets it to the submission
    time of a *resubmitted* attempt so the fresh monitor never judges
    the new gang on its dead predecessor's stale evidence (which would
    read as an instant HANG during warmup/compile, before the new
    attempt's first heartbeat).
    """

    def __init__(
        self,
        expected_replicas: int,
        hang_deadline_s: float,
        *,
        lease_ttl_s: float = 0.0,
        straggler_step_lag: int = 0,
        session: Optional[str] = None,
        trace_file: Optional[str] = None,
        clock: Callable[[], float] = time.time,
        ignore_evidence_before: float = 0.0,
    ) -> None:
        if expected_replicas < 1:
            raise ValueError(
                f"expected_replicas must be >= 1, got {expected_replicas}"
            )
        if hang_deadline_s <= 0:
            raise ValueError(
                f"hang_deadline_s must be > 0, got {hang_deadline_s}"
            )
        self.expected_replicas = expected_replicas
        self.hang_deadline_s = hang_deadline_s
        self.lease_ttl_s = lease_ttl_s or hang_deadline_s
        self.straggler_step_lag = straggler_step_lag
        self.session = session
        self.trace_file = trace_file or sinks.trace_path(session)
        self.clock = clock
        self.ignore_evidence_before = ignore_evidence_before
        self.replicas: dict[int, ReplicaHealth] = {}
        self._offset = 0
        # set by the first check() that sees any evidence: never-seen
        # replicas get a hang_deadline_s grace from this instant before
        # they count as lost (startup skew — replicas flush their first
        # heartbeat seconds apart)
        self._armed_at: Optional[float] = None

    # -- evidence ingestion -------------------------------------------------

    def observe(self) -> None:
        """Fold new trace lines and current leases into the replica map."""
        self._tail_trace()
        now_lease = read_leases(self.session) if self.session is not None else {}
        if not now_lease and self.session is None:
            now_lease = read_leases()
        for rid, rec in now_lease.items():
            ts = float(rec.get("epoch_usec", 0)) / 1e6
            if ts < self.ignore_evidence_before:
                continue  # leftover lease file from a previous attempt
            h = self.replicas.setdefault(rid, ReplicaHealth(replica=rid))
            h.last_lease = max(h.last_lease, ts)
            step = int(rec.get("step", -1))
            h.last_step = max(h.last_step, step)

    def _tail_trace(self) -> None:
        try:
            with open(self.trace_file, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return
        if not chunk:
            return
        # hold back a torn final line; re-read it once the writer finishes
        complete, nl, _rest = chunk.rpartition(b"\n")
        if not nl:
            return
        self._offset += len(complete) + 1
        for raw in complete.split(b"\n"):
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("kind") != "span" or rec.get("name") not in HEARTBEAT_SPANS:
                continue
            ts = float(rec.get("start_epoch_usec", 0)) / 1e6
            if ts < self.ignore_evidence_before:
                continue  # a previous attempt's heartbeat
            attrs = rec.get("attrs") or {}
            try:
                rid = int(attrs.get("replica", 0))
            except (TypeError, ValueError):
                rid = 0
            h = self.replicas.setdefault(rid, ReplicaHealth(replica=rid))
            h.last_heartbeat = max(h.last_heartbeat, ts)
            try:
                step = int(attrs.get("step", -1))
            except (TypeError, ValueError):
                step = -1
            h.last_step = max(h.last_step, step)

    # -- judgment -----------------------------------------------------------

    def check(self) -> GangVerdict:
        """Ingest fresh evidence and return the current verdict."""
        self.observe()
        now = self.clock()
        if not self.replicas:
            return GangVerdict(
                state=GangState.WAITING,
                detail="no heartbeats or leases observed yet",
                expected=self.expected_replicas,
            )
        if self._armed_at is None:
            self._armed_at = now
        live, lost, pending = [], [], []
        for rid in range(self.expected_replicas):
            h = self.replicas.get(rid)
            if h is None:
                # never produced evidence: ordinary startup skew can put
                # replicas' first flushes seconds apart, so a silent
                # replica only counts as lost once the hang deadline has
                # passed since the gang armed (first evidence observed)
                if now - self._armed_at <= self.hang_deadline_s:
                    pending.append(rid)
                else:
                    lost.append(rid)
                continue
            fresh = (
                now - h.last_heartbeat <= self.hang_deadline_s
                if h.last_heartbeat
                else False
            )
            if not fresh and h.last_lease:
                fresh = now - h.last_lease <= self.lease_ttl_s
            (live if fresh else lost).append(rid)
        # replicas reporting beyond the expected range still count as live
        # evidence of *something*, but the verdict is over the expected set
        if not live and not pending:
            return GangVerdict(
                state=GangState.HANG,
                detail=(
                    f"all {self.expected_replicas} replicas stale past"
                    f" {self.hang_deadline_s:.1f}s hang deadline"
                ),
                expected=self.expected_replicas,
                live=(),
                lost=tuple(lost),
            )
        if lost:
            return GangVerdict(
                state=GangState.PARTIAL_LOSS,
                detail=(
                    f"{len(lost)}/{self.expected_replicas} replicas stale past"
                    f" {self.hang_deadline_s:.1f}s deadline: {lost}"
                ),
                expected=self.expected_replicas,
                live=tuple(live),
                lost=tuple(lost),
            )
        if pending:
            return GangVerdict(
                state=GangState.WAITING,
                detail=(
                    f"{len(live)}/{self.expected_replicas} replicas"
                    f" reporting; waiting for first evidence from"
                    f" {pending} (armed {now - self._armed_at:.1f}s ago)"
                ),
                expected=self.expected_replicas,
                live=tuple(live),
            )
        if self.straggler_step_lag:
            steps = [
                self.replicas[r].last_step
                for r in live
                if self.replicas[r].last_step >= 0
            ]
            if steps and max(steps) - min(steps) > self.straggler_step_lag:
                return GangVerdict(
                    state=GangState.STRAGGLER,
                    detail=(
                        f"step spread {max(steps) - min(steps)} exceeds"
                        f" straggler lag {self.straggler_step_lag}"
                        f" (min={min(steps)}, max={max(steps)})"
                    ),
                    expected=self.expected_replicas,
                    live=tuple(live),
                )
        return GangVerdict(
            state=GangState.HEALTHY,
            detail=f"{len(live)}/{self.expected_replicas} replicas live",
            expected=self.expected_replicas,
            live=tuple(live),
        )
