"""Retry policy for the supervisor: per-failure-class budgets and backoff.

The central design decision (SURVEY §5, BASELINE config 4): preemptions,
infra failures, and app failures are NOT the same event and must not share
one retry counter. A spot v5e slice may be reclaimed a dozen times over a
long run — that is the product working as priced, and resubmitting is free
progress as long as checkpoints land. An app bug, on the other hand, will
fail deterministically forever; resubmitting it burns quota. So each
:class:`~torchx_tpu.specs.api.FailureClass` gets its own budget, with
defaults tilted accordingly (many preemptions, a few infra retries, zero
app retries).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from torchx_tpu import settings
from torchx_tpu.specs.api import FailureClass


@dataclass
class SupervisorPolicy:
    """Knobs for one supervised run; round-trips through
    :func:`torchx_tpu.specs.serialize.supervisor_policy_to_dict` so the CLI
    can load it from a JSON file.

    Budgets are *independent*: ``max_preemptions=8`` and
    ``max_app_retries=0`` means the eighth spot reclaim still resubmits,
    while the first genuine application error stays FAILED.
    """

    # -- retry budgets, one per FailureClass -------------------------------
    #: resubmissions allowed after spot/preemption reclaims.
    max_preemptions: int = 8
    #: resubmissions allowed after control-plane / node failures.
    max_infra_retries: int = 3
    #: resubmissions allowed after application (exit-code) failures.
    #: Default 0: an app bug fails deterministically; retrying burns quota.
    max_app_retries: int = 0
    #: resubmissions allowed after gang hangs / partial gang loss detected
    #: by the gang monitor (scheduler still says RUNNING, heartbeats
    #: stale). A hang is usually a wedged collective, worth a couple of
    #: kill+resubmit cycles.
    max_hang_retries: int = 2

    # -- capped exponential backoff between resubmissions ------------------
    #: first delay before a resubmit, seconds.
    backoff_seconds: float = 5.0
    #: multiplier applied per consecutive retry of the same class.
    backoff_factor: float = 2.0
    #: ceiling on any single delay, seconds.
    backoff_max_seconds: float = 300.0
    #: ± fraction of random perturbation applied to every delay so many
    #: supervisors recovering from one zone-wide event decorrelate.
    jitter: float = 0.1

    # -- monitoring --------------------------------------------------------
    #: cap on the jittered incremental poll interval while an attempt runs.
    poll_interval: float = 10.0
    #: consecutive status polls allowed to fail with a *transient* error
    #: (classified by :mod:`torchx_tpu.resilience.errors`) before the
    #: failure surfaces. Within the budget the poll loop degrades to a
    #: warning + ``poll_degraded`` event and keeps waiting — a control
    #: plane blip must not make the supervisor lose a healthy job.
    poll_miss_budget: int = 3
    #: run the elastic watcher (shrink-on-failure) during each attempt when
    #: the backend has one, instead of plain status polling.
    elastic: bool = False

    # -- gang health (hang detection while status reads RUNNING) -----------
    #: seconds without any fresh heartbeat/lease before a replica counts as
    #: stale; 0 disables gang monitoring entirely (plain wait).
    hang_deadline_seconds: float = 0.0
    #: how often the gang monitor re-reads heartbeats between polls.
    gang_check_interval: float = 5.0
    #: liveness-lease TTL the monitor uses for replicas that renew leases;
    #: 0 falls back to ``hang_deadline_seconds``.
    lease_ttl_seconds: float = 0.0
    #: warn (event + metric, no kill) when the fastest and slowest replica
    #: drift more than this many steps apart; 0 disables straggler checks.
    straggler_step_lag: int = 0

    # -- elastic mesh reshape on resubmit ----------------------------------
    #: after PREEMPTION/HANG, recompute a degraded mesh (shrink dp/fsdp,
    #: preserve pp/ep/tp/sp) and inject it as ``TPX_MESH`` on resubmit.
    #: Requires ``mesh``.
    elastic_reshape: bool = False
    #: the job's launch mesh spec (``--mesh`` syntax); basis for reshapes.
    mesh: Optional[str] = None
    #: accelerator devices each replica contributes to the mesh (surviving
    #: replicas × this = the device count a degraded shape must fit).
    devices_per_replica: int = 1

    # -- checkpoint resume -------------------------------------------------
    #: client-visible checkpoint directory to read the step manifest from;
    #: None disables resume injection (the app's own restore_latest still
    #: applies in-job).
    checkpoint_dir: Optional[str] = None
    #: env var injected into every role with the resume step.
    resume_env: str = field(default=settings.ENV_TPX_RESUME_STEP)

    def __post_init__(self) -> None:
        for name in (
            "max_preemptions",
            "max_infra_retries",
            "max_app_retries",
            "max_hang_retries",
        ):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, got {getattr(self, name)}")
        if self.backoff_seconds < 0 or self.backoff_max_seconds < 0:
            raise ValueError("backoff delays must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1.0, got {self.backoff_factor}"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.poll_interval <= 0:
            raise ValueError(f"poll_interval must be > 0, got {self.poll_interval}")
        if self.poll_miss_budget < 0:
            raise ValueError(
                f"poll_miss_budget must be >= 0, got {self.poll_miss_budget}"
            )
        if self.hang_deadline_seconds < 0 or self.lease_ttl_seconds < 0:
            raise ValueError("gang deadlines must be >= 0")
        if self.gang_check_interval <= 0:
            raise ValueError(
                f"gang_check_interval must be > 0, got {self.gang_check_interval}"
            )
        if self.straggler_step_lag < 0:
            raise ValueError(
                f"straggler_step_lag must be >= 0, got {self.straggler_step_lag}"
            )
        if self.devices_per_replica < 1:
            raise ValueError(
                f"devices_per_replica must be >= 1, got {self.devices_per_replica}"
            )
        if self.elastic_reshape and not self.mesh:
            raise ValueError("elastic_reshape requires a mesh spec")
        if self.mesh is not None:
            # validate early: a bad spec should fail at policy build, not
            # mid-recovery (parse only — jax-free)
            from torchx_tpu.parallel.mesh_config import parse_mesh_spec

            parse_mesh_spec(self.mesh)

    def budget_for(self, failure_class: FailureClass) -> int:
        """The retry budget governing one failure class."""
        return {
            FailureClass.PREEMPTION: self.max_preemptions,
            FailureClass.INFRA: self.max_infra_retries,
            FailureClass.APP: self.max_app_retries,
            FailureClass.HANG: self.max_hang_retries,
        }[failure_class]

    def backoff_delay(
        self, retry_number: int, rng: Optional[random.Random] = None
    ) -> float:
        """Jittered delay (seconds) before retry ``retry_number`` (1-based
        count of consecutive retries for the failing class): capped
        exponential ``backoff_seconds * factor**(n-1)``, perturbed by
        ±``jitter``. A seeded ``rng`` makes tests deterministic."""
        if retry_number < 1:
            raise ValueError(f"retry_number must be >= 1, got {retry_number}")
        base = min(
            self.backoff_seconds * self.backoff_factor ** (retry_number - 1),
            self.backoff_max_seconds,
        )
        r = rng or random
        return max(0.0, base * (1.0 + r.uniform(-self.jitter, self.jitter)))
