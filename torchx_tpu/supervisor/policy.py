"""Retry policy for the supervisor: per-failure-class budgets and backoff.

The central design decision (SURVEY §5, BASELINE config 4): preemptions,
infra failures, and app failures are NOT the same event and must not share
one retry counter. A spot v5e slice may be reclaimed a dozen times over a
long run — that is the product working as priced, and resubmitting is free
progress as long as checkpoints land. An app bug, on the other hand, will
fail deterministically forever; resubmitting it burns quota. So each
:class:`~torchx_tpu.specs.api.FailureClass` gets its own budget, with
defaults tilted accordingly (many preemptions, a few infra retries, zero
app retries).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from torchx_tpu import settings
from torchx_tpu.specs.api import FailureClass


@dataclass
class SupervisorPolicy:
    """Knobs for one supervised run; round-trips through
    :func:`torchx_tpu.specs.serialize.supervisor_policy_to_dict` so the CLI
    can load it from a JSON file.

    Budgets are *independent*: ``max_preemptions=8`` and
    ``max_app_retries=0`` means the eighth spot reclaim still resubmits,
    while the first genuine application error stays FAILED.
    """

    # -- retry budgets, one per FailureClass -------------------------------
    #: resubmissions allowed after spot/preemption reclaims.
    max_preemptions: int = 8
    #: resubmissions allowed after control-plane / node failures.
    max_infra_retries: int = 3
    #: resubmissions allowed after application (exit-code) failures.
    #: Default 0: an app bug fails deterministically; retrying burns quota.
    max_app_retries: int = 0

    # -- capped exponential backoff between resubmissions ------------------
    #: first delay before a resubmit, seconds.
    backoff_seconds: float = 5.0
    #: multiplier applied per consecutive retry of the same class.
    backoff_factor: float = 2.0
    #: ceiling on any single delay, seconds.
    backoff_max_seconds: float = 300.0
    #: ± fraction of random perturbation applied to every delay so many
    #: supervisors recovering from one zone-wide event decorrelate.
    jitter: float = 0.1

    # -- monitoring --------------------------------------------------------
    #: cap on the jittered incremental poll interval while an attempt runs.
    poll_interval: float = 10.0
    #: consecutive status polls allowed to fail with a *transient* error
    #: (classified by :mod:`torchx_tpu.resilience.errors`) before the
    #: failure surfaces. Within the budget the poll loop degrades to a
    #: warning + ``poll_degraded`` event and keeps waiting — a control
    #: plane blip must not make the supervisor lose a healthy job.
    poll_miss_budget: int = 3
    #: run the elastic watcher (shrink-on-failure) during each attempt when
    #: the backend has one, instead of plain status polling.
    elastic: bool = False

    # -- checkpoint resume -------------------------------------------------
    #: client-visible checkpoint directory to read the step manifest from;
    #: None disables resume injection (the app's own restore_latest still
    #: applies in-job).
    checkpoint_dir: Optional[str] = None
    #: env var injected into every role with the resume step.
    resume_env: str = field(default=settings.ENV_TPX_RESUME_STEP)

    def __post_init__(self) -> None:
        for name in ("max_preemptions", "max_infra_retries", "max_app_retries"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0, got {getattr(self, name)}")
        if self.backoff_seconds < 0 or self.backoff_max_seconds < 0:
            raise ValueError("backoff delays must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor must be >= 1.0, got {self.backoff_factor}"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.poll_interval <= 0:
            raise ValueError(f"poll_interval must be > 0, got {self.poll_interval}")
        if self.poll_miss_budget < 0:
            raise ValueError(
                f"poll_miss_budget must be >= 0, got {self.poll_miss_budget}"
            )

    def budget_for(self, failure_class: FailureClass) -> int:
        """The retry budget governing one failure class."""
        return {
            FailureClass.PREEMPTION: self.max_preemptions,
            FailureClass.INFRA: self.max_infra_retries,
            FailureClass.APP: self.max_app_retries,
        }[failure_class]

    def backoff_delay(
        self, retry_number: int, rng: Optional[random.Random] = None
    ) -> float:
        """Jittered delay (seconds) before retry ``retry_number`` (1-based
        count of consecutive retries for the failing class): capped
        exponential ``backoff_seconds * factor**(n-1)``, perturbed by
        ±``jitter``. A seeded ``rng`` makes tests deterministic."""
        if retry_number < 1:
            raise ValueError(f"retry_number must be >= 1, got {retry_number}")
        base = min(
            self.backoff_seconds * self.backoff_factor ** (retry_number - 1),
            self.backoff_max_seconds,
        )
        r = rng or random
        return max(0.0, base * (1.0 + r.uniform(-self.jitter, self.jitter)))
