"""Preemption-aware job supervisor: auto-resubmit with retry budgets,
capped exponential backoff, and checkpoint-resume wiring.

The missing layer between "the scheduler restarts replicas inside one job"
(RetryPolicy / JobSet failurePolicy) and "the operator resubmits the job by
hand": a client-side loop that watches one app to a terminal state,
classifies *why* it died (:class:`~torchx_tpu.specs.api.FailureClass`),
and — within independent per-class budgets — re-materializes the original
:class:`~torchx_tpu.specs.api.AppDryRunInfo` and submits a fresh attempt,
telling it which checkpoint step to resume from. See
:class:`~torchx_tpu.supervisor.api.Supervisor` for the state machine and
:class:`~torchx_tpu.supervisor.policy.SupervisorPolicy` for the knobs.

Gang health (:mod:`torchx_tpu.supervisor.gang`) extends the loop to
failures the scheduler cannot see: a :class:`GangMonitor` tails the job's
heartbeats and liveness leases between status polls, and a HANG /
PARTIAL_LOSS verdict makes the supervisor kill the attempt, classify it
``FailureClass.HANG``, and — with ``elastic_reshape`` — resubmit onto a
degraded mesh that fits the surviving capacity.
"""

from torchx_tpu.supervisor.api import (
    Supervisor,
    SupervisorResult,
    latest_checkpoint_step,
    supervise,
)
from torchx_tpu.supervisor.gang import (
    GangMonitor,
    GangState,
    GangVerdict,
    read_leases,
    renew_lease,
)
from torchx_tpu.supervisor.ledger import AttemptLedger, list_sessions
from torchx_tpu.supervisor.policy import SupervisorPolicy

__all__ = [
    "AttemptLedger",
    "GangMonitor",
    "GangState",
    "GangVerdict",
    "Supervisor",
    "SupervisorPolicy",
    "SupervisorResult",
    "latest_checkpoint_step",
    "list_sessions",
    "read_leases",
    "renew_lease",
    "supervise",
]
