"""The supervisor engine: babysit one app through preemptions to success.

Scheduler-agnostic by construction — it only speaks the Runner/Scheduler
contract (``schedule`` / ``status`` / ``cancel``), consumes the
:class:`~torchx_tpu.specs.api.FailureClass` the backends attach to terminal
states, and re-materializes fresh submissions from the attempt's
:class:`~torchx_tpu.specs.api.AppDryRunInfo`. The loop:

    SUBMITTED -> poll -> terminal?
        SUCCEEDED / CANCELLED          -> done
        PREEMPTED / FAILED (classified) -> budget left?
            yes -> backoff -> inject resume step -> resubmit
            no  -> give up (final status stands)

Checkpoint resume is wired through the jax-free manifest sidecar
(:data:`~torchx_tpu.settings.CHECKPOINT_MANIFEST`): this module runs on
the client and must never import jax/orbax, so it reads the JSON the
in-job :class:`~torchx_tpu.parallel.checkpoint.Checkpointer` maintains and
falls back to scanning the step layout on disk.

Every transition emits a :class:`~torchx_tpu.runner.events.api.TpxEvent`
(``api="supervise"``) with the transition name, attempt number, failure
class, and resume step in ``app_metadata`` — the audit trail for "why did
my job restart at 3am".
"""

from __future__ import annotations

import copy
import json
import logging
import os
import random
import re
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional

from torchx_tpu import settings
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs import trace as obs_trace
from torchx_tpu.runner.events import record
from torchx_tpu.runner.events.api import TpxEvent
from torchx_tpu.specs.api import (
    AppDryRunInfo,
    AppHandle,
    AppState,
    AppStatus,
    FailureClass,
    parse_app_handle,
)
from torchx_tpu.parallel.mesh_config import (
    mesh_sizes_spec,
    parse_mesh_spec,
    shrink_data_axes,
)
from torchx_tpu.schedulers.ids import make_unique
from torchx_tpu.supervisor.gang import GangMonitor, GangState, GangVerdict
from torchx_tpu.supervisor.ledger import AttemptLedger
from torchx_tpu.supervisor.policy import SupervisorPolicy
from torchx_tpu.util.times import poll_intervals

if TYPE_CHECKING:  # import cycle: runner.api imports specs, we import runner
    from torchx_tpu.runner.api import Runner

logger = logging.getLogger(__name__)


def latest_checkpoint_step(directory: str) -> Optional[int]:
    """Newest checkpoint step under ``directory``, or None, WITHOUT
    importing jax/orbax (this runs on the client).

    Prefers the ``MANIFEST.json`` sidecar the in-job Checkpointer writes;
    falls back to scanning the on-disk step layout (orbax digit-named step
    dirs, ``step_N.pkl`` pickle files) for checkpoints written by older
    jobs that predate the manifest. ``.corrupt``-quarantined steps never
    match either pattern."""
    manifest = os.path.join(directory, settings.CHECKPOINT_MANIFEST)
    try:
        with open(manifest) as f:
            step = json.load(f).get("latest_step")
        if isinstance(step, int):
            return step
    except (OSError, ValueError):
        pass
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in entries:
        if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            steps.append(int(name))
        elif m := re.fullmatch(r"step_(\d+)\.pkl", name):
            steps.append(int(m.group(1)))
    return max(steps, default=None)


@dataclass
class SupervisorResult:
    """Outcome of one :meth:`Supervisor.run`: the final status plus the
    full attempt history for reporting and tests."""

    #: status of the last attempt (terminal), or None if it vanished.
    status: Optional[AppStatus]
    #: handle of every attempt, oldest first; the last one is the survivor.
    handles: list[AppHandle] = field(default_factory=list)
    #: total submissions (== len(handles)).
    attempts: int = 0
    #: resubmissions consumed per failure class.
    retries: dict[FailureClass, int] = field(default_factory=dict)
    #: checkpoint step injected on each resubmit (None = fresh start).
    resume_steps: list[Optional[int]] = field(default_factory=list)
    #: set when a retry budget ran out and the failure stood.
    budget_exhausted: Optional[FailureClass] = None
    #: durable session name; ``tpx supervise --resume <session>`` reattaches.
    session: str = ""

    @property
    def handle(self) -> Optional[AppHandle]:
        """Handle of the final attempt."""
        return self.handles[-1] if self.handles else None

    @property
    def succeeded(self) -> bool:
        """True iff the final attempt reached SUCCEEDED."""
        return self.status is not None and self.status.state == AppState.SUCCEEDED


class Supervisor:
    """Drives one :class:`~torchx_tpu.specs.api.AppDryRunInfo` to completion
    under a :class:`~torchx_tpu.supervisor.policy.SupervisorPolicy`.

    Construct with a live :class:`~torchx_tpu.runner.api.Runner` (the
    session that produced the dryrun) and call :meth:`run`. ``sleep`` and
    ``rng`` are injectable for tests — a scripted fake scheduler plus a
    recording sleep makes the whole state machine deterministic."""

    def __init__(
        self,
        runner: "Runner",
        dryrun_info: AppDryRunInfo,
        policy: Optional[SupervisorPolicy] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        session: Optional[str] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if dryrun_info._app is None or not dryrun_info._scheduler:
            raise ValueError(
                "dryrun_info was not produced by Runner.dryrun/materialize_dryrun"
                " (missing _app/_scheduler); the supervisor cannot resubmit it"
            )
        self._runner = runner
        self._dryrun_info = dryrun_info
        self._policy = policy or SupervisorPolicy()
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()
        self.session = session or make_unique("sup")
        self._ledger = AttemptLedger(self.session)
        # resume state (populated by :meth:`resume`): reattach here instead
        # of submitting a fresh first attempt, with restored counters
        self._resume_handle: Optional[AppHandle] = None
        self._resume_attempts = 0
        self._resume_retries: dict[FailureClass, int] = {}
        self._resume_steps: list[Optional[int]] = []
        # gang health: factory is injectable so tests can hand the monitor
        # a synthetic trace file / clock; verdict of the attempt the gang
        # monitor killed, consumed by the reshape step
        self.monitor_factory: Callable[..., GangMonitor] = GangMonitor
        self._last_verdict: Optional[GangVerdict] = None
        # heartbeats/leases stamped before this epoch belong to a previous
        # attempt: each resubmission advances the floor so the fresh
        # monitor never reads the dead attempt's evidence as an instant
        # HANG while the new gang is still compiling
        self._evidence_floor = 0.0
        # did the monitor see every expected replica live on the current
        # shape? consumed by _maybe_reshape's preemption grow-back
        self._gang_was_full = False
        # elastic reshape: resolved axis sizes of the mesh the CURRENT
        # attempt runs on (None until the first reshape when no resume
        # replayed one); the spec string injected as $TPX_MESH
        self._current_mesh: Optional[dict[str, int]] = None
        self._mesh_spec: Optional[str] = None

    # -- crash-safe resume -------------------------------------------------

    @classmethod
    def resume(
        cls,
        runner: "Runner",
        session: str,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> "Supervisor":
        """Reattach to a supervised session after the client crashed.

        Rebuilds the submission recipe from the session's ``meta.json``
        (AppDef + cfg + policy re-materialized through the scheduler's own
        ``materialize_dryrun``) and replays ``ledger.jsonl`` to restore the
        attempt counter, per-class retry counts, and — crucially — the
        handle of the last submitted attempt. :meth:`run` then polls that
        live handle instead of submitting a duplicate: a job that kept
        running while the supervisor was dead is simply picked back up.
        """
        from torchx_tpu.specs.serialize import (
            appdef_from_dict,
            supervisor_policy_from_dict,
        )

        ledger = AttemptLedger(session)
        meta = ledger.read_meta()
        scheduler = meta["scheduler"]
        app = appdef_from_dict(meta["app"])
        policy = supervisor_policy_from_dict(meta.get("policy") or {})
        sched = runner._scheduler(scheduler)
        info = sched.materialize_dryrun(app, meta.get("cfg") or {})
        sup = cls(runner, info, policy, sleep=sleep, rng=rng, session=session)
        sup._restore(ledger)
        if sup._resume_handle is None:
            raise ValueError(
                f"session {session!r} has no submitted attempt to reattach"
                " to (the original client died before its first submit);"
                " start a fresh supervise instead"
            )
        return sup

    def _restore(self, ledger: AttemptLedger) -> None:
        retries: dict[FailureClass, int] = {fc: 0 for fc in FailureClass}
        for entry in ledger.entries():
            transition = entry.get("transition")
            if transition == "submitted":
                self._resume_attempts = max(
                    self._resume_attempts, int(entry.get("attempt") or 0)
                )
                handle = entry.get("handle")
                if handle:
                    self._resume_handle = str(handle)
                    ts = entry.get("time_usec")
                    if ts:
                        # evidence older than the reattached attempt's own
                        # submission came from an earlier attempt
                        self._evidence_floor = float(ts) / 1e6
                step = entry.get("resume_step")
                self._resume_steps.append(
                    int(step) if step is not None else None
                )
                mesh = entry.get("mesh")
                if mesh:
                    # replay the reshaped mesh so a resumed session keeps
                    # resubmitting onto the degraded shape, not the launch one
                    self._mesh_spec = str(mesh)
                    self._current_mesh = self._sizes_from_spec(str(mesh))
            elif transition == "resubmitting":
                name = str(entry.get("failure_class") or "").rsplit(".", 1)[-1]
                try:
                    retries[FailureClass[name]] += 1
                except KeyError:
                    pass
        self._resume_retries = retries

    # -- event plumbing ----------------------------------------------------

    def _emit(
        self, transition: str, app_id: Optional[str], **metadata: object
    ) -> None:
        record(
            TpxEvent(
                session=self._runner._name,
                scheduler=self._dryrun_info._scheduler or "",
                api="supervise",
                app_id=app_id,
                app_metadata={"transition": transition, **metadata},
            )
        )
        # the same transition goes to the durable ledger so a fresh client
        # can reconstruct the loop's exact state after a crash
        self._ledger.append(transition, app_id, **metadata)

    def _write_meta(self) -> None:
        from torchx_tpu.specs.serialize import (
            appdef_to_dict,
            supervisor_policy_to_dict,
        )

        try:
            meta = {
                "session": self.session,
                "scheduler": self._dryrun_info._scheduler or "",
                "runner_session": self._runner._name,
                "app": appdef_to_dict(self._dryrun_info._app),
                "cfg": dict(self._dryrun_info._cfg or {}),
                "policy": supervisor_policy_to_dict(self._policy),
            }
        except (TypeError, ValueError) as e:  # unserializable cfg value
            logger.warning(
                "session %s: could not persist resume metadata (%s);"
                " --resume will not be available",
                self.session,
                e,
            )
            return
        self._ledger.write_meta(meta)

    # -- gang / mesh helpers -----------------------------------------------

    def _total_replicas(self) -> int:
        app = self._dryrun_info._app
        assert app is not None  # checked in __init__
        return max(1, sum(r.num_replicas for r in app.roles))

    def _total_devices(self) -> int:
        return self._total_replicas() * self._policy.devices_per_replica

    def _sizes_from_spec(self, spec: str) -> Optional[dict[str, int]]:
        """Resolved axis sizes for a spec: a fully-explicit spec resolves
        against its own product; a wildcard one against the launch device
        count. None when the spec cannot resolve (caller skips reshaping)."""
        try:
            cfg = parse_mesh_spec(spec)
            sizes = {
                a: getattr(cfg, a)
                for a in ("pp", "dp", "fsdp", "ep", "tp", "sp")
            }
            if -1 not in sizes.values():
                return sizes
            return cfg.resolve(self._total_devices())
        except ValueError as e:
            logger.warning("cannot resolve mesh spec %r: %s", spec, e)
            return None

    def _maybe_reshape(self, fclass: FailureClass) -> None:
        """After PREEMPTION/HANG, degrade the mesh for the next attempt.

        With a gang verdict (the monitor killed the attempt and counted
        survivors) the data axes are refit to the surviving capacity;
        without one (plain scheduler-reported preemption) the shape
        degrades one binary step. A shape that cannot shrink further —
        or a target that cannot preserve the model axes — keeps the
        current shape: resubmitting at the same size is always safe.

        The blind binary step must not ratchet a healthy job toward dp=1
        across a long run's occasional preemptions: once the monitor has
        seen the full gang live during the attempt (``_gang_was_full``), a
        verdict-less preemption restores the launch mesh instead — a
        reschedule is a fresh allocation at the requested size, and the
        capacity demonstrably came back."""
        policy = self._policy
        verdict = self._last_verdict
        self._last_verdict = None
        if not policy.elastic_reshape or not policy.mesh:
            return
        if fclass not in (FailureClass.PREEMPTION, FailureClass.HANG):
            return
        cur = self._current_mesh or self._sizes_from_spec(policy.mesh)
        if cur is None:
            return
        target = None
        if verdict is not None and 0 < verdict.survivors < verdict.expected:
            target = verdict.survivors * policy.devices_per_replica
        elif fclass is FailureClass.PREEMPTION and self._gang_was_full:
            launch = self._sizes_from_spec(policy.mesh)
            if launch is not None and launch != cur:
                self._current_mesh = launch
                self._mesh_spec = mesh_sizes_spec(launch)
                obs_metrics.GANG_RESHAPES.inc()
                logger.info(
                    "elastic grow-back: %s -> %s (full gang was healthy"
                    " before the preemption)",
                    mesh_sizes_spec(cur),
                    self._mesh_spec,
                )
            return  # gang was demonstrably whole: never blind-shrink it
        try:
            new = shrink_data_axes(cur, target)
        except ValueError as e:
            logger.warning(
                "keeping mesh %s: %s", mesh_sizes_spec(cur), e
            )
            self._current_mesh = cur
            self._mesh_spec = mesh_sizes_spec(cur)
            return
        self._current_mesh = new
        self._mesh_spec = mesh_sizes_spec(new)
        obs_metrics.GANG_RESHAPES.inc()
        logger.info(
            "elastic reshape: %s -> %s%s",
            mesh_sizes_spec(cur),
            self._mesh_spec,
            f" ({verdict.survivors}/{verdict.expected} replicas survive)"
            if verdict is not None
            else "",
        )

    # -- attempt mechanics -------------------------------------------------

    def _submit(self, attempt: int, resume_step: Optional[int]) -> AppHandle:
        """Re-materialize and submit one attempt. Works on a deep copy of
        the original AppDef (resume env must not accumulate across
        attempts) and goes through the scheduler's own materialize so each
        attempt gets a fresh unique app id."""
        if attempt > 1:
            # floor BEFORE scheduling so nothing the new attempt emits can
            # land below it; the first attempt keeps floor 0 (pre-submit
            # evidence can only be ours)
            self._evidence_floor = self._clock()
        self._gang_was_full = False
        info = self._dryrun_info
        app = copy.deepcopy(info._app)
        assert app is not None  # checked in __init__
        for role in app.roles:
            if resume_step is not None:
                role.env[self._policy.resume_env] = str(resume_step)
            if self._mesh_spec:
                # degraded shape from an elastic reshape: trainers honor
                # $TPX_MESH over their --mesh flag
                role.env[settings.ENV_TPX_MESH] = self._mesh_spec
            # re-point the in-job trace context at THIS attempt (the
            # deep-copied env still carries the dryrun-time context)
            obs_trace.inject_env(role.env, force=True)
        sched = self._runner._scheduler(info._scheduler)
        new_info = sched.materialize_dryrun(app, info._cfg or {})
        handle = self._runner.schedule(new_info)
        _, _, app_id = parse_app_handle(handle)
        self._emit(
            "submitted",
            app_id,
            attempt=attempt,
            resume_step=resume_step,
            handle=handle,
            mesh=self._mesh_spec,
        )
        return handle

    def _await_terminal(self, handle: AppHandle) -> Optional[AppStatus]:
        """Block until the attempt reaches a terminal state (or vanishes).

        With ``policy.elastic`` the backend's elastic watcher runs first —
        in-attempt shrink-restarts are its job; only the attempt's terminal
        outcome comes back to the supervisor. With a hang deadline set the
        gang monitor interleaves with status polling
        (:meth:`_await_terminal_gang`)."""
        if self._policy.elastic:
            try:
                self._runner.watch_elastic(
                    handle, poll_interval=self._policy.poll_interval
                )
            except ValueError:
                logger.debug(
                    "backend has no elastic watcher; falling back to polling"
                )
        if self._policy.hang_deadline_seconds > 0:
            return self._await_terminal_gang(handle)
        return self._runner.wait(
            handle, wait_interval=self._policy.poll_interval, rng=self._rng,
            sleep=self._sleep,
            poll_miss_budget=self._policy.poll_miss_budget,
        )

    def _await_terminal_gang(self, handle: AppHandle) -> Optional[AppStatus]:
        """Status polling interleaved with gang-health checks.

        ``Runner.wait`` runs in ``gang_check_interval`` slices; every
        timeout slice the monitor re-reads heartbeats/leases. An unhealthy
        verdict (HANG / PARTIAL_LOSS) means the scheduler still says
        RUNNING but the gang is dead: the supervisor kills the attempt
        itself and synthesizes a terminal FAILED status classified
        :attr:`FailureClass.HANG` so the normal budget/backoff/resume path
        takes over. STRAGGLER is warn-only (event + metric, once per
        verdict change)."""
        policy = self._policy
        monitor = self.monitor_factory(
            expected_replicas=self._total_replicas(),
            hang_deadline_s=policy.hang_deadline_seconds,
            lease_ttl_s=policy.lease_ttl_seconds,
            straggler_step_lag=policy.straggler_step_lag,
            ignore_evidence_before=self._evidence_floor,
        )
        _, _, app_id = parse_app_handle(handle)
        last_state: Optional[GangState] = None
        while True:
            try:
                return self._runner.wait(
                    handle,
                    wait_interval=min(
                        policy.poll_interval, policy.gang_check_interval
                    ),
                    timeout=policy.gang_check_interval,
                    rng=self._rng,
                    sleep=self._sleep,
                    poll_miss_budget=policy.poll_miss_budget,
                )
            except TimeoutError:
                pass  # the attempt is still running: gang-check it
            verdict = monitor.check()
            if verdict.state != last_state and verdict.state not in (
                GangState.HEALTHY,
                GangState.WAITING,
            ):
                obs_metrics.GANG_UNHEALTHY.inc(kind=str(verdict.state))
                self._emit(
                    "gang_" + str(verdict.state).lower(),
                    app_id,
                    detail=verdict.detail,
                    survivors=verdict.survivors,
                    expected=verdict.expected,
                    lost=list(verdict.lost),
                )
            last_state = verdict.state
            if verdict.state in (GangState.HEALTHY, GangState.STRAGGLER):
                # every expected replica live on the current shape —
                # capacity evidence for the preemption grow-back
                self._gang_was_full = True
            if not verdict.unhealthy:
                continue
            logger.warning(
                "app %s gang %s: %s; killing the attempt",
                app_id,
                verdict.state,
                verdict.detail,
            )
            self._last_verdict = verdict
            try:
                self._runner.cancel(handle)
            except Exception as e:  # best effort: the kill must not mask
                logger.warning("cancel of hung app %s failed: %s", app_id, e)
            return AppStatus(
                state=AppState.FAILED,
                msg=f"gang {verdict.state}: {verdict.detail}",
                failure_class=FailureClass.HANG,
            )

    # -- the state machine -------------------------------------------------

    def run(self) -> SupervisorResult:
        """Run attempts until SUCCEEDED/CANCELLED, a budget is exhausted,
        or the app vanishes from its scheduler; returns the full
        :class:`SupervisorResult` history.

        Each attempt (submit → wait-to-terminal → classification) is one
        ``supervisor.attempt`` span and each backoff sleep one
        ``supervisor.backoff`` span, all nested under the caller's trace —
        together with the transition events this is the full audit trail
        ``tpx trace`` renders."""
        # umbrella span: guarantees all attempts share ONE trace even when
        # run() is called directly (Runner.supervise adds its own parent)
        self._write_meta()
        with obs_trace.span(
            "supervisor.run",
            session=self._runner._name,
            scheduler=self._dryrun_info._scheduler,
        ) as root:
            result = self._run_attempts()
            if root is not None:
                root.attrs["attempts"] = result.attempts
                if result.status is not None:
                    root.attrs["state"] = str(result.status.state)
        return result

    def _run_attempts(self) -> SupervisorResult:
        policy = self._policy
        retries: dict[FailureClass, int] = {fc: 0 for fc in FailureClass}
        for fc, n in self._resume_retries.items():
            retries[fc] = n
        result = SupervisorResult(
            status=None, retries=retries, session=self.session
        )

        # a resumed session reattaches to the last submitted attempt (it
        # may still be running — or already terminal, in which case the
        # normal classification path below takes over immediately)
        reattach = self._resume_handle
        self._resume_handle = None
        resume_step: Optional[int] = None
        attempt = self._resume_attempts
        if reattach is not None and self._resume_steps:
            resume_step = self._resume_steps[-1]
        while True:
            if reattach is None:
                attempt += 1
            with obs_trace.span(
                "supervisor.attempt",
                session=self._runner._name,
                attempt=attempt,
                resume_step=resume_step,
            ) as asp:
                if reattach is not None:
                    handle = reattach
                    reattach = None
                    _, _, rid = parse_app_handle(handle)
                    self._emit("reattached", rid, attempt=attempt)
                else:
                    handle = self._submit(attempt, resume_step)
                result.handles.append(handle)
                result.resume_steps.append(resume_step)
                result.attempts = attempt

                status = self._await_terminal(handle)
                result.status = status
                _, _, app_id = parse_app_handle(handle)
                if asp is not None:
                    asp.attrs["app_id"] = app_id
                    if status is not None:
                        asp.attrs["state"] = str(status.state)
                if status is None:
                    # the scheduler forgot the app (expired / deleted from
                    # under us); resubmitting blind could double-run — stop.
                    self._emit("vanished", app_id, attempt=attempt)
                    logger.warning("app %s vanished from its scheduler", app_id)
                    return result
                if status.state in (AppState.SUCCEEDED, AppState.CANCELLED):
                    self._emit(
                        "finished",
                        app_id,
                        attempt=attempt,
                        state=str(status.state),
                    )
                    return result

                # terminal failure: classify conservatively (APP) when the
                # backend attached nothing
                fclass = status.failure_class or FailureClass.APP
                if asp is not None:
                    asp.attrs["failure_class"] = str(fclass)
                retries[fclass] += 1
                budget = policy.budget_for(fclass)
                if retries[fclass] > budget:
                    retries[fclass] = budget  # report consumed, not attempted
                    result.budget_exhausted = fclass
                    self._emit(
                        "budget_exhausted",
                        app_id,
                        attempt=attempt,
                        failure_class=str(fclass),
                        budget=budget,
                        state=str(status.state),
                    )
                    logger.error(
                        "app %s: %s budget (%d) exhausted; final state %s",
                        app_id,
                        fclass,
                        budget,
                        status.state,
                    )
                    return result

                obs_metrics.RETRIES.inc(failure_class=str(fclass))
                delay = policy.backoff_delay(retries[fclass], rng=self._rng)
                if policy.checkpoint_dir:
                    resume_step = latest_checkpoint_step(policy.checkpoint_dir)
                self._maybe_reshape(fclass)
                self._emit(
                    "resubmitting",
                    app_id,
                    attempt=attempt,
                    failure_class=str(fclass),
                    retry=retries[fclass],
                    budget=budget,
                    backoff_seconds=round(delay, 3),
                    resume_step=resume_step,
                    state=str(status.state),
                    mesh=self._mesh_spec,
                )
                logger.info(
                    "app %s %s (%s); retry %d/%d in %.1fs%s",
                    app_id,
                    status.state,
                    fclass,
                    retries[fclass],
                    budget,
                    delay,
                    f", resuming from step {resume_step}"
                    if resume_step is not None
                    else "",
                )
            with obs_trace.span(
                "supervisor.backoff",
                session=self._runner._name,
                failure_class=str(fclass),
                retry=retries[fclass],
                delay_seconds=round(delay, 3),
            ):
                self._sleep(delay)
            obs_metrics.BACKOFF_SECONDS.inc(delay)


def supervise(
    runner: "Runner",
    dryrun_info: AppDryRunInfo,
    policy: Optional[SupervisorPolicy] = None,
) -> SupervisorResult:
    """Convenience wrapper: build a :class:`Supervisor` and :meth:`run` it."""
    return Supervisor(runner, dryrun_info, policy).run()
