"""Deferred entry-point loading (reference analog: torchx/util/entrypoints.py).

``load_group`` returns {name: deferred-loader} so importing a package with
heavy/broken entry points costs nothing until a specific one is used.
"""

from __future__ import annotations

from typing import Any, Callable, Optional


def load_group(
    group: str, default: Optional[dict[str, Any]] = None
) -> dict[str, Callable[[], Any]]:
    try:
        from importlib.metadata import entry_points
    except ImportError:  # pragma: no cover
        return dict(default or {})
    try:
        eps = list(entry_points(group=group))
    except Exception:  # noqa: BLE001
        eps = []
    if not eps:
        return dict(default or {})

    out: dict[str, Callable[[], Any]] = {}
    for ep in eps:
        out[ep.name] = _deferred(ep)
    return out


def _deferred(ep) -> Callable[[], Any]:  # noqa: ANN001
    def load() -> Any:
        return ep.load()

    load.__name__ = f"load_{ep.name}"
    return load


def load(group: str, name: str, default: Any = None) -> Any:
    loaders = load_group(group)
    if name in loaders:
        return loaders[name]()
    return default
