"""Crash-safe JSONL + JSON-state helpers: the durable-IO seam.

Every journal in the repo shares one durability contract (enforced by
``tpx selfcheck`` TPX93x):

* appends are line-atomic (one ``write`` of a complete line on an
  O_APPEND handle) and flushed + fsync'd before the write is claimed
  durable — :func:`append_jsonl`;
* state files are rewritten atomically (tmp + fsync + ``os.replace``)
  so readers never observe a torn file — :func:`rewrite_json`;
* readers hold back a torn final line (a killed writer leaves at most
  one) instead of crashing or silently swallowing mid-file corruption —
  :func:`iter_jsonl` / :func:`read_jsonl`.

The helpers are stdlib-only and jax-free.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator


def append_jsonl(path: str, record: dict[str, Any]) -> None:
    """Durably append one record: mkdir + O_APPEND + flush + fsync.

    One ``write()`` of the complete newline-terminated line, so
    concurrent same-file appenders (O_APPEND is atomic on POSIX for
    short writes) interleave whole lines, never fragments."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    line = json.dumps(record, sort_keys=True) + "\n"
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())


def iter_jsonl(path: str, *, skip: str = "tail") -> Iterator[dict[str, Any]]:
    """Parsed records of one JSONL file, torn-line holdback included.

    Args:
        path: the journal file; missing file yields nothing.
        skip: ``"tail"`` (default) holds back only a torn FINAL line —
            the one shape a crashed writer legally leaves — and raises
            ``ValueError`` on mid-file garbage (that is corruption, not
            a crash artifact). ``"all"`` skips every unparseable line
            (for feeds written by foreign processes, e.g. scraped
            textfiles).
    """
    if skip not in ("tail", "all"):
        raise ValueError(f"skip must be 'tail' or 'all', got {skip!r}")
    if not os.path.exists(path):
        return
    with open(path) as f:
        lines = f.readlines()
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            yield json.loads(line)
        except ValueError:
            if skip == "all":
                continue
            if i == last:
                return  # torn final line from a killed writer: hold back
            raise ValueError(
                f"{path}:{i + 1}: corrupt journal line (not a torn tail)"
            )


def read_jsonl(path: str, *, skip: str = "tail") -> list[dict[str, Any]]:
    """:func:`iter_jsonl`, materialized."""
    return list(iter_jsonl(path, skip=skip))


def rewrite_json(path: str, obj: Any, *, indent: int = 2) -> None:
    """Atomically rewrite a JSON state file: tmp + fsync + os.replace.

    A process killed mid-write leaves either the old file or the new
    one, never a torn hybrid — and a concurrent reader always sees a
    complete document."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
