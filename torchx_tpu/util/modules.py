"""Dynamic import helpers (reference analog: torchx/util/modules.py).

Library surface for plugin/user code resolving ``pkg.module:attr``
strings and optional-dependency attributes without hard imports. The
launcher's own registries (schedulers, finder) keep their eager variants
on purpose — a broken builtin must raise loudly, while a broken PLUGIN
string degrades to "absent", which is what these helpers encode.
"""

from __future__ import annotations

import importlib
from types import ModuleType
from typing import Any, Callable, Optional, TypeVar, Union

T = TypeVar("T")


def load_module(path: str) -> Optional[Union[ModuleType, Callable[..., Any]]]:
    """Resolve ``full.module.path[:attr]`` to the module or its attribute;
    ``None`` when anything along the way fails to import (callers treat a
    bad plugin string as absent, not fatal)."""
    module_path, _, attr = path.partition(":")
    try:
        module = importlib.import_module(module_path)
        return getattr(module, attr) if attr else module
    except Exception:  # noqa: BLE001 - any import-time failure means "not loadable"
        return None


def import_attr(name: str, attr: str, default: T) -> T:
    """``name.attr`` if the module imports, else ``default``.

    For optional dependencies: a MISSING module yields the default, but a
    module that imports and lacks the attribute raises AttributeError —
    that is a bug in the module, not an absent dependency.
    """
    try:
        mod = importlib.import_module(name)
    except ModuleNotFoundError:
        return default
    return getattr(mod, attr)
