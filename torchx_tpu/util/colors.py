"""ANSI color helpers for CLI output (reference analog: torchx/util/colors.py)."""

from __future__ import annotations

import sys

RESET = "\x1b[0m"
_CODES = {
    "red": 31,
    "green": 32,
    "yellow": 33,
    "blue": 34,
    "magenta": 35,
    "cyan": 36,
    "gray": 90,
}


def supports_color(stream=sys.stdout) -> bool:  # noqa: ANN001
    return hasattr(stream, "isatty") and stream.isatty()


def colored(text: str, color: str, enabled: bool = True) -> str:
    if not enabled or color not in _CODES:
        return text
    return f"\x1b[{_CODES[color]}m{text}{RESET}"


def state_color(state_name: str) -> str:
    """Conventional color for an AppState name."""
    return {
        "RUNNING": "green",
        "SUCCEEDED": "green",
        "FAILED": "red",
        "CANCELLED": "yellow",
        "PREEMPTED": "yellow",
        "PENDING": "cyan",
        "SUBMITTED": "cyan",
    }.get(state_name, "gray")
