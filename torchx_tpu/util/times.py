"""Human time parsing for log windows (reference analog:
torchx/util/datetime.py — generalized from day-granularity to the
``--since 2h`` style every log CLI actually needs).
"""

from __future__ import annotations

import math
import re
from datetime import datetime
from typing import Optional

_REL = re.compile(r"^(\d+)([smhdw])$")
_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}


def parse_when(value: Optional[str], now: Optional[float] = None) -> Optional[float]:
    """-> epoch seconds for ``None``/''/relative/''ISO''/epoch inputs.

    Accepted forms:
      - ``"300"`` / ``"1722333444.5"``  absolute epoch seconds
      - ``"2h"`` ``"30m"`` ``"45s"`` ``"7d"`` ``"1w"``  ago-from-now
      - ``"2026-07-29T10:00:00"`` (any ``datetime.fromisoformat`` string)
    """
    if not value:
        return None
    ts = now if now is not None else datetime.now().timestamp()
    m = _REL.match(value)
    if m:
        return ts - int(m.group(1)) * _UNITS[m.group(2)]
    try:
        f = float(value)
    except ValueError:
        f = None
    if f is not None:
        if not math.isfinite(f):
            raise ValueError(f"non-finite time {value!r}")
        return f
    try:
        return datetime.fromisoformat(value).timestamp()
    except ValueError:
        raise ValueError(
            f"cannot parse time {value!r}; use epoch seconds, a relative"
            " window like 2h/30m/7d, or an ISO timestamp"
        ) from None
