"""Human time parsing for log windows (reference analog:
torchx/util/datetime.py — generalized from day-granularity to the
``--since 2h`` style every log CLI actually needs), plus the shared
jittered poll-interval generator used by ``Runner.wait`` and the
supervisor loop, and the clock helpers every telemetry record is
stamped with (one definition of "now" for events, spans, and metrics).
"""

from __future__ import annotations

import math
import random
import re
import time
from datetime import datetime
from typing import Iterator, Optional

# Wall-clock zero for process-relative stamps: events emitted outside a
# measured block (e.g. supervisor transitions) carry "time since this
# module loaded", so consecutive records can still be diffed.
_WALL_ZERO_NS = time.perf_counter_ns()


def epoch_usec() -> int:
    """Current wall time in integer epoch microseconds — the stamp unit
    shared by :class:`~torchx_tpu.runner.events.api.TpxEvent` and
    :class:`~torchx_tpu.obs.trace.Span`."""
    return int(time.time() * 1e6)


def process_wall_usec() -> int:
    """Monotonic microseconds since this module was first imported
    (process start, for practical purposes)."""
    return (time.perf_counter_ns() - _WALL_ZERO_NS) // 1000


def process_cpu_usec() -> int:
    """This process's total CPU time in microseconds."""
    return time.process_time_ns() // 1000


def stamp_event(event) -> None:  # noqa: ANN001 - TpxEvent; avoids an import cycle
    """Fill any still-``None`` time fields of a telemetry event at emit
    time: ``start_epoch_time_usec`` gets the wall clock, ``wall``/``cpu``
    get process-relative clocks (so instantaneous records — supervisor
    transitions — are diffable). Events measured by ``log_event`` arrive
    with these already set and are left untouched."""
    if event.start_epoch_time_usec is None:
        event.start_epoch_time_usec = epoch_usec()
    if event.wall_time_usec is None:
        event.wall_time_usec = process_wall_usec()
    if event.cpu_time_usec is None:
        event.cpu_time_usec = process_cpu_usec()

_REL = re.compile(r"^(\d+)([smhdw])$")
_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}


def parse_when(value: Optional[str], now: Optional[float] = None) -> Optional[float]:
    """-> epoch seconds for ``None``/''/relative/''ISO''/epoch inputs.

    Accepted forms:
      - ``"300"`` / ``"1722333444.5"``  absolute epoch seconds
      - ``"2h"`` ``"30m"`` ``"45s"`` ``"7d"`` ``"1w"``  ago-from-now
      - ``"2026-07-29T10:00:00"`` (any ``datetime.fromisoformat`` string)
    """
    if not value:
        return None
    ts = now if now is not None else datetime.now().timestamp()
    m = _REL.match(value)
    if m:
        return ts - int(m.group(1)) * _UNITS[m.group(2)]
    try:
        f = float(value)
    except ValueError:
        f = None
    if f is not None:
        if not math.isfinite(f):
            raise ValueError(f"non-finite time {value!r}")
        return f
    try:
        return datetime.fromisoformat(value).timestamp()
    except ValueError:
        raise ValueError(
            f"cannot parse time {value!r}; use epoch seconds, a relative"
            " window like 2h/30m/7d, or an ISO timestamp"
        ) from None


def poll_intervals(
    initial: float = 1.0,
    factor: float = 1.5,
    max_interval: float = 10.0,
    jitter: float = 0.1,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Infinite stream of sleep intervals for a status-poll loop: starts at
    ``initial`` seconds, grows by ``factor`` up to ``max_interval``, with
    each value perturbed by ±``jitter`` fraction so a fleet of clients
    polling the same control plane decorrelates instead of thundering.
    Pass a seeded ``rng`` for deterministic tests."""
    if initial <= 0:
        raise ValueError(f"initial poll interval must be > 0, got {initial}")
    rng = rng or random
    interval = initial
    while True:
        yield max(0.0, interval * (1.0 + rng.uniform(-jitter, jitter)))
        interval = min(interval * factor, max_interval)
