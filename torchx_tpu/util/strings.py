"""Small string helpers (reference analog: torchx/util/strings.py)."""

from __future__ import annotations

import re


def normalize_str(s: str, max_len: int = 63) -> str:
    """Lowercase alnum+dash, trimmed — safe for DNS labels / job names."""
    s = re.sub(r"[^a-z0-9\-]", "-", s.lower())
    s = re.sub(r"-+", "-", s).strip("-")
    return s[:max_len].rstrip("-")


def truncate_middle(s: str, max_len: int) -> str:
    """Keep head and tail when shortening (ids carry entropy at both ends)."""
    if len(s) <= max_len:
        return s
    if max_len <= 3:
        return s[:max_len]
    head = (max_len - 3 + 1) // 2
    tail = max_len - 3 - head
    return s[:head] + "..." + (s[-tail:] if tail else "")
