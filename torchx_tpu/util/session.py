"""Process-wide session id, propagated to subprocesses via env.

Reference analog: torchx/util/session.py — a uuid created once per client
process and forwarded through $TPX_INTERNAL_SESSION_ID so nested runners /
launched jobs correlate telemetry events.
"""

from __future__ import annotations

import os
import uuid
from typing import Optional

from torchx_tpu import settings

_session_id: Optional[str] = None


def get_session_id_or_create_new() -> str:
    global _session_id
    if _session_id is None:
        _session_id = os.environ.get(settings.ENV_TPX_INTERNAL_SESSION_ID) or str(
            uuid.uuid4()
        )
        os.environ[settings.ENV_TPX_INTERNAL_SESSION_ID] = _session_id
    return _session_id


def current_session_id() -> Optional[str]:
    return _session_id
