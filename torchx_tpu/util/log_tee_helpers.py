"""Multi-replica threaded log tee with role/replica prefixes.

Reference analog: torchx/util/log_tee_helpers.py — one thread per replica
streams ``runner.log_lines`` to stdout, each line prefixed ``role/replica``
with a stable ANSI color per replica.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional, TextIO

from torchx_tpu.runner.api import Runner
from torchx_tpu.specs.api import AppStatus, is_started

from torchx_tpu.util.colors import colored

_COLOR_CYCLE = ["cyan", "green", "yellow", "blue", "magenta", "red"]


def _colored(prefix: str, idx: int, enabled: bool) -> str:
    return colored(prefix, _COLOR_CYCLE[idx % len(_COLOR_CYCLE)], enabled)


class LineEmitter:
    """Thread-safe line-atomic writer shared by every replica stream.

    Each :meth:`emit` performs ONE ``write()`` of a complete
    newline-terminated line plus a flush, under one lock — concurrent
    replica threads can interleave whole lines but never partial lines
    (separate write("text") / write("\\n") calls, as ``print`` issues,
    interleave under load even when each call is individually atomic)."""

    def __init__(self, out: TextIO = sys.stdout) -> None:
        self._out = out
        self._lock = threading.Lock()

    def emit(self, prefix: str, line: str) -> None:
        text = f"{prefix} {line.rstrip(chr(10))}\n" if prefix else f"{line.rstrip(chr(10))}\n"
        with self._lock:
            self._out.write(text)
            self._out.flush()


def find_role_replicas(
    app_status: Optional[AppStatus], role_name: Optional[str]
) -> list[tuple[str, int]]:
    """All (role, replica_id) pairs, optionally filtered to one role."""
    out: list[tuple[str, int]] = []
    if app_status is None:
        return out
    for role_status in app_status.roles:
        if role_name and role_status.role != role_name:
            continue
        for r in role_status.replicas:
            out.append((role_status.role, r.id))
    return out


def _stream_one(
    runner: Runner,
    app_handle: str,
    role: str,
    replica: int,
    prefix: str,
    should_tail: bool,
    emitter: LineEmitter,
) -> None:
    try:
        for line in runner.log_lines(
            app_handle, role, replica, should_tail=should_tail
        ):
            emitter.emit(prefix, line)
    except Exception as e:  # noqa: BLE001 - log streaming is best-effort
        emitter.emit(prefix, f"<log stream error: {e}>")


def wait_for_app_started(
    runner: Runner, app_handle: str, poll_interval: float = 0.5, timeout: float = 600
) -> Optional[AppStatus]:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = runner.status(app_handle)
        if status is None:
            return None
        if is_started(status.state):
            return status
        time.sleep(poll_interval)
    return runner.status(app_handle)


def tee_logs(
    runner: Runner,
    app_handle: str,
    role_name: Optional[str] = None,
    should_tail: bool = True,
    out: TextIO = sys.stderr,
    colors: Optional[bool] = None,
) -> threading.Thread:
    """Spawn one streaming thread per replica; returns a supervisor thread
    that joins them all."""
    status = wait_for_app_started(runner, app_handle)
    replicas = find_role_replicas(status, role_name)
    use_colors = colors if colors is not None else out.isatty()
    emitter = LineEmitter(out)
    threads = []
    for idx, (role, replica) in enumerate(replicas):
        prefix = _colored(f"{role}/{replica}", idx, use_colors)
        t = threading.Thread(
            target=_stream_one,
            args=(runner, app_handle, role, replica, prefix, should_tail, emitter),
            daemon=True,
        )
        t.start()
        threads.append(t)

    def _join_all() -> None:
        for t in threads:
            t.join()

    supervisor = threading.Thread(target=_join_all, daemon=True)
    supervisor.start()
    return supervisor
