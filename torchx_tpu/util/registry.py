"""Per-user ``key = value`` registry files with safe concurrent access.

Shared by the local scheduler's app registry and the slurm job-dir
registry (one behavior to maintain). Writers serialize on a sidecar
``.lock`` file (fcntl); compaction rewrites through a temp file +
``os.replace`` so lock-free readers only ever observe a complete old or
new file, never a truncated one.
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Callable, Optional

logger = logging.getLogger(__name__)

COMPACT_THRESHOLD_BYTES = 256 * 1024


def record(
    path: str,
    key: str,
    value: str,
    keep: Optional[Callable[[str], bool]] = None,
) -> None:
    """Append ``key = value``; when the file is large, first drop entries
    whose value fails ``keep`` — writers hold the sidecar lock so
    concurrent appends/compactions cannot lose each other's entries."""
    try:
        with _locked(path):
            if (
                keep is not None
                and os.path.exists(path)
                and os.path.getsize(path) > COMPACT_THRESHOLD_BYTES
            ):
                _compact(path, keep)
            with open(path, "a") as f:
                f.write(f"{key} = {value}\n")
    except OSError as e:
        logger.debug("could not record %s in %s: %s", key, path, e)


def _compact(path: str, keep: Callable[[str], bool]) -> None:
    """Caller holds the lock. tmp + os.replace so readers never see a
    partial file."""
    with open(path) as f:
        lines = f.readlines()
    kept = [ln for ln in lines if keep(ln.partition(" = ")[2].strip())]
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".", prefix=".reg_")
    try:
        with os.fdopen(fd, "w") as f:
            f.writelines(kept)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def remove(path: str, key: str) -> None:
    """Drop every entry for ``key`` (idempotent; missing file is a no-op).
    Rewrites through the same locked tmp + ``os.replace`` path as
    compaction, so readers never observe a partial file."""
    try:
        with _locked(path):
            if not os.path.exists(path):
                return
            with open(path) as f:
                lines = f.readlines()
            kept = [
                ln for ln in lines if ln.partition(" = ")[0].strip() != key
            ]
            if len(kept) == len(lines):
                return
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path) or ".", prefix=".reg_"
            )
            try:
                with os.fdopen(fd, "w") as f:
                    f.writelines(kept)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
    except OSError as e:
        logger.debug("could not remove %s from %s: %s", key, path, e)


def lookup(path: str, key: str) -> Optional[str]:
    try:
        with open(path) as f:
            for line in f:
                k, _, v = line.partition(" = ")
                if k.strip() == key:
                    return v.strip()
    except OSError:
        return None
    return None


def entries(path: str) -> list[tuple[str, str]]:
    """All (key, value) pairs, later entries last (callers may dedup)."""
    try:
        with open(path) as f:
            return [
                (k.strip(), v.strip())
                for line in f
                if " = " in line
                for k, _, v in [line.partition(" = ")]
            ]
    except OSError:
        return []


class _locked:
    """Exclusive sidecar-file lock (best-effort where fcntl is missing)."""

    def __init__(self, path: str) -> None:
        self._lock_path = path + ".lock"
        self._fd: Optional[int] = None

    def __enter__(self) -> "_locked":
        self._fd = os.open(self._lock_path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            import fcntl

            fcntl.flock(self._fd, fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass
        return self

    def __exit__(self, *exc) -> None:  # noqa: ANN002
        if self._fd is not None:
            os.close(self._fd)
