"""Per-user ``key = value`` registry files with safe concurrent access.

Shared by the local scheduler's app registry and the slurm job-dir
registry (one behavior to maintain). Appends and compaction hold an
``fcntl`` exclusive lock so concurrent writers can't drop each other's
entries; lookups are lock-free reads (the file is line-atomic).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

logger = logging.getLogger(__name__)

COMPACT_THRESHOLD_BYTES = 256 * 1024


def record(
    path: str,
    key: str,
    value: str,
    keep: Optional[Callable[[str], bool]] = None,
) -> None:
    """Append ``key = value``; when the file is large, first drop entries
    whose value fails ``keep`` (all kept when keep is None) — under an
    exclusive lock so a concurrent append can't be lost."""
    try:
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            _flock(fd)
            if keep is not None and os.fstat(fd).st_size > COMPACT_THRESHOLD_BYTES:
                with open(path) as f:
                    lines = f.readlines()
                kept = [
                    ln for ln in lines if keep(ln.partition(" = ")[2].strip())
                ]
                os.lseek(fd, 0, os.SEEK_SET)
                os.ftruncate(fd, 0)
                os.write(fd, "".join(kept).encode())
            os.lseek(fd, 0, os.SEEK_END)
            os.write(fd, f"{key} = {value}\n".encode())
        finally:
            os.close(fd)  # releases the lock
    except OSError as e:
        logger.debug("could not record %s in %s: %s", key, path, e)


def lookup(path: str, key: str) -> Optional[str]:
    try:
        with open(path) as f:
            for line in f:
                k, _, v = line.partition(" = ")
                if k.strip() == key:
                    return v.strip()
    except OSError:
        return None
    return None


def entries(path: str) -> list[tuple[str, str]]:
    """All (key, value) pairs, later entries last (callers may dedup)."""
    try:
        with open(path) as f:
            return [
                (k.strip(), v.strip())
                for line in f
                if " = " in line
                for k, _, v in [line.partition(" = ")]
            ]
    except OSError:
        return []


def _flock(fd: int) -> None:
    try:
        import fcntl

        fcntl.flock(fd, fcntl.LOCK_EX)
    except (ImportError, OSError):  # non-POSIX: best-effort without lock
        pass
