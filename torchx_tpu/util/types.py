"""String -> typed-value decoding for component function arguments.

Reference analog: torchx/util/types.py. Component functions declare typed
params (int/str/float/bool/list[str]/dict[str,str]/Optional[...]), the CLI
passes strings, and this module decodes them according to the annotation.
"""

from __future__ import annotations

import inspect
import typing
from typing import Any, Optional, Union


def none_throws(x: Optional[Any], msg: str = "unexpected None") -> Any:
    if x is None:
        raise AssertionError(msg)
    return x


def _unwrap_optional(t: Any) -> Any:
    origin = typing.get_origin(t)
    if origin is Union or origin is getattr(__import__("types"), "UnionType", None):
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return t


def is_bool(t: Any) -> bool:
    return _unwrap_optional(t) is bool


def decode(value: str, annotation: Any) -> Any:
    """Decode a CLI string per the annotation. Non-strings pass through."""
    if not isinstance(value, str):
        return value
    t = _unwrap_optional(annotation)
    if t in (Any, inspect.Parameter.empty, str, None):
        return value
    if t is bool:
        return value.strip().lower() in ("true", "1", "yes", "on")
    if t is int:
        return int(value)
    if t is float:
        return float(value)
    origin = typing.get_origin(t)
    if origin in (list, typing.List):
        (elem_t,) = typing.get_args(t) or (str,)
        if value == "":
            return []
        return [decode(v, elem_t) for v in value.split(",")]
    if origin in (dict, typing.Dict):
        args = typing.get_args(t) or (str, str)
        key_t, val_t = args
        out = {}
        if value == "":
            return out
        for pair in value.split(","):
            if "=" in pair:
                k, _, v = pair.partition("=")
            else:
                k, _, v = pair.partition(":")
            out[decode(k, key_t)] = decode(v, val_t)
        return out
    # fall back: constructor from string (e.g. enums, pathlib.Path)
    try:
        return t(value)
    except Exception:
        return value


def decode_optional(value: Optional[str], annotation: Any) -> Any:
    if value is None:
        return None
    return decode(value, annotation)
