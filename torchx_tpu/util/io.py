"""URL-agnostic file IO over fsspec with a stdlib fallback
(reference analog: torchx/util/io.py, generalized: the reference reads
packaged conf files; TPU jobs also shuttle checkpoints/corpora through
``gs://`` URLs, so these helpers accept any fsspec URL).
"""

from __future__ import annotations

import os
import shutil


def copy_path(src: str, dst: str) -> None:
    """Copy a file (any fsspec URL) or a local directory tree."""
    if os.path.isdir(src):  # local tree: byte-stream open would fail
        shutil.copytree(src, dst, dirs_exist_ok=True)
        return
    try:
        import fsspec

        with fsspec.open(src, "rb") as r, fsspec.open(dst, "wb") as w:
            shutil.copyfileobj(r, w)
        return
    except ImportError:
        pass
    os.makedirs(os.path.dirname(os.path.abspath(dst)) or ".", exist_ok=True)
    shutil.copyfile(src, dst)


def read_text(path: str) -> str:
    """Text contents of a local path or fsspec URL."""
    try:
        import fsspec

        with fsspec.open(path, "r") as f:
            return f.read()
    except ImportError:
        with open(path) as f:
            return f.read()


def exists(path: str) -> bool:
    try:
        import fsspec

        fs, rel = fsspec.core.url_to_fs(path)
        return bool(fs.exists(rel))
    except ImportError:
        return os.path.exists(path)
