"""Notebook helpers: the ``%%workspacefile`` cell magic.

Reference analog: torchx/notebook.py (41 LoC) — lets notebook users build a
workspace incrementally by writing cells into an in-memory (or on-disk)
workspace directory that ``tpx run --workspace`` then packages.

Usage::

    from torchx_tpu.notebook import get_workspace
    ws = get_workspace()          # a temp dir workspace for this kernel

    %%workspacefile main.py
    print("hello")

then ``runner.run_component(..., workspace=str(ws))``.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

_workspace_dir: Optional[str] = None


def get_workspace() -> str:
    """The kernel-lifetime workspace directory (created on first use)."""
    global _workspace_dir
    if _workspace_dir is None:
        _workspace_dir = tempfile.mkdtemp(prefix="tpx_notebook_ws_")
    return _workspace_dir


def workspacefile(line: str, cell: str) -> None:
    """``%%workspacefile relative/path.py`` cell magic body."""
    rel = line.strip()
    if not rel:
        raise ValueError("usage: %%workspacefile <relative-path>")
    ws = get_workspace()
    path = os.path.normpath(os.path.join(ws, rel))
    if os.path.isabs(rel) or not path.startswith(ws + os.sep):
        raise ValueError(
            f"workspace file path must be relative and stay inside the"
            f" workspace, got {rel!r}"
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(cell)
    print(f"wrote {path}")


def load_ipython_extension(ipython) -> None:  # noqa: ANN001
    """``%load_ext torchx_tpu.notebook`` registers the magic."""
    ipython.register_magic_function(workspacefile, magic_kind="cell")
