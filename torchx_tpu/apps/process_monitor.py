"""process_monitor — run a command with timeout / start-on-file / exit-on-file.

Reference analog: torchx/apps/utils/process_monitor.py. Wraps a sidecar
process (e.g. a TensorBoard server) so it starts only once a marker file
exists (the trainer wrote its first logs) and exits once another appears
(training finished) or a timeout lapses — the glue that lets finite jobs
host infinite servers.

    python -m torchx_tpu.apps.process_monitor \
        --timeout 3600 \
        --start_on_file /mnt/logs/STARTED \
        --exit_on_file /mnt/logs/DONE \
        -- tensorboard --logdir /mnt/logs
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _exists(path: str) -> bool:
    if "://" in path:
        try:
            import fsspec

            fs, _, (p,) = fsspec.get_fs_token_paths(path)
            return fs.exists(p)
        except ImportError:
            raise SystemExit("fsspec required for remote marker files")
    return os.path.exists(path)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--timeout", type=float, default=0, help="seconds; 0 = none")
    parser.add_argument("--start_on_file", default=None)
    parser.add_argument("--exit_on_file", default=None)
    parser.add_argument("--poll_interval", type=float, default=5.0)
    parser.add_argument("cmd", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")

    deadline = time.monotonic() + args.timeout if args.timeout else None

    if args.start_on_file:
        while not _exists(args.start_on_file):
            if deadline and time.monotonic() > deadline:
                print(f"timeout waiting for {args.start_on_file}", file=sys.stderr)
                sys.exit(1)
            time.sleep(args.poll_interval)

    proc = subprocess.Popen(cmd)
    try:
        while True:
            rc = proc.poll()
            if rc is not None:
                sys.exit(rc)
            if args.exit_on_file and _exists(args.exit_on_file):
                break
            if deadline and time.monotonic() > deadline:
                break
            time.sleep(args.poll_interval)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    main()
