"""Model-serving registration client.

Reference analog: torchx/apps/serve/serve.py — registers a trained model
archive with a model server's management API (the reference targets
TorchServe; the protocol here is a plain HTTP management endpoint so any
registry-style server works, e.g. a JetStream/vLLM-router sidecar or an
internal registry).

    python -m torchx_tpu.apps.serve_main \
        --model_path gs://bucket/ckpts/llama3-8b/500 \
        --management_api http://server:8081 \
        --model_name llama3-8b

Exits non-zero (and writes the structured error file) if registration is
rejected.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request


def register_model(
    management_api: str,
    model_path: str,
    model_name: str,
    timeout: float = 60.0,
    params: dict[str, str] | None = None,
) -> dict:
    query = {"url": model_path, "model_name": model_name, **(params or {})}
    url = (
        management_api.rstrip("/")
        + "/models?"
        + urllib.parse.urlencode(query)
    )
    req = urllib.request.Request(url, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read().decode()
        try:
            return json.loads(body)
        except json.JSONDecodeError:
            return {"status": body, "code": resp.status}


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model_path", required=True)
    parser.add_argument("--management_api", required=True)
    parser.add_argument("--model_name", required=True)
    parser.add_argument("--timeout", type=float, default=60.0)
    parser.add_argument(
        "--params", default=None, help="extra query params k=v,k2=v2"
    )
    args = parser.parse_args(argv)
    params = None
    if args.params:
        params = {}
        for pair in args.params.split(","):
            if "=" not in pair:
                parser.error(f"--params entry {pair!r} must be k=v")
            k, _, v = pair.partition("=")
            params[k] = v
    try:
        result = register_model(
            args.management_api,
            args.model_path,
            args.model_name,
            timeout=args.timeout,
            params=params,
        )
    except Exception as e:  # noqa: BLE001
        print(f"model registration failed: {e}", file=sys.stderr)
        from torchx_tpu.apps.spmd_main import write_error_file

        write_error_file(e)
        sys.exit(1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
