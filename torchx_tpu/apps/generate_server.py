"""Generation server: HTTP inference over the KV-cache decode loop.

The serving half the reference delegates to TorchServe, rebuilt
TPU-native (JetStream-style, minimal): load a model family config (+
optional orbax checkpoint, optional int8 weight-only quantization), jit
the prefill+decode loop once per shape bucket, and serve token-in/
token-out generation over plain HTTP — no framework dependencies, so the
same binary runs under every scheduler backend.

    python -m torchx_tpu.apps.generate_server \
        --config llama_tiny [--ckpt-dir DIR] [--int8] [--port 8000]

API (JSON):
    GET  /healthz            -> {"status": "ok", "model": ..., "requests": N}
                                (503 {"status": "draining"} during SIGTERM
                                grace)
    GET  /metricz            -> tpx_* metrics, Prometheus text format
    POST /v1/generate        {"tokens": [[...]], "max_new_tokens": 16,
                              "temperature": 0.0}
                          or {"text": "...", ...} (byte-level codec, the
                              same tokenization datapreproc defaults to)
                          -> {"tokens": [[...]]} / {"text": [...]}
    POST /v1/kv              (decode role) serialized KvPayload handoff
                              from a prefill replica -> the decode
                              completion; 503 while draining so the
                              sender requeues elsewhere

Disaggregated serving (``--serve-role prefill|decode``): prefill
replicas take /v1/generate traffic, run the cache-aware chunked prefill
(shared prompt prefixes hit the radix prefix cache and skip
recomputation), then stream the computed KV blocks to a decode replica
over ``--kv-transfer`` and relay its completion. Decode replicas accept
handoffs on /v1/kv (or a file: spool) and batch pure decode steps.

Two serving engines, selected by ``--engine``:

* ``continuous`` (default): the :mod:`torchx_tpu.serve.engine`
  continuous-batching loop — a fixed ``--max-batch`` slot array decoding
  over a paged KV cache, with requests admitted into free slots between
  steps and completions returned the step they finish. Arbitrary prompt
  lengths, temperatures, and seeds share one device step.
* ``coalesce``: the legacy batch-to-completion batcher — compatible
  sequences (same prompt length / max_new / temperature) from concurrent
  clients merge into one device batch within a few-ms window, and each
  batch decodes to completion before the next dispatch. Kept as the
  serving-bench baseline and for bit-exact parity with
  :func:`torchx_tpu.models.generate.generate`.

On SIGTERM the server drains instead of dying mid-request: admission
stops, ``/healthz`` flips to 503 (so routers and the serve pool stop
sending traffic), in-flight slots decode to completion, then the process
exits 0.
"""

from __future__ import annotations

import argparse
import codecs
import dataclasses
import json
import logging
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)


def _assert_platform() -> None:
    """Make the launcher's JAX_PLATFORMS choice stick even when a site
    hook programmatically forced another platform (the same defense as
    spmd_main — this app is launched directly, not through the spmd
    bootstrap)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)


class ServiceDraining(RuntimeError):
    """Raised for requests arriving during the SIGTERM drain window; the
    HTTP layer maps it to 503 so load balancers retry elsewhere."""


@dataclasses.dataclass
class _Pending:
    """One sequence awaiting decode, owned by a handler thread until the
    batcher thread fills ``result`` (or ``error``) and sets ``done``."""

    tokens: list[int]
    key: tuple  # (prompt_len, max_new_tokens, temperature) — seed is NOT
    # part of the key: rows carry their own seed and sample from their own
    # folded stream, so differently-seeded requests share a device batch
    seed: int = 0
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Optional[list[int]] = None
    error: Optional[Exception] = None
    # serving-latency telemetry (bench_serving.py percentiles): when this
    # sequence entered the queue, when its device batch dispatched, and
    # when the batch finished — queue_ms = coalescing/backlog wait,
    # total_ms = request-observed latency
    t_enqueue: float = 0.0
    t_dispatch: float = 0.0
    t_done: float = 0.0


class GenerateService:
    """Model + serving engine, shared by all handler threads.

    ``engine="continuous"`` (default) runs the
    :class:`torchx_tpu.serve.engine.ServeEngine` continuous-batching loop:
    ``max_batch`` decode slots over a paged KV pool, admission/eviction
    every step, any mix of prompt lengths / temperatures / seeds in one
    compiled step.

    ``engine="coalesce"`` keeps the legacy batch-to-completion batcher:
    handler threads enqueue sequences and a single batcher thread drains
    the queue in a short window, merging compatible sequences (same prompt
    length / max_new / temperature) into ONE device batch that decodes to
    completion before the next dispatch.

    Seed semantics (both engines): every sequence samples from its own
    per-row PRNG stream derived from its request seed, so a (prompt, seed,
    temperature) triple reproduces the same tokens regardless of what
    other traffic it batched with. In coalesce mode a lone request is
    token-identical to :func:`torchx_tpu.models.generate.generate` at the
    same seed (per-row keys stack to exactly the single-key draw).
    """

    def __init__(
        self,
        config: str,
        ckpt_dir: Optional[str] = None,
        int8: bool = False,
        seed: int = 0,
        batch_window_ms: float = 3.0,
        max_batch: int = 16,
        engine: str = "continuous",
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        serve_role: str = "unified",
        kv_transfer: Optional[str] = None,
        enable_prefix_cache: bool = True,
        prefix_cache_reserve: float = 0.0,
    ) -> None:
        if engine not in ("continuous", "coalesce"):
            raise ValueError(
                f"unknown engine {engine!r}; have 'continuous', 'coalesce'"
            )
        if serve_role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"unknown serve role {serve_role!r}; have 'unified',"
                f" 'prefill', 'decode'"
            )
        if serve_role != "unified" and engine != "continuous":
            raise ValueError(
                f"serve role {serve_role!r} requires the continuous engine"
                f" (got {engine!r})"
            )
        if serve_role == "prefill" and not kv_transfer:
            raise ValueError(
                "prefill role needs a --kv-transfer spec (decode targets)"
            )
        from torchx_tpu.examples.train_llama import all_configs

        configs = all_configs()
        if config not in configs:
            raise ValueError(f"unknown config {config!r}; have {sorted(configs)}")
        self.cfg = configs[config]()
        self.name = config
        from torchx_tpu.models import llama

        if ckpt_dir:
            from torchx_tpu.parallel.checkpoint import Checkpointer

            abstract = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
            ckpt = Checkpointer(ckpt_dir)
            step, params = ckpt.restore_latest(abstract)
            ckpt.close()
            if params is None:
                raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
            self.params = params
            self.ckpt_step = step
        else:
            self.params = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
            self.ckpt_step = None
        if int8:
            from torchx_tpu.ops.quant import quantize_params

            self.params = quantize_params(self.params)
        self.int8 = int8
        self._cache_lock = threading.Lock()  # handlers run concurrently
        self._jit_cache: dict[tuple, Any] = {}
        self.requests = 0
        self.batches = 0  # device dispatches (< enqueued seqs when coalesced)
        self.batched_sequences = 0
        self.batch_window_s = batch_window_ms / 1000.0
        self.max_batch = max_batch
        self.engine_mode = engine
        self.serve_role = serve_role
        self.draining = False
        self._closed = False
        self._count_lock = threading.Lock()
        self._engine = None
        # prefill role: KV handoffs in flight to decode replicas — the
        # disaggregated twin of the engine's _prefilling counter; drain()
        # must wait these out or a mid-transfer SIGTERM drops the request
        self._transferring = 0
        self._transfer_done = threading.Condition()
        self._transfer = None
        self._spool_stop: Optional[threading.Event] = None
        self._spool_thread: Optional[threading.Thread] = None
        if engine == "continuous":
            from torchx_tpu.serve.engine import ServeEngine

            self._engine = ServeEngine(
                self.params,
                self.cfg,
                max_slots=max_batch,
                block_size=block_size,
                num_blocks=num_blocks,
                enable_prefix_cache=enable_prefix_cache,
                prefix_cache_reserve=prefix_cache_reserve,
            ).start()
            if serve_role == "prefill":
                from torchx_tpu.serve.kv_transfer import (
                    TransferConfig,
                    make_transfer,
                )

                self._transfer = make_transfer(
                    TransferConfig.from_spec(kv_transfer)
                )
            elif serve_role == "decode" and kv_transfer:
                # a decode role given a file: spec pumps the spool dir
                # itself (HTTP decode targets are served by /v1/kv)
                from torchx_tpu.serve import kv_transfer as kvt

                tcfg = kvt.TransferConfig.from_spec(kv_transfer)
                if tcfg.mode == "file":
                    self._spool_stop = threading.Event()
                    self._spool_thread = threading.Thread(
                        target=kvt.serve_spool,
                        args=(
                            tcfg.endpoints[0],
                            self.handle_kv_payload,
                            self._spool_stop,
                        ),
                        name="tpx-kv-spool",
                        daemon=True,
                    )
                    self._spool_thread.start()
            return
        self._submit_lock = threading.Lock()  # orders enqueue vs close
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._batcher = threading.Thread(
            target=self._batch_loop, name="tpx-batcher", daemon=True
        )
        self._batcher.start()

    def close(self) -> None:
        """Stop the serving engine (idempotent). Work enqueued before close
        drains to completion; work racing close fails fast — never hangs."""
        if self._engine is not None:
            self._closed = True
            if self._spool_stop is not None:
                self._spool_stop.set()
            self._engine.drain(timeout=60)
            self._wait_transfers(timeout=60)
            self._engine.stop()
            if self._spool_thread is not None:
                self._spool_thread.join(timeout=5)
            return
        with self._submit_lock:
            # under the same lock generate() enqueues with, so every put
            # either lands before the sentinel (drained by the batcher) or
            # observes _closed and raises
            self._closed = True
            self._queue.put(None)
        self._batcher.join(timeout=60)
        if self._batcher.is_alive():
            # a dispatch (e.g. cold compile) outlived the join budget; the
            # loop will finish it, drain its backlog, and exit on the
            # sentinel — nothing is stranded, we just stop waiting
            logger.warning("batcher still draining at close(); detaching")

    def drain(self, grace_s: float = 30.0) -> bool:
        """SIGTERM grace: stop admitting (:attr:`draining` flips healthz to
        503 and fails new requests fast), finish everything in flight.
        True when fully drained within ``grace_s``."""
        self.draining = True
        if self._engine is not None:
            if self._spool_stop is not None:
                self._spool_stop.set()
            t0 = time.monotonic()
            ok = self._engine.drain(timeout=grace_s)
            # prefill role: engine-drained handoffs may still be streaming
            # to decode replicas; they count as in-flight until the reply
            ok = (
                self._wait_transfers(
                    timeout=max(0.0, grace_s - (time.monotonic() - t0))
                )
                and ok
            )
            return ok
        deadline = time.monotonic() + grace_s
        with self._submit_lock:
            self._closed = True
            self._queue.put(None)
        self._batcher.join(timeout=max(0.0, deadline - time.monotonic()))
        return not self._batcher.is_alive()

    def _wait_transfers(self, timeout: float) -> bool:
        """Block until every in-flight KV handoff has its decode reply
        (prefill role; trivially True elsewhere)."""
        deadline = time.monotonic() + timeout
        with self._transfer_done:
            while self._transferring > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._transfer_done.wait(remaining)
        return True

    def handle_kv_payload(self, payload: Any) -> dict:
        """Decode role: admit one prefilled handoff and decode it out.

        Raises :class:`~torchx_tpu.serve.kv_transfer.TransferRejected`
        while draining so the prefill side requeues to another decode
        replica — the drain-race contract."""
        from torchx_tpu.serve.engine import serve_kv_payload
        from torchx_tpu.serve.kv_transfer import TransferRejected

        if self.serve_role != "decode":
            raise TransferRejected(
                f"replica role is {self.serve_role!r}, not decode"
            )
        if self.draining or self._closed:
            raise TransferRejected("decode replica draining; requeue")
        with self._count_lock:
            self.requests += 1
        # decode inside the handoff's originating trace, so the request's
        # stitched timeline covers router -> prefill -> transfer -> decode
        from torchx_tpu.serve.kv_transfer import payload_span

        with payload_span(payload, "serve.decode"):
            return serve_kv_payload(self._engine, payload)

    # -- batcher thread ----------------------------------------------------

    def _batch_loop(self) -> None:
        """Single dispatcher: groups compatible pendings, keeps a local
        backlog for incompatible ones so the OLDEST deferred key becomes
        the next group head (no starvation under a sustained stream of one
        key), and on shutdown drains queue + backlog before exiting."""
        from collections import deque

        backlog: "deque[_Pending]" = deque()
        shutdown = False
        while True:
            if backlog:
                item = backlog.popleft()
            elif shutdown:
                return
            else:
                item = self._queue.get()
                if item is None:
                    return
            group = [item]
            deadline = time.monotonic() + self.batch_window_s
            # adopt compatible backlog items first (they are oldest)
            for p in list(backlog):
                if len(group) >= self.max_batch:
                    break
                if p.key == item.key:
                    backlog.remove(p)
                    group.append(p)
            while not shutdown and len(group) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    shutdown = True  # drain backlog, then exit above
                    break
                if nxt.key == item.key:
                    group.append(nxt)
                else:
                    backlog.append(nxt)
            self._dispatch(group)

    def _dispatch(self, group: list[_Pending]) -> None:
        _, max_new, temperature = group[0].key
        now = time.monotonic()
        for p in group:
            p.t_dispatch = now
        try:
            fn = self._decode_fn(max_new, temperature)
            rows = [p.tokens for p in group]
            # pad the group to a power-of-2 bucket (row 0 repeated): group
            # size depends on request-arrival jitter, and each distinct
            # batch shape is a fresh XLA compile — bucketing caps the jit
            # cache at log2(max_batch) shapes per (max_new, temperature)
            # instead of one per observed group size
            bucket = 1
            while bucket < len(rows):
                bucket *= 2
            # never exceed the operator's ceiling (max_batch bounds
            # KV-cache HBM): a non-power-of-2 max_batch clamps here
            bucket = min(bucket, self.max_batch)
            rows = rows + [rows[0]] * (bucket - len(rows))
            batch = jnp.asarray(rows, dtype=jnp.int32)
            if temperature <= 0:
                rng = jax.random.PRNGKey(0)  # greedy never reads it
            else:
                # one PRNG stream per row, from each request's own seed —
                # differently-seeded requests coalesce, and each row draws
                # exactly what a solo call with its seed would
                seeds = [p.seed for p in group]
                seeds += [group[0].seed] * (bucket - len(group))
                rng = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
            out = jax.device_get(fn(self.params, batch, rng))
            self.batches += 1
            self.batched_sequences += len(group)
            for row, p in enumerate(group):
                p.result = [int(x) for x in out[row]]
        except Exception as e:  # noqa: BLE001 - surfaced per-request
            for p in group:
                p.error = e
        finally:
            now = time.monotonic()
            for p in group:
                p.t_done = now
                p.done.set()

    _JIT_CACHE_MAX = 32

    def _decode_fn(self, max_new_tokens: int, temperature: float):
        """One jitted generate per (max_new, temperature); jax's own cache
        handles distinct (batch, prompt_len) shapes under each entry.

        Request-supplied floats key the cache, so temperature is rounded
        (1e-3 is far below sampling noise) and the cache is FIFO-bounded —
        adversarial parameter sweeps cannot grow compile state without
        bound."""
        from torchx_tpu.models import generate as gen

        key = (max_new_tokens, round(temperature, 3))
        with self._cache_lock:
            fn = self._jit_cache.get(key)
            if fn is None:
                if len(self._jit_cache) >= self._JIT_CACHE_MAX:
                    self._jit_cache.pop(next(iter(self._jit_cache)))
                fn = jax.jit(
                    lambda p, b, rng: gen.generate(
                        p,
                        b,
                        self.cfg,
                        max_new_tokens=max_new_tokens,
                        temperature=key[1],
                        rng=rng,
                    )
                )
                self._jit_cache[key] = fn
            return fn

    def generate(
        self,
        tokens: list[list[int]],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
    ) -> list[list[int]]:
        return self.generate_timed(
            tokens, max_new_tokens, temperature=temperature, seed=seed,
            eos_id=eos_id,
        )[0]

    def generate_timed(
        self,
        tokens: list[list[int]],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
    ) -> tuple[list[list[int]], dict]:
        """:meth:`generate` plus per-request latency telemetry:
        ``{"queue_ms", "total_ms", "ttft_ms"}`` — the admission/backlog
        wait, the end-to-end latency of the request's slowest sequence,
        and the time to its first decoded token. The HTTP layer attaches
        it to responses as ``timing`` so serving benchmarks can report
        percentiles without server-side scraping. ``eos_id`` stops a
        sequence early on that token (continuous engine only; the
        coalescing baseline always decodes the full budget)."""
        if not tokens or any(not t for t in tokens):
            raise ValueError("tokens must be non-empty sequences")
        longest = max(len(t) for t in tokens)
        if longest + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt length {longest} + {max_new_tokens} new tokens"
                f" exceeds max_seq {self.cfg.max_seq}"
            )
        if self.draining:
            raise ServiceDraining("server is draining; retry elsewhere")
        if self._closed:
            raise RuntimeError("generate service is closed")
        with self._count_lock:
            self.requests += 1
        if self._engine is not None:
            return self._generate_engine(
                tokens, max_new_tokens, temperature, seed, eos_id
            )
        # one _Pending per sequence, keyed by EXACT length (padding would
        # pollute the causal context — correctness over cleverness; one
        # compile per distinct (length, max_new) pair, cached by jit). The
        # batcher thread merges compatible sequences ACROSS requests into
        # single device batches.
        t_enqueue = time.monotonic()
        pendings = [
            _Pending(
                tokens=list(t),
                key=(len(t), max_new_tokens, round(temperature, 3)),
                seed=seed,
                t_enqueue=t_enqueue,
            )
            for t in tokens
        ]
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("generate service is closed")
            for p in pendings:
                self._queue.put(p)
        for p in pendings:
            p.done.wait()
        errors = [p.error for p in pendings if p.error is not None]
        if errors:
            raise errors[0]
        # request-level timing: the slowest sequence bounds the response.
        # batch-to-completion delivers all tokens at once, so the first
        # token arrives when the batch does: ttft == total
        total_ms = round(
            max((p.t_done - p.t_enqueue) for p in pendings) * 1e3, 2
        )
        timing = {
            "queue_ms": round(
                max((p.t_dispatch - p.t_enqueue) for p in pendings) * 1e3, 2
            ),
            "total_ms": total_ms,
            "ttft_ms": total_ms,
        }
        return [p.result for p in pendings], timing

    def _generate_engine(
        self,
        tokens: list[list[int]],
        max_new_tokens: int,
        temperature: float,
        seed: int,
        eos_id: Optional[int],
    ) -> tuple[list[list[int]], dict]:
        from torchx_tpu.serve.engine import EngineStopped, ServeRequest

        if self.serve_role == "prefill":
            return self._generate_disagg(
                tokens, max_new_tokens, temperature, seed, eos_id
            )
        reqs = [
            ServeRequest(
                prompt=list(t),
                max_new_tokens=max_new_tokens,
                temperature=round(temperature, 3),
                seed=seed,
                eos_id=eos_id,
            )
            for t in tokens
        ]
        try:
            for r in reqs:
                self._engine.submit(r)
        except EngineStopped as e:
            raise ServiceDraining(str(e)) from e
        for r in reqs:
            r.wait()
        errors = [r.error for r in reqs if r.error is not None]
        if errors:
            raise RuntimeError(errors[0])
        with self._count_lock:
            self.batches = self._engine.steps
            self.batched_sequences += len(reqs)
        timing = {
            "queue_ms": round(max(r.ttft_s for r in reqs) * 1e3, 2),
            "total_ms": round(
                max(r.t_done - r.t_enqueue for r in reqs) * 1e3, 2
            ),
            "ttft_ms": round(max(r.ttft_s for r in reqs) * 1e3, 2),
        }
        return [r.tokens for r in reqs], timing

    def _generate_disagg(
        self,
        tokens: list[list[int]],
        max_new_tokens: int,
        temperature: float,
        seed: int,
        eos_id: Optional[int],
    ) -> tuple[list[list[int]], dict]:
        """Prefill role: run the cache-aware prefill locally, then stream
        each computed KV payload to a decode replica and relay its
        completion. TTFT is the locally-sampled first token; the decode
        gang owns the rest of the latency."""
        from torchx_tpu.serve.engine import EngineStopped, ServeRequest

        reqs = [
            ServeRequest(
                prompt=list(t),
                max_new_tokens=max_new_tokens,
                temperature=round(temperature, 3),
                seed=seed,
                eos_id=eos_id,
                prefill_only=True,
            )
            for t in tokens
        ]
        t0 = time.monotonic()
        # the handoff window counts as in-flight for drain(): a SIGTERM
        # between prefill completion and the decode reply must not drop
        # the request (the disaggregated twin of _prefilling)
        with self._transfer_done:
            self._transferring += len(reqs)
        try:
            try:
                for r in reqs:
                    self._engine.submit(r)
            except EngineStopped as e:
                raise ServiceDraining(str(e)) from e
            outs: list[list[int]] = []
            ttft = 0.0
            for r in reqs:
                r.wait()
                if r.error is not None:
                    raise RuntimeError(r.error)
                ttft = max(ttft, r.ttft_s)
                if r.handoff is None:  # finished at the first token
                    outs.append(r.tokens)
                    continue
                result = self._transfer.send(r.handoff)
                # transfer replies carry generated tokens only; restore
                # the prompt+generated shape the unified path returns
                outs.append(list(r.prompt) + [int(x) for x in result["tokens"]])
        finally:
            with self._transfer_done:
                self._transferring -= len(reqs)
                self._transfer_done.notify_all()
        with self._count_lock:
            self.batches = self._engine.steps
            self.batched_sequences += len(reqs)
        total_ms = round((time.monotonic() - t0) * 1e3, 2)
        timing = {
            "queue_ms": round(ttft * 1e3, 2),
            "total_ms": total_ms,
            "ttft_ms": round(ttft * 1e3, 2),
        }
        return outs, timing

    def generate_stream(
        self,
        tokens: list[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
        chunk: int = 8,
    ):
        """Yield lists of new token ids as they decode (single sequence).

        Streaming bypasses the batcher — a stream holds the device for its
        whole decode, so it trades coalescing for time-to-first-token;
        token-identical to the batch path at the same seed."""
        if self.draining:
            raise ServiceDraining("server is draining; retry elsewhere")
        if self._closed:
            raise RuntimeError("generate service is closed")
        if not tokens:
            raise ValueError("tokens must be a non-empty sequence")
        if len(tokens) + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt length {len(tokens)} + {max_new_tokens} new tokens"
                f" exceeds max_seq {self.cfg.max_seq}"
            )
        from torchx_tpu.models import generate as gen

        with self._count_lock:
            self.requests += 1
        batch = jnp.asarray([tokens], dtype=jnp.int32)
        # gen.generate_stream ALSO validates eagerly (chunk/max_new/max_seq)
        # before returning its generator, so every argument error surfaces
        # here — before the caller commits an HTTP status line
        it = gen.generate_stream(
            self.params,
            batch,
            self.cfg,
            max_new_tokens=max_new_tokens,
            temperature=round(temperature, 3),
            rng=jax.random.PRNGKey(seed),
            chunk=chunk,
        )

        def rows():
            for piece in it:
                yield [int(x) for x in piece[0]]

        return rows()


def _make_handler(service: GenerateService):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt: str, *args: Any) -> None:  # quiet
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802
            if self.path == "/healthz":
                body = {
                    "status": "draining" if service.draining else "ok",
                    "model": service.name,
                    "engine": service.engine_mode,
                    "serve_role": service.serve_role,
                    "int8": service.int8,
                    "ckpt_step": service.ckpt_step,
                    "requests": service.requests,
                    "batches": service.batches,
                    "batched_sequences": service.batched_sequences,
                }
                if service._engine is not None:
                    body.update(service._engine.stats())
                    # cache-aware routing inputs: what this replica holds
                    body["block_size"] = service._engine.block_size
                    body["prefix_summary"] = service._engine.prefix_summary()
                # a draining replica must fail its health check so routers
                # and the serve pool stop sending it traffic
                self._reply(503 if service.draining else 200, body)
            elif self.path == "/metricz":
                from torchx_tpu.obs.metrics import REGISTRY

                text = REGISTRY.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _handle_kv(self) -> None:
            """Decode-role KV handoff intake (``HttpTransfer`` sender):
            octet-stream payload in, decode completion out; 503 while
            draining so the prefill side requeues elsewhere."""
            from torchx_tpu.serve.kv_transfer import KvPayload, TransferRejected

            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = KvPayload.from_bytes(self.rfile.read(n))
                self._reply(200, service.handle_kv_payload(payload))
            except TransferRejected as e:
                self._reply(503, {"error": str(e)})
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface, don't kill the server
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        def _stream(self, tokens: list[int], req: dict, text_mode: bool) -> None:
            """JSONL streaming response (one line per decoded chunk,
            terminated by {\"done\": true}); connection closes at the end.

            The iterator is created BEFORE the 200 goes out — validation
            errors still surface as a clean 400. Once streaming has begun
            no status line may be written; mid-stream failures just end
            the stream (the missing done marker tells the client)."""
            it = service.generate_stream(
                tokens,
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                temperature=float(req.get("temperature", 0.0)),
                seed=int(req.get("seed", 0)),
                # clamp: chunk < 1 would raise, huge chunks defeat streaming
                chunk=max(1, min(int(req.get("stream_chunk", 8)), 64)),
            )
            self._streamed = True  # no _reply may run after this point
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.send_header("Connection", "close")
            self.end_headers()
            # multibyte UTF-8 sequences can split across chunk boundaries;
            # an incremental decoder carries the partial bytes over
            decoder = codecs.getincrementaldecoder("utf-8")("replace")
            try:
                for piece in it:
                    if text_mode:
                        payload = {
                            "text_delta": decoder.decode(
                                bytes(b for b in piece if 0 <= b < 256)
                            )
                        }
                    else:
                        payload = {"tokens": piece}
                    self.wfile.write(json.dumps(payload).encode() + b"\n")
                    self.wfile.flush()
                if text_mode:
                    tail = decoder.decode(b"", final=True)
                    if tail:
                        self.wfile.write(
                            json.dumps({"text_delta": tail}).encode() + b"\n"
                        )
                self.wfile.write(b'{"done": true}\n')
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-stream; nothing to reply to

        def do_POST(self) -> None:  # noqa: N802
            if self.path == "/v1/kv":
                self._handle_kv()
                return
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                text_mode = "text" in req and "tokens" not in req
                if text_mode:
                    texts = req["text"]
                    if isinstance(texts, str):
                        texts = [texts]
                    tokens = [list(t.encode("utf-8")) for t in texts]
                else:
                    tokens = req["tokens"]
                if req.get("stream"):
                    if len(tokens) != 1:
                        self._reply(
                            400,
                            {"error": "stream mode takes exactly one sequence"},
                        )
                        return
                    self._stream(tokens[0], req, text_mode)
                    return
                eos = req.get("eos_id")
                # adopt the router's trace context (HTTP headers): the
                # replica's span — and, on a prefill role, the KV
                # transfer it triggers — join the request's one trace
                from torchx_tpu.obs import trace as obs_trace

                tid, sid = obs_trace.extract_headers(self.headers)
                with obs_trace.trace_context(tid, sid):
                    with obs_trace.span(
                        f"serve.{service.serve_role}", sequences=len(tokens)
                    ):
                        out, timing = service.generate_timed(
                            tokens,
                            max_new_tokens=int(req.get("max_new_tokens", 16)),
                            temperature=float(req.get("temperature", 0.0)),
                            seed=int(req.get("seed", 0)),
                            eos_id=None if eos is None else int(eos),
                        )
                if text_mode:
                    self._reply(
                        200,
                        {
                            "text": [
                                bytes(
                                    b for b in seq if 0 <= b < 256
                                ).decode("utf-8", errors="replace")
                                for seq in out
                            ],
                            "timing": timing,
                        },
                    )
                else:
                    self._reply(200, {"tokens": out, "timing": timing})
            except ServiceDraining as e:
                self._reply(503, {"error": str(e)})
            except (KeyError, ValueError, TypeError) as e:
                if getattr(self, "_streamed", False):
                    logger.warning("stream aborted mid-flight: %s", e)
                else:
                    self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface, don't kill the server
                if getattr(self, "_streamed", False):
                    logger.error(
                        "stream aborted mid-flight: %s: %s", type(e).__name__, e
                    )
                else:
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def serve(
    config: str,
    port: int = 8000,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    ready_event: Optional[threading.Event] = None,
    batch_window_ms: float = 3.0,
    max_batch: int = 16,
    engine: str = "continuous",
    block_size: int = 16,
    num_blocks: Optional[int] = None,
    serve_role: str = "unified",
    kv_transfer: Optional[str] = None,
    enable_prefix_cache: bool = True,
    prefix_cache_reserve: float = 0.0,
) -> ThreadingHTTPServer:
    service = GenerateService(
        config,
        ckpt_dir=ckpt_dir,
        int8=int8,
        batch_window_ms=batch_window_ms,
        max_batch=max_batch,
        engine=engine,
        block_size=block_size,
        num_blocks=num_blocks,
        serve_role=serve_role,
        kv_transfer=kv_transfer,
        enable_prefix_cache=enable_prefix_cache,
        prefix_cache_reserve=prefix_cache_reserve,
    )
    server = ThreadingHTTPServer(("", port), _make_handler(service))
    server.service = service  # for tests / shutdown hooks
    if ready_event is not None:
        ready_event.set()
    return server


def make_drain(
    server: ThreadingHTTPServer,
    service: GenerateService,
    grace_s: float = 30.0,
) -> Any:
    """The SIGTERM drain sequence, as a callable (testable without
    signals): stop admission + fail ``/healthz``, let in-flight slots
    decode out, then shut the HTTP loop down so :func:`main` returns and
    the process exits 0 inside the preemption notice window."""

    def _drain() -> None:
        logger.warning("SIGTERM: draining (grace %.0fs)", grace_s)
        ok = service.drain(grace_s)
        if not ok:
            logger.warning("drain grace expired with requests in flight")
        server.shutdown()

    return _drain


def _install_drain_handler(
    server: ThreadingHTTPServer,
    service: GenerateService,
    grace_s: float = 30.0,
) -> bool:
    """Arm SIGTERM -> graceful drain (mirrors train_llama's preemption
    handler: main thread only, previous handler semantics preserved by
    process exit). The handler thread exists because ``server.shutdown``
    must not run on the thread ``serve_forever`` occupies."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return False
    drain = make_drain(server, service, grace_s)

    def _on_sigterm(signum, frame):  # noqa: ANN001
        threading.Thread(target=drain, name="tpx-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # no signal support here
        return False
    return True


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="generate_server", description=__doc__)
    parser.add_argument("--config", required=True, help="model config name")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--int8", action="store_true", help="int8 weight-only")
    parser.add_argument(
        "--batch-window-ms",
        type=float,
        default=3.0,
        help="how long the coalescing batcher waits for concurrent requests",
    )
    parser.add_argument(
        "--max-batch",
        type=int,
        default=16,
        help="decode slots (continuous) / max sequences per batch (coalesce)",
    )
    parser.add_argument(
        "--engine",
        choices=("continuous", "coalesce"),
        default="continuous",
        help="continuous batching over paged KV (default), or the legacy"
        " batch-to-completion coalescer",
    )
    parser.add_argument(
        "--block-size", type=int, default=16, help="paged KV-cache block size"
    )
    parser.add_argument(
        "--num-blocks",
        type=int,
        default=None,
        help="paged KV pool size in blocks (default: sized from max-batch)",
    )
    parser.add_argument(
        "--serve-role",
        choices=("unified", "prefill", "decode"),
        default="unified",
        help="disaggregated serving role: 'prefill' computes prompt KV and"
        " streams it out over --kv-transfer, 'decode' accepts handoffs on"
        " /v1/kv; 'unified' (default) does both in one replica",
    )
    parser.add_argument(
        "--kv-transfer",
        default=None,
        help="KV transfer spec: local | file:<dir> |"
        " http:<url>[,<url>...] (decode replica base URLs)",
    )
    parser.add_argument(
        "--no-prefix-cache",
        action="store_true",
        help="disable the radix prefix cache (every prompt prefills cold)",
    )
    parser.add_argument(
        "--prefix-cache-reserve",
        type=float,
        default=0.0,
        help="cap cached prefix blocks at this fraction of the KV pool"
        " (0 = share the whole pool, evicting under pressure)",
    )
    parser.add_argument(
        "--drain-grace-s",
        type=float,
        default=30.0,
        help="SIGTERM drain budget before shutdown proceeds anyway",
    )
    parser.add_argument(
        "--port-stride",
        type=int,
        default=0,
        help="listen on port + stride * TPX_REPLICA_ID, so a serve pool's"
        " replicas co-located by the local scheduler get distinct ports",
    )
    args = parser.parse_args(argv)
    if args.port_stride and args.port:
        from torchx_tpu.settings import ENV_TPX_REPLICA_ID

        replica_id = int(os.environ.get(ENV_TPX_REPLICA_ID, "0") or "0")
        args.port += args.port_stride * replica_id
    _assert_platform()
    t0 = time.monotonic()
    server = serve(
        args.config,
        args.port,
        args.ckpt_dir,
        args.int8,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
        engine=args.engine,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        serve_role=args.serve_role,
        kv_transfer=args.kv_transfer,
        enable_prefix_cache=not args.no_prefix_cache,
        prefix_cache_reserve=args.prefix_cache_reserve,
    )
    _install_drain_handler(server, server.service, args.drain_grace_s)
    # report the BOUND port: with --port 0 the OS picks one, and whatever
    # launched us (serve pool, smoke test) reads it from this line
    port = server.server_address[1]
    print(
        f"generate_server: {args.config} [{args.engine}] on :{port}"
        f" (loaded in {time.monotonic() - t0:.1f}s)",
        flush=True,
    )
    server.serve_forever()
    server.server_close()


if __name__ == "__main__":
    main()
