"""Generation server: HTTP inference over the KV-cache decode loop.

The serving half the reference delegates to TorchServe, rebuilt
TPU-native (JetStream-style, minimal): load a model family config (+
optional orbax checkpoint, optional int8 weight-only quantization), jit
the prefill+decode loop once per shape bucket, and serve token-in/
token-out generation over plain HTTP — no framework dependencies, so the
same binary runs under every scheduler backend.

    python -m torchx_tpu.apps.generate_server \
        --config llama_tiny [--ckpt-dir DIR] [--int8] [--port 8000]

API (JSON):
    GET  /healthz            -> {"status": "ok", "model": ..., "requests": N}
    POST /v1/generate        {"tokens": [[...]], "max_new_tokens": 16,
                              "temperature": 0.0}
                          or {"text": "...", ...} (byte-level codec, the
                              same tokenization datapreproc defaults to)
                          -> {"tokens": [[...]]} / {"text": [...]}

Same-length prompts batch together; each distinct (prompt_len,
max_new_tokens) pair compiles once and is then served from the jit cache.
Requests run under a lock — one chip, one model, sequential batches
(continuous batching is the next rung; see docs/ROADMAP.md).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import jax
import jax.numpy as jnp


def _assert_platform() -> None:
    """Make the launcher's JAX_PLATFORMS choice stick even when a site
    hook programmatically forced another platform (the same defense as
    spmd_main — this app is launched directly, not through the spmd
    bootstrap)."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)


class GenerateService:
    """Model + jitted decode, shared by all handler threads."""

    def __init__(
        self,
        config: str,
        ckpt_dir: Optional[str] = None,
        int8: bool = False,
        seed: int = 0,
    ) -> None:
        from torchx_tpu.examples.train_llama import all_configs

        configs = all_configs()
        if config not in configs:
            raise ValueError(f"unknown config {config!r}; have {sorted(configs)}")
        self.cfg = configs[config]()
        self.name = config
        from torchx_tpu.models import llama

        if ckpt_dir:
            from torchx_tpu.parallel.checkpoint import Checkpointer

            abstract = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
            ckpt = Checkpointer(ckpt_dir)
            step, params = ckpt.restore_latest(abstract)
            ckpt.close()
            if params is None:
                raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
            self.params = params
            self.ckpt_step = step
        else:
            self.params = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
            self.ckpt_step = None
        if int8:
            from torchx_tpu.ops.quant import quantize_params

            self.params = quantize_params(self.params)
        self.int8 = int8
        self._lock = threading.Lock()
        self._cache_lock = threading.Lock()  # handlers run concurrently
        self._jit_cache: dict[tuple, Any] = {}
        self.requests = 0

    _JIT_CACHE_MAX = 32

    def _decode_fn(self, max_new_tokens: int, temperature: float):
        """One jitted generate per (max_new, temperature); jax's own cache
        handles distinct (batch, prompt_len) shapes under each entry.

        Request-supplied floats key the cache, so temperature is rounded
        (1e-3 is far below sampling noise) and the cache is FIFO-bounded —
        adversarial parameter sweeps cannot grow compile state without
        bound."""
        from torchx_tpu.models import generate as gen

        key = (max_new_tokens, round(temperature, 3))
        with self._cache_lock:
            fn = self._jit_cache.get(key)
            if fn is None:
                if len(self._jit_cache) >= self._JIT_CACHE_MAX:
                    self._jit_cache.pop(next(iter(self._jit_cache)))
                fn = jax.jit(
                    lambda p, b, rng: gen.generate(
                        p,
                        b,
                        self.cfg,
                        max_new_tokens=max_new_tokens,
                        temperature=key[1],
                        rng=rng,
                    )
                )
                self._jit_cache[key] = fn
            return fn

    def generate(
        self,
        tokens: list[list[int]],
        max_new_tokens: int,
        temperature: float = 0.0,
        seed: int = 0,
    ) -> list[list[int]]:
        if not tokens or any(not t for t in tokens):
            raise ValueError("tokens must be non-empty sequences")
        longest = max(len(t) for t in tokens)
        if longest + max_new_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt length {longest} + {max_new_tokens} new tokens"
                f" exceeds max_seq {self.cfg.max_seq}"
            )
        # batch EXACT-length groups (padding would pollute the causal
        # context — correctness over cleverness; one compile per distinct
        # (length, max_new) pair, cached by jit)
        groups: dict[int, list[int]] = {}
        for i, t in enumerate(tokens):
            groups.setdefault(len(t), []).append(i)
        result: list[list[int]] = [[] for _ in tokens]
        fn = self._decode_fn(max_new_tokens, temperature)
        with self._lock:
            self.requests += 1
            for length, idxs in groups.items():
                batch = jnp.asarray(
                    [tokens[i] for i in idxs], dtype=jnp.int32
                )
                out = jax.device_get(
                    fn(self.params, batch, jax.random.PRNGKey(seed))
                )
                for row, i in enumerate(idxs):
                    result[i] = [int(x) for x in out[row]]
        return result


def _make_handler(service: GenerateService):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt: str, *args: Any) -> None:  # quiet
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802
            if self.path == "/healthz":
                self._reply(
                    200,
                    {
                        "status": "ok",
                        "model": service.name,
                        "int8": service.int8,
                        "ckpt_step": service.ckpt_step,
                        "requests": service.requests,
                    },
                )
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self) -> None:  # noqa: N802
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                text_mode = "text" in req and "tokens" not in req
                if text_mode:
                    texts = req["text"]
                    if isinstance(texts, str):
                        texts = [texts]
                    tokens = [list(t.encode("utf-8")) for t in texts]
                else:
                    tokens = req["tokens"]
                out = service.generate(
                    tokens,
                    max_new_tokens=int(req.get("max_new_tokens", 16)),
                    temperature=float(req.get("temperature", 0.0)),
                    seed=int(req.get("seed", 0)),
                )
                if text_mode:
                    self._reply(
                        200,
                        {
                            "text": [
                                bytes(
                                    b for b in seq if 0 <= b < 256
                                ).decode("utf-8", errors="replace")
                                for seq in out
                            ]
                        },
                    )
                else:
                    self._reply(200, {"tokens": out})
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface, don't kill the server
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def serve(
    config: str,
    port: int = 8000,
    ckpt_dir: Optional[str] = None,
    int8: bool = False,
    ready_event: Optional[threading.Event] = None,
) -> ThreadingHTTPServer:
    service = GenerateService(config, ckpt_dir=ckpt_dir, int8=int8)
    server = ThreadingHTTPServer(("", port), _make_handler(service))
    if ready_event is not None:
        ready_event.set()
    return server


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="generate_server", description=__doc__)
    parser.add_argument("--config", required=True, help="model config name")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument("--int8", action="store_true", help="int8 weight-only")
    args = parser.parse_args(argv)
    _assert_platform()
    t0 = time.monotonic()
    server = serve(args.config, args.port, args.ckpt_dir, args.int8)
    print(
        f"generate_server: {args.config} on :{args.port}"
        f" (loaded in {time.monotonic() - t0:.1f}s)",
        flush=True,
    )
    server.serve_forever()


if __name__ == "__main__":
    main()
