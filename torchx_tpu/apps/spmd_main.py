"""SPMD bootstrap: initialize jax.distributed on every host, then run user code.

This is the TPU analog of the reference's torchrun invocation
(torchx/components/dist.py:261-287): where torchrun rendezvouses N agents
via a c10d TCPStore and forks workers, a TPU slice runs ONE JAX process per
host and `jax.distributed.initialize` connects them through the coordinator
service. The launcher injects the gang identity (TPX_REPLICA_ID /
TPX_NUM_REPLICAS / TPX_COORDINATOR_HOST); this module turns it into a live
`jax.distributed` world and then execs the user script/module in-process.

Usage (as built by components.dist.spmd):

    python -m torchx_tpu.apps.spmd_main [--port P] (--script S | -m MOD) [args...]

Structured errors are written to $TPX_ERROR_FILE on failure so the
launcher's status surface shows root cause (reference analog: torchelastic
error files, local_scheduler.py:996-1001).
"""

from __future__ import annotations

import argparse
import os
import runpy
import socket
import sys
import time
import traceback
from contextlib import nullcontext
from typing import Any, ContextManager

from torchx_tpu import settings

_PROCESS_START = time.monotonic()


def _job_span(name: str, **attrs: Any) -> ContextManager[Any]:
    """A span joining the client's trace via the injected $TPX_TRACE_ID /
    $TPX_PARENT_SPAN context, or a no-op when this process was not
    launched under tracing (keeps bare `python -m spmd_main` runs from
    minting orphan traces)."""
    if not os.environ.get(settings.ENV_TPX_TRACE_ID):
        return nullcontext()
    from torchx_tpu.obs import trace as obs_trace

    return obs_trace.span(name, **attrs)


def _gang() -> tuple[int, int, str]:
    """(process_id, num_processes, coordinator_host) — shared parser in
    torchx_tpu.distributed so user code and the bootstrap agree."""
    from torchx_tpu.distributed import gang_info

    return gang_info()


def _wait_for_coordinator(host: str, port: int, timeout: float = 300.0) -> None:
    """Non-coordinator hosts wait for the coordinator socket so slow pod
    starts don't fail the gang (launch-latency critical path)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=2):
                return
        except OSError:
            time.sleep(0.25)
    raise TimeoutError(f"coordinator {host}:{port} unreachable after {timeout}s")


def _assert_platform() -> None:
    """Make the launcher's JAX_PLATFORMS choice stick even when a site hook
    (sitecustomize registering a vendor PJRT plugin) programmatically forced
    another platform before user code ran."""
    platforms = os.environ.get(settings.ENV_JAX_PLATFORMS)
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)


def initialize_distributed(port: int) -> None:
    _assert_platform()
    process_id, num_processes, coordinator = _gang()
    # surface the resolved GLOBAL id to user code even when the backend
    # injected only the (slice, host) decomposition (e.g. GKE multi-slice)
    os.environ.setdefault(settings.ENV_TPX_REPLICA_ID, str(process_id))
    if num_processes <= 1:
        return  # single process: jax works without a coordinator
    from torchx_tpu import distributed as tpx_dist

    if process_id != 0:
        _wait_for_coordinator(coordinator, port)
    # init through the shared helper so a user script that also calls
    # init_from_env() sees the world as already initialized
    tpx_dist.init_from_env(port)


def write_error_file(exc: BaseException) -> None:
    error_file = os.environ.get(settings.ENV_TPX_ERROR_FILE)
    if not error_file:
        return
    try:
        os.makedirs(os.path.dirname(error_file), exist_ok=True)
        from torchx_tpu.specs.api import make_structured_error

        payload = make_structured_error(
            f"{type(exc).__name__}: {exc}\n{traceback.format_exc()}", exitcode=1
        )
        with open(error_file, "w") as f:
            f.write(payload)
    except OSError:
        pass


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="spmd_main", description=__doc__)
    parser.add_argument("--port", type=int, default=settings.TPX_COORDINATOR_PORT)
    parser.add_argument("--no-init", action="store_true", help="skip jax.distributed")
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument("--script", help="path to user python script")
    group.add_argument("-m", dest="module", help="user python module")
    args, rest = parser.parse_known_args(argv)
    if rest and rest[0] == "--":
        rest = rest[1:]

    try:
        with _job_span(
            "job.bootstrap",
            replica=os.environ.get(settings.ENV_TPX_REPLICA_ID),
            no_init=args.no_init or None,
            # interpreter+import time already paid before bootstrap began
            # (the "import" slice of the launch.breakdown family)
            import_s=round(time.monotonic() - _PROCESS_START, 3),
        ):
            if not args.no_init:
                initialize_distributed(args.port)
        sys.argv = [args.script or args.module, *rest]
        if os.environ.get(settings.ENV_TPX_TRACE_ID):
            # instantaneous marker: distributed init is done, user code
            # starts now — the in-job half of launch latency
            from torchx_tpu.obs import trace as obs_trace

            obs_trace.heartbeat(
                "job.exec",
                replica=os.environ.get(settings.ENV_TPX_REPLICA_ID),
                target=args.script or args.module,
            )
        if args.script:
            runpy.run_path(args.script, run_name="__main__")
        else:
            runpy.run_module(args.module, run_name="__main__", alter_sys=True)
    except SystemExit as e:
        code = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
        if code != 0:
            write_error_file(e)
        raise
    except BaseException as e:
        write_error_file(e)
        raise


if __name__ == "__main__":
    main()
