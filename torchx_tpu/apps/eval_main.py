"""Scored checkpoint eval: the app behind a pipeline's eval gate.

Verifies the checkpoint it was handed (recomputes the sha256 content
digest of the latest finalized step and compares it against the
MANIFEST.json record — the PR 7 digest chain, reimplemented here with
stdlib only so eval gangs need no accelerator runtime), produces a
score, and writes an fsync'd JSON record the pipeline engine's eval gate
reads::

    python -m torchx_tpu.apps.eval_main \\
        --ckpt /path/to/ckpt_dir --out /path/to/score.json [--score 0.97]

``--score`` forces the result (deterministic tests and the tier-1 smoke
induce gate passes/regressions with it); without it the score is derived
from the verified digest — stable for a given checkpoint, which is what
a gate test needs from a stub evaluator. A digest mismatch (corrupt or
tampered payload) exits non-zero: a gate must never score garbage.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import Optional


def _digest_dir(path: str) -> str:
    """sha256 over relpath + bytes of every file, sorted — byte-for-byte
    the manifest writer's recipe (parallel/checkpoint._digest_path)."""
    h = hashlib.sha256()
    if os.path.isdir(path):
        for root, dirs, files in sorted(os.walk(path)):
            dirs.sort()
            for name in sorted(files):
                fp = os.path.join(root, name)
                h.update(os.path.relpath(fp, path).encode())
                with open(fp, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
    else:
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def verify_checkpoint(ckpt_dir: str) -> tuple[int, str]:
    """-> (latest_step, digest) after recomputing and matching the
    manifest's recorded digest; raises ValueError on a missing manifest,
    no finalized step, or a digest mismatch. A manifest entry without a
    digest (pre-digest checkpoint) passes unverified, matching
    ``CheckpointManager.verify_step``'s None-means-proceed contract."""
    manifest = os.path.join(ckpt_dir, "MANIFEST.json")
    try:
        with open(manifest) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"no readable manifest at {manifest}: {e}") from e
    step = doc.get("latest_step")
    if not isinstance(step, int) or step < 0:
        raise ValueError(f"{manifest} records no finalized step")
    rec = doc.get("steps", {}).get(str(step)) or {}
    digest = str(rec.get("digest", ""))
    payload = rec.get("path") or _step_payload(ckpt_dir, step)
    if digest and payload is not None:
        actual = _digest_dir(payload)
        if actual != digest:
            raise ValueError(
                f"checkpoint step {step} digest mismatch: manifest"
                f" {digest[:12]}… vs on-disk {actual[:12]}…"
            )
    return step, digest


def _step_payload(ckpt_dir: str, step: int) -> Optional[str]:
    """Best-effort payload path for ``step``: the orbax convention is a
    directory (or file) named after the step number."""
    for name in (str(step), f"step_{step}", f"{step}.ckpt"):
        path = os.path.join(ckpt_dir, name)
        if os.path.exists(path):
            return path
    return None


def _score_from_digest(digest: str) -> float:
    """Deterministic stub score in [0, 1) derived from the digest."""
    if not digest:
        return 0.5
    return int(digest[:8], 16) / float(0xFFFFFFFF)


def main(argv: Optional[list[str]] = None) -> int:
    """Entry point; returns the process exit code."""
    parser = argparse.ArgumentParser(
        prog="eval_main", description="score a verified checkpoint"
    )
    parser.add_argument(
        "--ckpt", required=True, help="checkpoint directory to evaluate"
    )
    parser.add_argument(
        "--out", required=True, help="where to write the score JSON record"
    )
    parser.add_argument(
        "--score",
        type=float,
        default=None,
        help="force the score (deterministic gates in tests/smoke)",
    )
    parser.add_argument(
        "--no-verify",
        action="store_true",
        help="skip the digest check (checkpoints without manifests)",
    )
    args = parser.parse_args(argv)

    step, digest = -1, ""
    if not args.no_verify:
        try:
            step, digest = verify_checkpoint(args.ckpt)
        except ValueError as e:
            print(f"eval_main: checkpoint verification failed: {e}", file=sys.stderr)
            return 1

    score = args.score if args.score is not None else _score_from_digest(digest)
    record = {
        "score": score,
        "ckpt": args.ckpt,
        "digest": digest,
        "step": step,
    }
    out_dir = os.path.dirname(os.path.abspath(args.out))
    os.makedirs(out_dir, exist_ok=True)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, args.out)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
