"""fsspec-powered copy app (reference analog: torchx/apps/utils/copy_main.py)."""

from __future__ import annotations

import argparse
import os
import shutil


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="copy a file/dir between URLs")
    parser.add_argument("--src", required=True)
    parser.add_argument("--dst", required=True)
    args = parser.parse_args(argv)
    try:
        import fsspec

        with fsspec.open(args.src, "rb") as r:
            with fsspec.open(args.dst, "wb") as w:
                shutil.copyfileobj(r, w)
    except ImportError:
        # plain filesystem fallback
        if os.path.isdir(args.src):
            shutil.copytree(args.src, args.dst, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(os.path.abspath(args.dst)), exist_ok=True)
            shutil.copyfile(args.src, args.dst)
    print(f"copied {args.src} -> {args.dst}")


if __name__ == "__main__":
    main()
