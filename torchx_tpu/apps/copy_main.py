"""fsspec-powered copy app (reference analog: torchx/apps/utils/copy_main.py)."""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="copy a file/dir between URLs")
    parser.add_argument("--src", required=True)
    parser.add_argument("--dst", required=True)
    args = parser.parse_args(argv)
    from torchx_tpu.util.io import copy_path

    copy_path(args.src, args.dst)
    print(f"copied {args.src} -> {args.dst}")


if __name__ == "__main__":
    main()
