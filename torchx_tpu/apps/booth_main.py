"""Booth-function test objective (reference analog: torchx/apps/utils/booth_main.py).

f(x1,x2) = (x1 + 2*x2 - 7)^2 + (2*x1 + x2 - 5)^2 — global min at (1, 3).
Records the value through the in-job tracker so hpo/tracker integration can
be validated end-to-end.
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="booth test objective")
    parser.add_argument("--x1", type=float, required=True)
    parser.add_argument("--x2", type=float, required=True)
    args = parser.parse_args(argv)
    value = (args.x1 + 2 * args.x2 - 7) ** 2 + (2 * args.x1 + args.x2 - 5) ** 2
    from torchx_tpu.tracker import app_run_from_env

    app_run_from_env().add_metadata(booth_value=value, x1=args.x1, x2=args.x2)
    print(f"booth({args.x1}, {args.x2}) = {value}")


if __name__ == "__main__":
    main()
