"""Docker scheduler: one container per replica on a shared network.

Reference analog: torchx/schedulers/docker_scheduler.py (503 LoC). Kept
design: all replicas of an app share a user-defined bridge network; the
coordinator host is the *container name* of role-0/replica-0 (docker's
embedded DNS resolves container names on user networks — the analog of
``TORCHX_RANK0_HOST`` = container name at reference :243,290); resource
limits map to mem_limit/nano_cpus; ``restart_policy: on-failure`` carries
``max_retries`` (reference :316-320); logs stream through the docker logs
API.

The docker SDK import is deferred and injectable so dryrun tests run
without a daemon.
"""

from __future__ import annotations

import fnmatch
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional, TYPE_CHECKING

from torchx_tpu import settings
from torchx_tpu.resilience.call import resilient_call
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    DescribeAppResponse,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    role_replica_env,
    tpu_hosts_for_role,
)
from torchx_tpu.schedulers.devices import (
    get_device_mounts,
    local_tpu_device_mounts,
)
from torchx_tpu.schedulers.ids import make_unique
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    BindMount,
    CfgVal,
    DeviceMount,
    ReplicaStatus,
    RoleStatus,
    VolumeMount,
    macros,
    runopts,
)
from torchx_tpu.workspace.docker_workspace import DockerWorkspaceMixin

if TYPE_CHECKING:
    from docker import DockerClient

logger = logging.getLogger(__name__)

NETWORK_NAME = "tpx"
LABEL_APP_ID = "tpx.sh/app-id"
LABEL_ROLE = "tpx.sh/role-name"
LABEL_REPLICA = "tpx.sh/replica-id"

CONTAINER_STATE_MAP = {
    "created": AppState.SUBMITTED,
    "restarting": AppState.RUNNING,
    "running": AppState.RUNNING,
    "paused": AppState.PENDING,
    "removing": AppState.RUNNING,
    "dead": AppState.FAILED,
}


@dataclass
class DockerContainer:
    image: str
    command: list[str]
    kwargs: dict[str, Any]  # passed to client.containers.run


@dataclass
class DockerJob:
    app_id: str
    containers: list[DockerContainer] = field(default_factory=list)

    def __str__(self) -> str:
        import json

        return json.dumps(
            [
                {"image": c.image, "command": c.command, **c.kwargs}
                for c in self.containers
            ],
            indent=2,
            default=str,
        )


# Feature profile for the preflight analyzer (torchx_tpu.analyze): docker
# materializes mounts and honors MaximumRetryCount, but one daemon on one
# host cannot wire multi-slice DCN training or classify spot reclamation.
CAPABILITIES = SchedulerCapabilities(
    mounts=True,
    multi_role=True,
    multislice=False,
    delete=True,
    resize=False,
    logs=True,
    native_retries=True,
    concrete_resources=False,  # unset cpu/memMB simply means "no limits"
    classifies_preemption=False,
    # published container ports are scrapeable from the docker host
    metricz_scrape=True,
)


class DockerScheduler(DockerWorkspaceMixin, Scheduler[DockerJob]):
    capabilities = CAPABILITIES
    supports_log_windows = True  # docker daemon applies since/until
    def __init__(
        self,
        session_name: str,
        docker_client: Optional["DockerClient"] = None,
    ) -> None:
        super().__init__(
            docker_client=docker_client, backend="local_docker", session_name=session_name
        )

    @property
    def _client(self) -> "DockerClient":
        return self._docker_client

    def run_opts(self) -> runopts:
        opts = runopts()
        opts.add(
            "copy_env",
            type_=list,
            help="glob patterns of client env vars to copy into containers",
            default=None,
        )
        opts.add(
            "env",
            type_=dict,
            help="extra env vars for all containers",
            default=None,
        )
        opts.add(
            "privileged",
            type_=bool,
            help="run containers privileged (required to expose TPU chips"
            " on a TPU-VM host)",
            default=False,
        )
        return opts | self.workspace_opts()

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[DockerJob]:
        app_id = make_unique(app.name)
        req = DockerJob(app_id=app_id)
        copy_env = cfg.get("copy_env") or []
        extra_env = cfg.get("env") or {}

        coordinator = f"{app_id}-{app.roles[0].name}-0"
        for role in app.roles:
            num = tpu_hosts_for_role(role)
            for replica_id in range(num):
                values = macros.Values(
                    img_root="",
                    app_id=app_id,
                    replica_id=str(replica_id),
                    num_replicas=str(num),
                    coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
                )
                rrole = values.apply(role)
                name = f"{app_id}-{role.name}-{replica_id}"
                env = dict(rrole.env)
                if copy_env:
                    for pat in copy_env:
                        for k, v in os.environ.items():
                            if fnmatch.fnmatch(k, str(pat)):
                                env.setdefault(k, v)
                env.update({k: str(v) for k, v in dict(extra_env).items()})
                env[settings.ENV_TPX_APP_ID] = app_id
                env[settings.ENV_TPX_JOB_ID] = (
                    f"{self.backend}://{self.session_name}/{app_id}"
                )
                env[settings.ENV_TPX_ERROR_FILE] = "/tmp/tpx_error.json"
                env.update(
                    role_replica_env(
                        role,
                        replica_id,
                        coordinator_host=coordinator,
                        coordinator_port=settings.TPX_COORDINATOR_PORT,
                    )
                )

                mounts = []
                devices = []
                for m in rrole.mounts:
                    if isinstance(m, BindMount):
                        mounts.append(
                            {
                                "type": "bind",
                                "source": m.src_path,
                                "target": m.dst_path,
                                "read_only": m.read_only,
                            }
                        )
                    elif isinstance(m, VolumeMount):
                        mounts.append(
                            {
                                "type": "volume",
                                "source": m.src,
                                "target": m.dst_path,
                                "read_only": m.read_only,
                            }
                        )
                    elif isinstance(m, DeviceMount):
                        devices.append(f"{m.src_path}:{m.dst_path}:{m.permissions}")
                # named devices (e.g. nvidia.com/gpu on mixed clusters)
                for dm in get_device_mounts(rrole.resource.devices):
                    devices.append(f"{dm.src_path}:{dm.dst_path}:{dm.permissions}")
                # TPU roles on a TPU-VM host need the accel device nodes
                if rrole.resource.tpu is not None:
                    for dm in local_tpu_device_mounts():
                        devices.append(f"{dm.src_path}:{dm.dst_path}:{dm.permissions}")

                kwargs: dict[str, Any] = {
                    "name": name,
                    "environment": env,
                    "labels": {
                        LABEL_APP_ID: app_id,
                        LABEL_ROLE: role.name,
                        LABEL_REPLICA: str(replica_id),
                    },
                    "hostname": name,
                    "network": NETWORK_NAME,
                    "detach": True,
                }
                if mounts:
                    kwargs["mounts"] = mounts
                if devices:
                    kwargs["devices"] = devices
                if cfg.get("privileged"):
                    kwargs["privileged"] = True
                if rrole.max_retries > 0:
                    kwargs["restart_policy"] = {
                        "Name": "on-failure",
                        "MaximumRetryCount": rrole.max_retries,
                    }
                if rrole.resource.memMB > 0:
                    kwargs["mem_limit"] = f"{int(rrole.resource.memMB)}m"
                if rrole.resource.cpu > 0:
                    kwargs["nano_cpus"] = int(rrole.resource.cpu * 1e9)

                req.containers.append(
                    DockerContainer(
                        image=rrole.image,
                        command=[rrole.entrypoint, *rrole.args],
                        kwargs=kwargs,
                    )
                )
        return AppDryRunInfo(req)

    def schedule(self, dryrun_info: AppDryRunInfo[DockerJob]) -> str:
        req = dryrun_info.request
        self._ensure_network()
        try:
            for c in req.containers:
                resilient_call(
                    lambda c=c: self._client.containers.run(
                        c.image, c.command, **c.kwargs
                    ),
                    backend=self.backend,
                    op="submit",
                    policy=NON_IDEMPOTENT,
                )
        except Exception:
            self._cancel_existing(req.app_id)
            raise
        return req.app_id

    def _ensure_network(self) -> None:
        try:
            self._client.networks.create(
                NETWORK_NAME, driver="bridge", check_duplicate=True
            )
        except Exception as e:  # noqa: BLE001 - racing creates are fine
            if "already exists" not in str(e):
                logger.debug("network create: %s", e)

    def _containers(self, app_id: str) -> list[Any]:
        return resilient_call(
            lambda: self._client.containers.list(
                all=True, filters={"label": f"{LABEL_APP_ID}={app_id}"}
            ),
            backend=self.backend,
            op="describe",
        )

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        containers = self._containers(app_id)
        if not containers:
            return None
        roles: dict[str, RoleStatus] = {}
        states = []
        for c in containers:
            role = c.labels.get(LABEL_ROLE, "unknown")
            replica = int(c.labels.get(LABEL_REPLICA, 0))
            if c.status == "exited":
                rc = (c.attrs.get("State") or {}).get("ExitCode", 0)
                state = AppState.SUCCEEDED if rc == 0 else AppState.FAILED
            else:
                state = CONTAINER_STATE_MAP.get(c.status, AppState.UNKNOWN)
            states.append(state)
            roles.setdefault(role, RoleStatus(role=role)).replicas.append(
                ReplicaStatus(id=replica, state=state, role=role, hostname=c.name)
            )
        return DescribeAppResponse(
            app_id=app_id,
            state=_aggregate_states(states),
            roles_statuses=list(roles.values()),
        )

    def list(self) -> list[ListAppResponse]:
        containers = resilient_call(
            lambda: self._client.containers.list(
                all=True, filters={"label": LABEL_APP_ID}
            ),
            backend=self.backend,
            op="list",
        )
        per_app: dict[str, list[AppState]] = {}
        for c in containers:
            app_id = c.labels.get(LABEL_APP_ID, "")
            state = CONTAINER_STATE_MAP.get(c.status, AppState.UNKNOWN)
            if c.status == "exited":
                rc = (c.attrs.get("State") or {}).get("ExitCode", 0)
                state = AppState.SUCCEEDED if rc == 0 else AppState.FAILED
            per_app.setdefault(app_id, []).append(state)
        return [
            ListAppResponse(app_id=app_id, state=_aggregate_states(states))
            for app_id, states in per_app.items()
        ]

    def _cancel_existing(self, app_id: str) -> None:
        for c in self._containers(app_id):
            try:
                c.stop(timeout=10)
            except Exception as e:  # noqa: BLE001
                logger.warning("stopping %s: %s", c.name, e)

    def delete(self, app_id: str) -> None:
        for c in self._containers(app_id):
            c.remove(force=True)

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        containers = self._client.containers.list(
            all=True,
            filters={
                "label": [
                    f"{LABEL_APP_ID}={app_id}",
                    f"{LABEL_ROLE}={role_name}",
                    f"{LABEL_REPLICA}={k}",
                ]
            },
        )
        if not containers:
            raise ValueError(f"no container for {app_id}/{role_name}/{k}")
        c = containers[0]
        kwargs: dict[str, Any] = {
            "stdout": streams in (None, Stream.COMBINED, Stream.STDOUT),
            "stderr": streams in (None, Stream.COMBINED, Stream.STDERR),
        }
        if since:
            kwargs["since"] = since
        if until:
            kwargs["until"] = until
        if should_tail:
            raw = c.logs(stream=True, follow=True, **kwargs)
            lines: Iterable[str] = (
                ln.decode("utf-8", errors="replace").rstrip("\n") for ln in raw
            )
        else:
            raw = c.logs(**kwargs)
            lines = raw.decode("utf-8", errors="replace").splitlines()
        if regex:
            lines = filter_regex(regex, lines)
        return lines


def _aggregate_states(states: list[AppState]) -> AppState:
    """Gang aggregation: any FAILED fails the app; any RUNNING keeps it
    running (a partially-finished gang is not terminal); all SUCCEEDED
    succeeds."""
    if not states:
        return AppState.UNKNOWN
    if any(s == AppState.FAILED for s in states):
        return AppState.FAILED
    if any(s == AppState.RUNNING for s in states):
        return AppState.RUNNING
    if all(s == AppState.SUCCEEDED for s in states):
        return AppState.SUCCEEDED
    return states[0]


def create_scheduler(session_name: str, **kwargs: Any) -> DockerScheduler:
    known = {"docker_client"}
    return DockerScheduler(
        session_name=session_name,
        **{k: v for k, v in kwargs.items() if k in known},
    )
