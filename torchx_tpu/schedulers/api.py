"""Scheduler interface: the lifecycle contract every backend implements.

Reference analog: torchx/schedulers/api.py:364-526. The load-bearing design
decision (kept): ``submit = resolve cfg -> build workspace -> submit_dryrun
-> schedule`` where ``submit_dryrun`` returns the *complete materialized
backend request* without submitting — tests assert on that request object
with no cluster (reference api.py:410-426).
"""

from __future__ import annotations

import subprocess
from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import Any, Generic, Iterable, Mapping, Optional, TypeVar

from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    FailureClass,
    Role,
    RoleStatus,
    runopts,
)

T = TypeVar("T")


class Stream(str, Enum):
    STDOUT = "stdout"
    STDERR = "stderr"
    COMBINED = "combined"


@dataclass
class DescribeAppResponse:
    """Scheduler's view of a submitted app (reference api.py:330-345).

    ``failure_class`` carries the backend's classification of a terminal
    failure when the describe payload itself reveals it (spot reclamation,
    node disruption); :meth:`Scheduler.classify_failure` reads it before
    falling back to the conservative default.
    """

    app_id: str = "<NOT_SET>"
    state: AppState = AppState.UNSUBMITTED
    num_restarts: int = -1
    msg: str = ""
    structured_error_msg: str = "<NONE>"
    ui_url: Optional[str] = None
    roles_statuses: list[RoleStatus] = None  # type: ignore[assignment]
    roles: list[Role] = None  # type: ignore[assignment]
    failure_class: Optional[FailureClass] = None

    def __post_init__(self) -> None:
        if self.roles_statuses is None:
            self.roles_statuses = []
        if self.roles is None:
            self.roles = []


@dataclass
class ListAppResponse:
    app_id: str
    state: AppState
    name: str = ""


@dataclass(frozen=True)
class SchedulerCapabilities:
    """Static feature profile of a scheduler backend.

    Declared as a module-level ``CAPABILITIES`` constant (and ``capabilities``
    class attribute) by every backend in :mod:`torchx_tpu.schedulers` so the
    preflight analyzer (:mod:`torchx_tpu.analyze`) can reject AppDefs that
    use features the target backend cannot honor *before* submission — e.g.
    mounts on tpu_vm, multi-role apps on gcp_batch, or a retry budget on a
    backend with no native restart support.

    Attributes:
        mounts: backend materializes Bind/Volume/Device mounts.
        multi_role: backend can launch more than one role per app.
        requires_tpu: backend only accepts roles with a TPU resource.
        multislice: backend wires multi-slice DCN training
            (TPU role with ``num_replicas > 1``).
        delete: backend implements :meth:`Scheduler.delete` — terminal
            attempts can be cleaned up by the supervisor before resubmit.
        resize: backend implements :meth:`Scheduler.resize`.
        logs: backend implements :meth:`Scheduler.log_iter`.
        native_retries: backend honors ``Role.max_retries`` itself
            (in-place restarts that do not consume supervisor budgets).
        concrete_resources: backend builds real resource requests from
            ``Resource.cpu`` / ``Resource.memMB`` (unset values fall back
            to backend defaults but are worth a warning).
        classifies_preemption: backend can distinguish PREEMPTED from FAILED
            in :meth:`Scheduler.classify_failure` — without it, preemptions
            burn the supervisor's (default zero) APP_ERROR budget.
        watch: backend has a *native* event source behind
            :meth:`Scheduler.watch` (local sidecar mtime, GKE kubectl
            stream) — transitions surface at event latency. Without it the
            same ``watch()`` interface still works but rides the generic
            poll adapter, so hang/terminal detection latency degrades to
            the watch poll interval (what analyze rule TPX601 warns about).
        metricz_scrape: replicas launched by this backend expose a
            ``/metricz`` endpoint the control daemon's telemetry
            collector can reach over the network (loopback for local
            backends, cluster DNS for GKE). Without it, SLO specs over
            replica-side metrics see no samples — burn rates stay zero
            and the alerts are dead weight (analyze rule TPX214).
    """

    mounts: bool = False
    multi_role: bool = True
    requires_tpu: bool = False
    multislice: bool = False
    delete: bool = False
    resize: bool = False
    logs: bool = True
    native_retries: bool = False
    concrete_resources: bool = False
    classifies_preemption: bool = False
    watch: bool = False
    metricz_scrape: bool = False


def dquote(s: str) -> str:
    """Double-quote a string for bash: metachars are safe but ``$VAR`` /
    ``${VAR}`` references (runtime macro values like the replica id) still
    expand. Command substitution is neutralized both ways — backticks and
    ``$(...)`` are escaped, since intentional variable expansion never
    requires running commands from inside role args/env values. Shared by
    every scheduler that materializes shell scripts."""
    out = (
        s.replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("`", "\\`")
        .replace("$(", "\\$(")
    )
    return '"' + out + '"'


def safe_int(value: Any, default: int = 0) -> int:
    """int() that never raises (scheduler payloads are untrusted JSON)."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def filter_regex(regex: str, data: Iterable[str]) -> Iterable[str]:
    """Lazily filter log lines by a regex (reference api.py:528-539)."""
    import re

    r = re.compile(regex)
    return (line for line in data if r.search(line))


_STAMP_RE = None  # compiled lazily; the pattern matches a real epoch only

# stdin->stdout line stamper (``<epoch.millis> <line>``) run as
# ``python3 -u -c``; shared by the tpu_vm remote wrapper and the slurm
# batch-script wrapper so every backend's window filter can reuse
# parse_epoch_stamp
EPOCH_STAMPER = (
    "import sys,time\n"
    "for line in sys.stdin:\n"
    "    sys.stdout.write(f'{time.time():.3f} '+line)\n"
    "    sys.stdout.flush()\n"
)



def parse_epoch_stamp(line: str) -> "tuple[Optional[float], str]":
    """-> (epoch or None, payload) for log lines stamped ``<epoch.millis> ``.

    Shared by the tpu_vm remote stamper and the local Tee: anything not
    shaped like a real epoch (legacy logs, raw writes, lines that merely
    start with a number like '3 retries left') passes through unstamped."""
    global _STAMP_RE
    if _STAMP_RE is None:
        import re

        _STAMP_RE = re.compile(r"^\d{9,12}\.\d{3}$")
    head, sep, rest = line.partition(" ")
    if sep and _STAMP_RE.match(head):
        return float(head), rest
    return None, line


def rfc3339(epoch: float) -> str:
    """Epoch seconds -> the RFC3339 UTC form Cloud Logging filters expect
    (shared by the gcp_batch and vertex log windows)."""
    from datetime import datetime, timezone

    return (
        datetime.fromtimestamp(epoch, tz=timezone.utc)
        .isoformat()
        .replace("+00:00", "Z")
    )


def window_stamped_lines(
    lines: Iterable[str],
    since: Optional[float] = None,
    until: Optional[float] = None,
) -> Iterable[str]:
    """Apply a since/until window to epoch-stamped lines and strip the
    stamps. Unstamped lines pass through whole (no stamp -> no window)."""
    for line in lines:
        ts, payload = parse_epoch_stamp(line)
        if ts is not None:
            if since is not None and ts < since:
                continue
            if until is not None and ts > until:
                continue
        yield payload


def split_lines(text: str) -> list[str]:
    """Split keeping trailing newlines on each line (reference api.py:541-554)."""
    lines = text.splitlines(keepends=True)
    return lines


class Scheduler(ABC, Generic[T]):
    """Backend lifecycle contract.

    Subclasses implement ``_submit_dryrun`` (materialize the full request),
    ``schedule`` (actually submit), ``describe``, ``list``, and
    ``_cancel_existing``; optionally ``log_iter``, ``delete``, ``_validate``.
    """

    # Feature profile consulted by the preflight analyzer; backends override
    # with their module's CAPABILITIES constant. None = unknown backend
    # profile, capability rules are skipped.
    capabilities: Optional[SchedulerCapabilities] = None

    def __init__(self, backend: str, session_name: str) -> None:
        self.backend = backend
        self.session_name = session_name

    # -- control-plane seam ------------------------------------------------

    def _cmd(
        self, cmd: list[str], op: str, **kwargs: Any
    ) -> "subprocess.CompletedProcess":
        """Run one control-plane CLI call through the resilient seam
        (:func:`torchx_tpu.resilience.call.resilient_cmd`): default
        deadline, transient-vs-permanent classification, per-kind retries,
        the backend's circuit breaker, and ``TPX_FAULT_PLAN`` injection.

        Backends keep ``_run_cmd`` as the raw subprocess seam (and the
        test monkeypatch point); call sites go through ``_cmd`` with a
        logical ``op`` name ("describe", "list", ...) so retries and
        faults are attributable. Non-idempotent ops (submits) must pass
        ``policy=NON_IDEMPOTENT`` — a call that may have reached the
        control plane is never replayed."""
        from torchx_tpu.resilience.call import resilient_cmd

        run_cmd = getattr(self, "_run_cmd", None)
        if run_cmd is None:
            raise NotImplementedError(
                f"{type(self).__name__} has no _run_cmd subprocess seam"
            )
        return resilient_cmd(
            run_cmd, cmd, backend=self.backend, op=op, **kwargs
        )

    # -- submission path ---------------------------------------------------

    def submit(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> str:
        """Convenience: resolve + workspace + dryrun + schedule."""
        resolved = self.run_opts().resolve(cfg)
        from torchx_tpu.workspace.api import WorkspaceMixin

        if isinstance(self, WorkspaceMixin):
            self.build_workspaces(app.roles, resolved)
        return self.schedule(self.materialize_dryrun(app, resolved))

    def submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> AppDryRunInfo[T]:
        """Materialize the complete backend request WITHOUT submitting."""
        return self.materialize_dryrun(app, self.run_opts().resolve(cfg))

    def materialize_dryrun(
        self, app: AppDef, resolved_cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[T]:
        """Like submit_dryrun but for callers (Runner) that already resolved
        the cfg — the single materialization point; cfg is resolved exactly
        once per submission path."""
        from torchx_tpu.obs import trace as obs_trace

        with obs_trace.span(
            "scheduler.dryrun",
            session=self.session_name,
            scheduler=self.backend,
            app=app.name,
        ):
            dryrun_info = self._submit_dryrun(app, resolved_cfg)
            for role in app.roles:
                dryrun_info = role.pre_proc_fn(self.backend, dryrun_info)
        dryrun_info._app = app
        dryrun_info._cfg = resolved_cfg
        dryrun_info._scheduler = self.backend
        return dryrun_info

    @abstractmethod
    def _submit_dryrun(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> AppDryRunInfo[T]:
        ...

    @abstractmethod
    def schedule(self, dryrun_info: AppDryRunInfo[T]) -> str:
        """Submit the materialized request; returns the backend app_id."""
        ...

    # -- monitoring path ---------------------------------------------------

    @abstractmethod
    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        """The backend's view of the app (state, per-replica statuses),
        or None when the id is unknown."""
        ...

    def list(self) -> list[ListAppResponse]:
        """All apps this backend knows about. Optional."""
        raise NotImplementedError(
            f"{self.backend} scheduler does not support listing apps"
        )

    def watch(
        self, app_ids: "Iterable[str]" = (), interval: Optional[float] = None
    ) -> Any:
        """An event stream over the given apps: a
        :class:`~torchx_tpu.control.watch.Watcher` whose ``events()``
        iterator yields one :class:`~torchx_tpu.control.events.StateEvent`
        per observed state transition.

        Every backend supports this interface; only backends that declare
        the ``watch`` capability back it with a native event source
        (sidecar mtime, kubectl stream). The default is the generic poll
        adapter — still one coalesced describe scan per tick regardless of
        how many waiters consume the stream, and still routed through the
        backend's resilient describe seam."""
        from torchx_tpu.control.watch import PollWatcher

        return PollWatcher(self, app_ids, interval=interval)

    def exists(self, app_id: str) -> bool:
        """True when the backend still knows ``app_id``."""
        return self.describe(app_id) is not None

    def classify_failure(
        self, resp: DescribeAppResponse
    ) -> Optional[FailureClass]:
        """Classify a terminal failure for retry policy (supervisor hook).

        Returns None for non-failure states. The default is conservative:
        PREEMPTED maps to PREEMPTION, everything else that FAILED is an APP
        failure unless the backend's describe already attached a more
        specific ``failure_class`` (retrying a buggy app by default burns
        money; backends that can tell infra faults apart override this or
        populate the response field).
        """
        if resp.state == AppState.PREEMPTED:
            return resp.failure_class or FailureClass.PREEMPTION
        if resp.state == AppState.FAILED:
            return resp.failure_class or FailureClass.APP
        return None

    def cancel(self, app_id: str) -> None:
        """Stop the app if it exists (idempotent); state/logs remain
        describable where the backend allows."""
        if self.exists(app_id):
            self._cancel_existing(app_id)

    @abstractmethod
    def _cancel_existing(self, app_id: str) -> None:
        ...

    def delete(self, app_id: str) -> None:
        """Remove all backend records of a (terminal) app. Optional."""
        raise NotImplementedError(
            f"{self.backend} scheduler does not support app deletion"
        )

    def resize(self, app_id: str, role_name: str, num_replicas: int) -> None:
        """Resize a running role's gang to ``num_replicas`` (AppDef units:
        slices for TPU roles, replicas for CPU roles). Optional.

        SPMD worlds resize by restart: implementations relaunch the gang
        with a coherent world (fresh TPX_NUM_REPLICAS / replica ids /
        megascale slice counts) and user code resumes from its checkpoint.
        The manual counterpart of the automatic shrink-on-failure elastic
        path; honors ``Role.min_replicas`` as the floor.
        """
        raise NotImplementedError(
            f"{self.backend} scheduler does not support resizing apps"
        )

    # True when this backend's log_iter actually applies since/until
    # windows (docker: daemon-side; tpu_vm: stamped log lines). Backends
    # whose log files carry no per-line timestamps leave it False and the
    # Runner warns rather than silently showing an unwindowed log.
    supports_log_windows: bool = False

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Stream one replica's log lines (optionally regex-filtered,
        time-windowed when ``supports_log_windows``, followed with
        ``should_tail``). Optional."""
        raise NotImplementedError(
            f"{self.backend} scheduler does not support log iteration"
        )

    # -- config / validation ----------------------------------------------

    def run_opts(self) -> runopts:
        """This backend's typed run-config schema (empty by default;
        StructuredOpts subclasses generate theirs from field docstrings)."""
        return runopts()

    def _pre_build_validate(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> None:
        """Hook before workspace build (cheap checks)."""

    def _validate(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> None:
        """Hook after workspace build, before dryrun."""

    def close(self) -> None:
        """Release client connections / child processes. Idempotent."""


# =========================================================================
# Gang expansion: roles with multi-host TPU slices -> per-host replicas
# =========================================================================


def tpu_hosts_for_role(role: Role) -> int:
    """Number of host processes a role's gang needs.

    For TPU roles the gang size is derived from the slice (one JAX process
    per TPU-VM host); ``num_replicas`` then means *number of slices* when >1
    (multi-slice DCN training). CPU roles just use num_replicas.
    """
    if role.resource is not None and role.resource.tpu is not None:
        return role.resource.tpu.hosts * max(1, role.num_replicas)
    return role.num_replicas


def role_replica_env(
    role: Role,
    replica_id: int,
    coordinator_host: str,
    coordinator_port: int,
) -> dict[str, str]:
    """Env vars every scheduler injects into each replica: gang identity +
    coordinator bootstrap for ``jax.distributed.initialize`` (the analog of
    the reference's c10d endpoint wiring, components/dist.py:234-243)."""
    from torchx_tpu import settings

    num = tpu_hosts_for_role(role)
    env = {
        settings.ENV_TPX_REPLICA_ID: str(replica_id),
        settings.ENV_TPX_ROLE_NAME: role.name,
        settings.ENV_TPX_NUM_REPLICAS: str(num),
        settings.ENV_TPX_COORDINATOR_HOST: coordinator_host,
    }
    if role.resource is not None and role.resource.tpu is not None:
        tpu = role.resource.tpu
        env["TPX_TPU_ACCELERATOR_TYPE"] = tpu.accelerator_type
        env["TPX_TPU_TOPOLOGY"] = tpu.default_topology()
        if role.num_replicas > 1:  # multi-slice: DCN identity
            from torchx_tpu import settings as s

            slice_id = replica_id // tpu.hosts
            # same surface as the GKE pod template's decomposition (there
            # the bootstrap derives the global id from these; here both
            # forms are present and TPX_REPLICA_ID wins)
            env[s.ENV_TPX_SLICE_ID] = str(slice_id)
            env[s.ENV_TPX_HOST_ID] = str(replica_id % tpu.hosts)
            env[s.ENV_TPX_HOSTS_PER_SLICE] = str(tpu.hosts)
            env[s.ENV_MEGASCALE_NUM_SLICES] = str(role.num_replicas)
            env[s.ENV_MEGASCALE_SLICE_ID] = str(slice_id)
            env[s.ENV_MEGASCALE_COORDINATOR_ADDRESS] = (
                f"{coordinator_host}:{coordinator_port + 1}"
            )
    return env
