"""Local scheduler: runs each replica as a host subprocess.

Reference analog: torchx/schedulers/local_scheduler.py (1211 LoC). Kept
behaviors: ImageProvider abstraction, per-replica log dirs with
stdout/stderr/combined Tee, macro substitution, coordinator env injection,
error-file injection, LRU app cache, SIGTERM->SIGKILL kill ladder, orphan
cleanup on client signals, tail-follow log iteration.

TPU-first departures:

* instead of ``auto_set_CUDA_VISIBLE_DEVICES`` (reference :855-945), replicas
  sharing one TPU host get ``TPU_VISIBLE_CHIPS`` partitioning; and when the
  role wants TPU but the host has none, ``tpu_simulate=True`` (default) runs
  the replica on CPU JAX with ``xla_force_host_platform_device_count`` equal
  to the requested per-host chip count — so SPMD apps run anywhere.
* the injected rendezvous env is ``TPX_COORDINATOR_HOST=localhost`` plus the
  gang identity vars consumed by ``torchx_tpu.distributed.init_from_env``
  (the analog of TORCHX_RANK0_HOST at reference :990-993).
"""

from __future__ import annotations

import glob
import logging
import os
import signal
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, IO, Iterable, Mapping, Optional, TextIO

from torchx_tpu import settings
from torchx_tpu.resilience.call import resilient_call
from torchx_tpu.schedulers.api import (
    DescribeAppResponse,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    role_replica_env,
    tpu_hosts_for_role,
    window_stamped_lines,
)
from torchx_tpu.schedulers.ids import make_unique
from torchx_tpu.schedulers.streams import Tee
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    NONE,
    ReplicaStatus,
    RetryPolicy,
    Role,
    RoleStatus,
    is_terminal,
    macros,
    runopts,
)

logger = logging.getLogger(__name__)

KILL_GRACE_SECONDS = 10
APP_CACHE_SIZE = 100

# Cross-process visibility: each app's owning scheduler writes a state file
# under its log dir and records app_id -> log_dir in a per-user registry,
# so `tpx status`/`tpx log` from ANOTHER process can still find and read it
# (the reference's local scheduler is in-process only; log files were
# always on disk — this makes the metadata reachable too).
STATE_FILE = ".tpx_state.json"

#: per-replica exit-code sidecar written by the /bin/sh launch wrapper;
#: read by _describe_external to recover terminal state after the owning
#: client process crashed (its in-memory Popen handles died with it).
EXITCODE_FILE = "exitcode"
APPS_REGISTRY = ".tpx_local_apps"


def _registry_path() -> str:
    return os.path.join(os.path.expanduser("~"), APPS_REGISTRY)


def _registry_record(app_id: str, log_dir: str) -> None:
    from torchx_tpu.util import registry

    # compaction drops entries whose log dirs are gone; lock-protected so
    # concurrent submits never lose each other's lines
    registry.record(_registry_path(), app_id, log_dir, keep=os.path.isdir)


def _registry_entries() -> list[tuple[str, str]]:
    from torchx_tpu.util import registry

    return registry.entries(_registry_path())


def _registry_lookup(app_id: str) -> Optional[str]:
    from torchx_tpu.util import registry

    return registry.lookup(_registry_path(), app_id)


def _recover_sidecar_state(log_dir: str, payload: dict) -> AppState:
    """Terminal state of a crashed-owner app from exit-code sidecars.

    The owner process died before writing a terminal state (SIGKILL, OOM,
    power loss), but each replica's /bin/sh launch wrapper durably wrote
    its exit code. All replicas 0 -> SUCCEEDED; any nonzero -> FAILED; any
    sidecar missing (replica still running when the machine died, or a
    pre-sidecar writer) -> UNKNOWN, exactly the pre-recovery behavior. A
    SUCCESS marker short-circuits (the owner DID finish; only the state
    file write was lost)."""
    if os.path.exists(os.path.join(log_dir, "SUCCESS")):
        return AppState.SUCCEEDED
    codes: list[int] = []
    for role_name, replicas in payload.get("roles", {}).items():
        for r in replicas:
            rc_file = os.path.join(
                log_dir, role_name, str(r.get("id", 0)), EXITCODE_FILE
            )
            try:
                with open(rc_file) as f:
                    codes.append(int(f.read().strip()))
            except (OSError, ValueError):
                return AppState.UNKNOWN
    if not codes:
        return AppState.UNKNOWN
    return AppState.SUCCEEDED if all(c == 0 for c in codes) else AppState.FAILED


def _state_file_says_cancelled(log_dir: str) -> bool:
    import json

    try:
        with open(os.path.join(log_dir, STATE_FILE)) as f:
            return json.load(f).get("state") == AppState.CANCELLED.name
    except (OSError, json.JSONDecodeError):
        return False


def _atomic_write_json(path: str, payload: dict) -> None:
    """Unique-tmp + os.replace: concurrent writers (owner vs external
    canceller) can't truncate each other's in-flight tmp, and readers
    never observe partial JSON."""
    import json
    import tempfile as _tempfile

    fd, tmp = _tempfile.mkstemp(dir=os.path.dirname(path), prefix=".tpx_state_")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _pid_start_time(pid: int) -> Optional[int]:
    """Process start time (clock ticks) from /proc — disambiguates pid
    reuse. None where /proc is unavailable."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(") ", 1)[-1].split()
        return int(fields[19])  # starttime is field 22 overall
    except (OSError, ValueError, IndexError):
        return None


def _pid_alive(pid: int, start_time: Optional[int] = None) -> bool:
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    if start_time is not None:
        current = _pid_start_time(pid)
        if current is not None and current != start_time:
            return False  # pid was reused by an unrelated process
    return True


# =========================================================================
# Image providers
# =========================================================================


class ImageProvider:
    """Resolves a Role.image to a local directory (reference :110-279)."""

    def fetch(self, image: str) -> str:
        """Returns the root dir for the image; '' means no chroot."""
        raise NotImplementedError

    def get_entrypoint(self, img_root: str, role_args_entrypoint: str) -> str:
        entrypoint = role_args_entrypoint
        if img_root and not os.path.isabs(entrypoint):
            candidate = os.path.join(img_root, entrypoint)
            if os.path.exists(candidate):
                return candidate
        return entrypoint


class LocalDirectoryImageProvider(ImageProvider):
    """image is an existing local directory path."""

    def fetch(self, image: str) -> str:
        if not os.path.isdir(image):
            raise ValueError(
                f"image {image!r} must be an existing local directory"
                " for the local scheduler"
            )
        return image


class CWDImageProvider(ImageProvider):
    """ignore image entirely; run from the current working directory."""

    def fetch(self, image: str) -> str:
        return os.getcwd()


# =========================================================================
# TPU host inventory / partitioning
# =========================================================================


def local_tpu_chip_count() -> int:
    """Count TPU chips attached to this host (accel device nodes)."""
    return len(glob.glob("/dev/accel*")) or len(glob.glob("/dev/vfio/[0-9]*"))


def tpu_device_env(
    role_tpu_chips_per_host: int,
    replica_id: int,
    replicas_on_host: int,
    host_chips: int,
    simulate: bool,
    partition: bool = True,
) -> dict[str, str]:
    """Env partitioning a host's chips among colocated replicas, or CPU
    simulation when the host has no TPUs (analog of the reference's
    CUDA_VISIBLE_DEVICES partitioning, local_scheduler.py:855-945).

    Raises at dryrun time when the gang is over-subscribed (more replicas
    than chips) — better than a wedged collective at runtime.
    """
    if host_chips <= 0:
        if not simulate:
            return {}
        return {
            settings.ENV_JAX_PLATFORMS: "cpu",
            settings.ENV_XLA_FLAGS: (
                f"--xla_force_host_platform_device_count={role_tpu_chips_per_host}"
            ),
        }
    if not partition or replicas_on_host <= 1:
        return {}  # replica sees all host chips
    if replicas_on_host > host_chips:
        raise ValueError(
            f"{replicas_on_host} replicas cannot share {host_chips} TPU chips"
            " on this host (at least one chip per replica required);"
            " reduce replicas or disable auto_set_tpu_chips"
        )
    per = host_chips // replicas_on_host
    start = (replica_id % replicas_on_host) * per
    chips = ",".join(str(c) for c in range(start, start + per))
    return {settings.ENV_TPU_VISIBLE_CHIPS: chips, settings.ENV_TPU_SKIP_MDS_QUERY: "true"}


# =========================================================================
# Materialized request
# =========================================================================


@dataclass
class ReplicaParam:
    """Everything needed to Popen one replica (pre-substituted)."""

    args: list[str]
    env: dict[str, str]
    stdout: str
    stderr: str
    combined: str
    cwd: Optional[str] = None


@dataclass
class PopenRequest:
    app_id: str
    log_dir: str
    role_params: dict[str, list[ReplicaParam]] = field(default_factory=dict)
    # retained for elastic restarts: rebuilding a SMALLER gang needs the
    # original roles (min_replicas/max_retries) and submit-time cfg
    app: Optional[AppDef] = None
    cfg: dict[str, CfgVal] = field(default_factory=dict)


# =========================================================================
# Live process bookkeeping
# =========================================================================


class _LocalReplica:
    def __init__(
        self,
        role_name: str,
        replica_id: int,
        proc: subprocess.Popen,
        stdout: Optional[IO],
        stderr: Optional[IO],
        tee: Optional[Tee],
        error_file: str,
    ) -> None:
        self.role_name = role_name
        self.replica_id = replica_id
        self.proc = proc
        self.stdout = stdout
        self.stderr = stderr
        self.tee = tee
        self.error_file = error_file

    def terminate(self) -> None:
        """SIGTERM the whole process group, wait, then SIGKILL survivors."""
        try:
            pgid = os.getpgid(self.proc.pid)
            os.killpg(pgid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            self.proc.wait(timeout=KILL_GRACE_SECONDS)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
            self.proc.wait()
        self._close_files()

    def _close_files(self) -> None:
        if self.tee:
            self.tee.close()
            self.tee = None
        for f in (self.stdout, self.stderr):
            if f:
                f.close()
        self.stdout = self.stderr = None

    def is_alive(self) -> bool:
        return self.proc.poll() is None

    def failed(self) -> bool:
        rc = self.proc.returncode
        return rc is not None and rc != 0


class _LocalApp:
    def __init__(
        self,
        app_id: str,
        log_dir: str,
        request: Optional[PopenRequest] = None,
    ) -> None:
        self.app_id = app_id
        self.log_dir = log_dir
        self.roles: dict[str, list[_LocalReplica]] = {}
        self.state = AppState.PENDING
        self.last_updated = time.time()
        self.request = request  # for elastic gang rebuilds
        self.num_restarts = 0  # app-wide total (surfaced in describe)
        self.role_restarts: dict[str, int] = {}  # per-role budget tracking

    def write_state_file(self) -> None:
        """Snapshot for cross-process status/log (best-effort)."""
        import json

        payload = {
            "app_id": self.app_id,
            "state": self.state.name,
            "log_dir": self.log_dir,
            "roles": {
                name: [
                    {
                        "id": r.replica_id,
                        "pid": r.proc.pid,
                        "pid_start": _pid_start_time(r.proc.pid),
                    }
                    for r in replicas
                ]
                for name, replicas in self.roles.items()
            },
        }
        try:
            os.makedirs(self.log_dir, exist_ok=True)
            _atomic_write_json(os.path.join(self.log_dir, STATE_FILE), payload)
        except OSError as e:
            logger.debug("could not write state file: %s", e)

    def add_replica(self, role_name: str, replica: _LocalReplica) -> None:
        self.roles.setdefault(role_name, []).append(replica)

    def replicas(self) -> Iterable[_LocalReplica]:
        for rs in self.roles.values():
            yield from rs

    def set_state(self, state: AppState) -> None:
        self.state = state
        self.last_updated = time.time()
        self.write_state_file()

    def kill(self) -> None:
        for r in self.replicas():
            r.terminate()
        if not is_terminal(self.state):
            self.set_state(AppState.CANCELLED)

    def first_error_file(self) -> str:
        """Earliest-written error file among failed replicas (reference
        _LocalAppDef._get_error_file, :422-433)."""
        candidates = [
            r.error_file
            for r in self.replicas()
            if r.failed() and os.path.exists(r.error_file)
        ]
        if not candidates:
            return ""
        return min(candidates, key=lambda p: os.path.getmtime(p))


# =========================================================================
# Scheduler
# =========================================================================


# Feature profile for the preflight analyzer (torchx_tpu.analyze): local
# subprocesses simulate gangs, multi-slice identity env, elastic restarts
# and (via TPX_SIMULATE_PREEMPTION_EXIT) preemption classification — but
# mounts are silently ignored by Popen, so they are declared unsupported.
CAPABILITIES = SchedulerCapabilities(
    mounts=False,
    multi_role=True,
    multislice=True,
    delete=True,
    resize=True,
    logs=True,
    native_retries=True,
    concrete_resources=False,
    classifies_preemption=True,
    # native event source: the state file + exitcode sidecars every job
    # leaves next to its logs (see LocalScheduler.watch)
    watch=True,
    # replicas bind loopback ports the daemon's collector can scrape
    metricz_scrape=True,
)


class LocalScheduler(Scheduler[PopenRequest]):
    """Executes AppDef roles as local subprocesses."""

    capabilities = CAPABILITIES

    # combined.log lines are epoch-stamped by the Tee (streams.py), so
    # since/until windows are honored on the default combined stream
    supports_log_windows = True

    def __init__(
        self,
        session_name: str,
        image_provider: Optional[ImageProvider] = None,
        cache_size: int = APP_CACHE_SIZE,
        extra_paths: Optional[list[str]] = None,
    ) -> None:
        super().__init__("local", session_name)
        self._image_provider = image_provider or CWDImageProvider()
        self._apps: dict[str, _LocalApp] = {}
        self._external_dirs: dict[str, str] = {}  # app_id -> log_dir cache
        self._cache_size = cache_size
        self._extra_paths = extra_paths or []
        self._installed_signal_cleanup = False

    # -- runopts ----------------------------------------------------------

    def run_opts(self) -> runopts:
        opts = runopts()
        opts.add(
            "log_dir",
            type_=str,
            default=None,
            help="root dir for per-replica logs (default: a tmp dir)",
        )
        opts.add(
            "prepend_cwd",
            type_=bool,
            default=False,
            help="prepend CWD to PATH when resolving entrypoints",
        )
        opts.add(
            "auto_set_tpu_chips",
            type_=bool,
            default=True,
            help="partition the host's TPU chips among colocated replicas"
            " via TPU_VISIBLE_CHIPS",
        )
        opts.add(
            "tpu_simulate",
            type_=bool,
            default=True,
            help="when a role requests TPU but this host has no chips, run"
            " on CPU JAX with xla_force_host_platform_device_count set to"
            " the per-host chip count",
        )
        return opts

    # -- dryrun -----------------------------------------------------------

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[PopenRequest]:
        app_id = make_unique(app.name)
        base_log_dir = cfg.get("log_dir") or os.path.join(
            tempfile.gettempdir(), "torchx_tpu", self.session_name
        )
        log_dir = os.path.join(str(base_log_dir), app_id)
        request = PopenRequest(
            app_id=app_id, log_dir=log_dir, app=app, cfg=dict(cfg)
        )
        for role in app.roles:
            request.role_params[role.name] = self._build_role_replicas(
                role, app_id, log_dir, cfg
            )
        return AppDryRunInfo(request, fmt=_pretty_request)

    def _build_role_replicas(
        self,
        role: Role,
        app_id: str,
        log_dir: str,
        cfg: Mapping[str, CfgVal],
        num_replicas: Optional[int] = None,
    ) -> list[ReplicaParam]:
        """Materialize the Popen params for one role's gang.

        ``num_replicas`` overrides the role-derived gang size — the elastic
        restart path rebuilds a SMALLER world after host loss (every replica
        gets fresh TPX_NUM_REPLICAS / TPX_REPLICA_ID for the resized mesh).
        """
        host_chips = local_tpu_chip_count()
        img_root = self._image_provider.fetch(role.image)
        replicas: list[ReplicaParam] = []
        if num_replicas is None:
            num_replicas = tpu_hosts_for_role(role)
        else:
            # elastic resize: rebuild the role at the new world size so
            # EVERY derived env agrees (TPX_NUM_REPLICAS, megascale slice
            # count, slice decomposition) — not just a patched world size.
            # For TPU roles num_replicas is in host units and the caller
            # guarantees it is a whole-slice multiple.
            hosts = (
                role.resource.tpu.hosts
                if role.resource is not None and role.resource.tpu is not None
                else 1
            )
            import dataclasses as _dc

            role = _dc.replace(role, num_replicas=num_replicas // hosts)
        for replica_id in range(num_replicas):
            values = macros.Values(
                img_root=img_root,
                app_id=app_id,
                replica_id=str(replica_id),
                num_replicas=str(num_replicas),
                coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
            )
            rrole = values.apply(role)
            replica_log_dir = os.path.join(log_dir, role.name, str(replica_id))

            env = dict(os.environ)
            env.update(rrole.env)
            env["PYTHONUNBUFFERED"] = "1"
            env[settings.ENV_TPX_APP_ID] = app_id
            env[settings.ENV_TPX_JOB_ID] = f"{self.backend}://{self.session_name}/{app_id}"
            env[settings.ENV_TPX_LOG_DIR] = replica_log_dir
            error_file = os.path.join(replica_log_dir, "error.json")
            env[settings.ENV_TPX_ERROR_FILE] = error_file
            env.update(
                role_replica_env(
                    role,
                    replica_id,
                    coordinator_host="localhost",
                    coordinator_port=settings.TPX_COORDINATOR_PORT,
                )
            )
            if role.resource is not None and role.resource.tpu is not None:
                env.update(
                    tpu_device_env(
                        role.resource.tpu.chips_per_host,
                        replica_id,
                        replicas_on_host=num_replicas,
                        host_chips=host_chips,
                        simulate=bool(cfg.get("tpu_simulate", True)),
                        partition=bool(cfg.get("auto_set_tpu_chips", True)),
                    )
                )
            paths = [p for p in self._extra_paths]
            if cfg.get("prepend_cwd"):
                paths.insert(0, os.getcwd())
            if img_root:
                paths.append(img_root)
            if paths:
                env["PATH"] = os.pathsep.join(paths + [env.get("PATH", "")])

            entrypoint = self._image_provider.get_entrypoint(
                img_root, rrole.entrypoint
            )
            replicas.append(
                ReplicaParam(
                    args=[entrypoint, *rrole.args],
                    env=env,
                    stdout=os.path.join(replica_log_dir, "stdout.log"),
                    stderr=os.path.join(replica_log_dir, "stderr.log"),
                    combined=os.path.join(replica_log_dir, "combined.log"),
                    cwd=img_root or None,
                )
            )
        return replicas

    # -- schedule ---------------------------------------------------------

    def schedule(self, dryrun_info: AppDryRunInfo[PopenRequest]) -> str:
        from torchx_tpu.obs import trace as obs_trace

        request = dryrun_info.request
        self._evict_lru()
        self._install_signal_cleanup()
        app = _LocalApp(request.app_id, request.log_dir, request=request)
        try:
            with obs_trace.span(
                "scheduler.spawn",
                session=self.session_name,
                scheduler=self.backend,
                app_id=request.app_id,
                replicas=sum(len(r) for r in request.role_params.values()),
            ):
                for role_name, replicas in request.role_params.items():
                    for replica_id, rp in enumerate(replicas):
                        app.add_replica(
                            role_name, self._popen(role_name, replica_id, rp)
                        )
        except Exception:
            app.kill()
            raise
        app.set_state(AppState.RUNNING)
        _registry_record(request.app_id, request.log_dir)
        self._apps[request.app_id] = app
        return request.app_id

    def _popen(self, role_name: str, replica_id: int, rp: ReplicaParam) -> _LocalReplica:
        os.makedirs(os.path.dirname(rp.stdout), exist_ok=True)
        stdout = open(rp.stdout, "wb")
        stderr = open(rp.stderr, "wb")
        tee = Tee(Path(rp.combined), Path(rp.stdout), Path(rp.stderr))
        # /bin/sh wrapper persists the replica's exit code next to its logs
        # (atomic tmp+rename). The launcher's in-memory proc handle dies
        # with the client process; the sidecar is what lets a RESUMED
        # supervise client (or any other process) recover SUCCEEDED vs
        # FAILED after the owner crashed. Exit codes pass through exactly
        # (`exit "$rc"`), so drills comparing proc.poll() to a specific
        # code (TPX_SIMULATE_PREEMPTION_EXIT) are unaffected.
        rc_file = os.path.join(os.path.dirname(rp.stdout), EXITCODE_FILE)
        try:
            os.unlink(rc_file)
        except OSError:
            pass
        wrapped = [
            "/bin/sh",
            "-c",
            '"$@"; rc=$?; printf %s "$rc" > "$0.tmp" && mv -f "$0.tmp" "$0"; exit "$rc"',
            rc_file,
            *rp.args,
        ]
        proc = subprocess.Popen(
            wrapped,
            env=rp.env,
            stdout=stdout,
            stderr=stderr,
            cwd=rp.cwd,
            start_new_session=True,  # own process group: clean gang kill
        )
        logger.debug(
            "started %s/%s pid=%d: %s", role_name, replica_id, proc.pid, rp.args
        )
        return _LocalReplica(
            role_name,
            replica_id,
            proc,
            stdout,
            stderr,
            tee,
            error_file=rp.env.get(settings.ENV_TPX_ERROR_FILE, ""),
        )

    def _evict_lru(self) -> None:
        while len(self._apps) >= self._cache_size:
            terminal = [
                (a.last_updated, app_id)
                for app_id, a in self._apps.items()
                if is_terminal(a.state)
            ]
            if not terminal:
                raise RuntimeError(
                    f"app cache full ({self._cache_size}) with no terminal"
                    " apps to evict; wait for or cancel running apps"
                )
            _, oldest = min(terminal)
            self._apps.pop(oldest)

    def _install_signal_cleanup(self) -> None:
        """Kill all child gangs if the client process dies (reference
        :541-549). Only from the main thread; no-op otherwise."""
        if self._installed_signal_cleanup:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
            prev = signal.getsignal(sig)

            def handler(signum, frame, prev=prev):  # noqa: ANN001
                self.close()
                if callable(prev):
                    prev(signum, frame)
                else:
                    signal.signal(signum, signal.SIG_DFL)
                    signal.raise_signal(signum)

            try:
                signal.signal(sig, handler)
            except ValueError:
                return  # not main thread after all
        self._installed_signal_cleanup = True

    # -- monitoring -------------------------------------------------------

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        # even the in-process backend routes status through the resilient
        # seam: TPX_FAULT_PLAN drills (inject transient failures into the
        # supervisor's poll loop) exercise the same retry/breaker/span
        # machinery that guards gcloud/kubectl on the cloud backends
        return resilient_call(
            lambda: self._describe_impl(app_id),
            backend=self.backend,
            op="describe",
        )

    def _describe_impl(self, app_id: str) -> Optional[DescribeAppResponse]:
        app = self._apps.get(app_id)
        if app is None:
            return self._describe_external(app_id)
        self._update_app_state(app)
        roles_statuses = []
        for role_name, replicas in app.roles.items():
            rs = RoleStatus(role=role_name)
            for r in replicas:
                rc = r.proc.poll()
                if rc is None:
                    state = AppState.RUNNING
                elif rc == 0:
                    state = AppState.SUCCEEDED
                else:
                    state = (
                        AppState.CANCELLED
                        if app.state == AppState.CANCELLED
                        else AppState.FAILED
                    )
                rs.replicas.append(
                    ReplicaStatus(
                        id=r.replica_id,
                        state=state,
                        role=role_name,
                        hostname="localhost",
                    )
                )
            roles_statuses.append(rs)

        structured_error_msg = NONE
        err_file = app.first_error_file()
        if app.state == AppState.FAILED and err_file:
            try:
                structured_error_msg = Path(err_file).read_text()
            except OSError:
                pass

        return DescribeAppResponse(
            app_id=app_id,
            state=app.state,
            num_restarts=app.num_restarts,
            structured_error_msg=structured_error_msg,
            ui_url=f"file://{app.log_dir}",
            roles_statuses=roles_statuses,
        )

    def _describe_external(self, app_id: str) -> Optional[DescribeAppResponse]:
        """Status of an app owned by ANOTHER process, from its state file.

        Terminal states are authoritative (the owner wrote them); for a
        still-RUNNING file, pid liveness decides between RUNNING and
        UNKNOWN (owner gone — exit codes are unknowable across processes).
        """
        import json

        log_dir = self._external_dirs.get(app_id) or _registry_lookup(app_id)
        if log_dir is None:
            return None
        self._external_dirs[app_id] = log_dir  # skip registry rescans on polls
        try:
            with open(os.path.join(log_dir, STATE_FILE)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        try:
            state = AppState[payload.get("state", "UNKNOWN")]
        except KeyError:  # unrecognized state name (newer writer / bad file)
            state = AppState.UNKNOWN
        if not is_terminal(state):
            procs = [
                (r["pid"], r.get("pid_start"))
                for replicas in payload.get("roles", {}).values()
                for r in replicas
            ]
            if any(_pid_alive(p, st) for p, st in procs):
                state = AppState.RUNNING
            else:
                # owner died without writing a terminal state; the launch
                # wrapper's exit-code sidecars are the crash-safe record
                state = _recover_sidecar_state(log_dir, payload)
        roles_statuses = [
            RoleStatus(
                role=name,
                replicas=[
                    ReplicaStatus(
                        id=r["id"], state=state, role=name, hostname="localhost"
                    )
                    for r in replicas
                ],
            )
            for name, replicas in payload.get("roles", {}).items()
        ]
        return DescribeAppResponse(
            app_id=app_id,
            state=state,
            ui_url=f"file://{log_dir}",
            roles_statuses=roles_statuses,
        )

    def _update_app_state(self, app: _LocalApp) -> None:
        if is_terminal(app.state):
            return
        any_alive = False
        any_failed = False
        for r in app.replicas():
            rc = r.proc.poll()
            if rc is None:
                any_alive = True
            else:
                r._close_files()
                if rc != 0:
                    any_failed = True
        if any_failed:
            # fail fast: kill the rest of the gang (SPMD semantics — a dead
            # host wedges the collective anyway). If an external `tpx
            # cancel` already marked the app CANCELLED on disk, honor that
            # instead of recording the SIGTERM'd children as a failure.
            if _state_file_says_cancelled(app.log_dir):
                for r in app.replicas():
                    if r.is_alive():
                        r.terminate()
                app.set_state(AppState.CANCELLED)
            elif self._simulated_preemption(app):
                for r in app.replicas():
                    if r.is_alive():
                        r.terminate()
                app.set_state(AppState.PREEMPTED)
            elif self._try_elastic_restart(app):
                return
            else:
                for r in app.replicas():
                    if r.is_alive():
                        r.terminate()
                app.set_state(AppState.FAILED)
        elif not any_alive:
            if _state_file_says_cancelled(app.log_dir):
                # an external cancel landed and the replicas exited 0
                # (graceful SIGTERM handling) — a cancelled run must not
                # report SUCCEEDED or mint a SUCCESS marker
                app.set_state(AppState.CANCELLED)
            else:
                app.set_state(AppState.SUCCEEDED)
                Path(app.log_dir, "SUCCESS").touch()

    def _simulated_preemption(self, app: _LocalApp) -> bool:
        """True when a preemption drill is armed and a replica tripped it.

        Opt-in only: a role env must set ``TPX_SIMULATE_PREEMPTION_EXIT``
        to an exit code, and some replica must have exited with exactly
        that code. The attempt then terminates PREEMPTED (the base
        ``classify_failure`` maps it to FailureClass.PREEMPTION), which
        lets ``tpx supervise`` be drilled against real spot semantics on
        a laptop. Everything else — elastic restart, FAILED fast-kill —
        is untouched when the env var is absent.
        """
        request = app.request
        if request is None:
            return False
        drill_code: Optional[int] = None
        for replicas in request.role_params.values():
            for rp in replicas:
                raw = rp.env.get(settings.ENV_TPX_SIMULATE_PREEMPTION_EXIT)
                if raw:
                    try:
                        drill_code = int(raw)
                    except ValueError:
                        return False
                    break
            if drill_code is not None:
                break
        if drill_code is None:
            return False
        return any(r.proc.poll() == drill_code for r in app.replicas())

    def _try_elastic_restart(self, app: _LocalApp) -> bool:
        """Shrink-and-restart a failed elastic gang (BASELINE config 4).

        SPMD worlds resize by restart: when a replica of a role with
        ``min_replicas`` dies, the surviving budget (``max_retries``)
        relaunches the WHOLE gang with a smaller world — every replica gets
        fresh TPX_REPLICA_ID / TPX_NUM_REPLICAS so ``spmd_main`` re-forms
        ``jax.distributed`` over the resized mesh and user code resumes from
        its last checkpoint. The analog of torchrun's ``--nnodes min:max``
        elastic rendezvous (reference components/dist.py:294-296), mapped to
        the TPU model where world size is fixed per jax.distributed world.
        """
        request = app.request
        if request is None or request.app is None:
            return False
        # plan per-role: every FAILED role must be restartable within ITS
        # OWN budget, and decides its new size; healthy roles restart as-is
        # only when some failed role is APPLICATION-scoped (ROLE-scoped
        # failures leave healthy roles running untouched)
        new_sizes: dict[str, int] = {}
        failed_roles: set[str] = set()
        role_scoped_only = True
        for role in request.app.roles:
            replicas = app.roles.get(role.name, [])
            n_failed = sum(1 for r in replicas if r.failed())
            cur = len(replicas)
            if n_failed == 0:
                continue  # planned below once the restart scope is known
            failed_roles.add(role.name)
            # each role consumes ITS OWN budget: a restart triggered by
            # role A must not burn role B's retries (and vice versa)
            spent = app.role_restarts.get(role.name, 0)
            if spent >= role.max_retries and role.min_replicas is None:
                return False  # this role's own budget is spent
            if role.min_replicas is None:
                # rigid gang: APPLICATION restarts the whole app, ROLE
                # restarts just this role, both at FULL size (the local
                # analog of JobSet maxRestarts / slurm requeue);
                # REPLICA-scoped retries are fatal for a gang
                if role.retry_policy == RetryPolicy.REPLICA:
                    return False
                if role.retry_policy == RetryPolicy.APPLICATION:
                    role_scoped_only = False
                new_sizes[role.name] = cur
                continue
            # elastic: shrink, budgeted by max_retries as well
            if spent >= max(1, role.max_retries):
                return False
            role_scoped_only = False  # a resized world needs a full restart
            hosts = (
                role.resource.tpu.hosts
                if role.resource is not None and role.resource.tpu is not None
                else 1
            )
            # TPU gangs shrink in whole slices: a partial slice can never
            # form a valid ICI topology
            new_n = ((cur - n_failed) // hosts) * hosts
            if new_n < max(1, role.min_replicas * hosts):
                return False  # below the elastic floor
            new_sizes[role.name] = new_n
        if not new_sizes:
            return False  # nothing actually failed
        if not role_scoped_only:
            # APPLICATION/elastic scope: healthy roles restart at full size
            for role in request.app.roles:
                if role.name not in new_sizes:
                    new_sizes[role.name] = len(app.roles.get(role.name, []))
        attempt = app.num_restarts + 1
        logger.warning(
            "gang restart #%d of %s (%s-scoped): %s",
            attempt,
            app.app_id,
            "role" if role_scoped_only else "app",
            {
                r: f"{len(app.roles.get(r, []))} -> {n}"
                for r, n in new_sizes.items()
            },
        )
        for role_name in new_sizes:
            self._teardown_role_gang(app, role_name)
        app.num_restarts = attempt
        for role_name in failed_roles:
            app.role_restarts[role_name] = app.role_restarts.get(role_name, 0) + 1
        try:
            for role in request.app.roles:
                if role.name not in new_sizes:
                    continue  # ROLE-scoped restart: healthy role kept alive
                self._launch_role_gang(
                    app, role, new_sizes[role.name], attempt, request.cfg
                )
        except Exception:
            app.kill()
            app.set_state(AppState.FAILED)
            return True  # state handled (failed during relaunch)
        app.set_state(AppState.RUNNING)
        return True

    def _teardown_role_gang(self, app: _LocalApp, role_name: str) -> None:
        """Stop one role's replicas and drop them from the app (shared by
        elastic restart and manual resize)."""
        for r in app.roles.get(role_name, []):
            if r.is_alive():
                r.terminate()
            else:
                r._close_files()
        app.roles.pop(role_name, None)

    def _launch_role_gang(
        self,
        app: _LocalApp,
        role: Role,
        num_replicas: int,
        attempt: int,
        cfg: Mapping[str, CfgVal],
    ) -> None:
        """(Re)launch one role's gang ``num_replicas`` hosts wide, rotating
        the previous attempt's logs aside."""
        params = self._build_role_replicas(
            role,
            app.app_id,
            app.log_dir,
            cfg,
            num_replicas=num_replicas,
        )
        for replica_id, rp in enumerate(params):
            _rotate_attempt_logs(rp, attempt)
            app.add_replica(role.name, self._popen(role.name, replica_id, rp))

    def watch(self, app_ids=(), interval=None):
        """Native event stream: mtime-polls the state file and counts the
        per-replica ``exitcode`` sidecars the launch wrapper writes, so a
        tick over N jobs costs N ``stat`` calls and a describe only fires
        to *confirm* an observed change (state writes, external cancels,
        replica exits all bump one of those signals)."""
        from torchx_tpu.control.watch import LocalSidecarWatcher

        return LocalSidecarWatcher(self, app_ids, interval=interval)

    def list(self) -> list[ListAppResponse]:
        return resilient_call(
            lambda: self._list_impl(), backend=self.backend, op="list"
        )

    def _list_impl(self) -> list[ListAppResponse]:
        out = []
        for app_id, app in self._apps.items():
            self._update_app_state(app)
            out.append(ListAppResponse(app_id=app_id, state=app.state, name=app_id))
        # apps owned by other processes, via the registry (one scan total)
        for app_id, log_dir in dict(_registry_entries()).items():
            if app_id in self._apps:
                continue
            self._external_dirs.setdefault(app_id, log_dir)
            desc = self._describe_external(app_id)
            if desc is not None:
                out.append(
                    ListAppResponse(app_id=app_id, state=desc.state, name=app_id)
                )
        return out

    def _cancel_existing(self, app_id: str) -> None:
        def _do() -> None:
            app = self._apps.get(app_id)
            if app is not None:
                app.kill()
                return
            self._cancel_external(app_id)

        resilient_call(_do, backend=self.backend, op="cancel")

    def _cancel_external(self, app_id: str) -> None:
        """Kill an app owned by another process: SIGTERM its process groups
        (replicas start_new_session, so pgid == pid) and mark the state
        file CANCELLED for every future reader."""
        import json

        desc = self._describe_external(app_id)
        if desc is None or is_terminal(desc.state):
            return
        log_dir = self._external_dirs.get(app_id) or _registry_lookup(app_id)
        try:
            with open(os.path.join(log_dir, STATE_FILE)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        # mark CANCELLED on disk FIRST: the live owner polls its children
        # and must find the mark before it can misread their SIGTERM deaths
        # as a failure (or a graceful exit-0 as success)
        payload["state"] = AppState.CANCELLED.name
        try:
            _atomic_write_json(os.path.join(log_dir, STATE_FILE), payload)
        except OSError:
            pass
        for replicas in payload.get("roles", {}).values():
            for r in replicas:
                if not _pid_alive(r["pid"], r.get("pid_start")):
                    continue  # dead or pid reused by an unrelated process
                try:
                    os.killpg(r["pid"], signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass

    def delete(self, app_id: str) -> None:
        """Cancel (if still running) and forget the app entirely: the
        session cache, the external-dir cache, and the per-user registry
        entry — ``exists``/``describe``/``list`` stop reporting it. Log
        files on disk are left for the operator to reclaim."""
        self.cancel(app_id)
        self._apps.pop(app_id, None)
        self._external_dirs.pop(app_id, None)
        from torchx_tpu.util import registry

        registry.remove(_registry_path(), app_id)

    def resize(self, app_id: str, role_name: str, num_replicas: int) -> None:
        """Manual gang resize (grow or shrink) — the operator-driven
        counterpart of ``_try_elastic_restart``'s shrink-on-failure. The
        whole role gang restarts with a coherent world: every replica gets
        fresh TPX_NUM_REPLICAS / TPX_REPLICA_ID / slice decomposition, and
        user code resumes from its checkpoint."""
        app = self._apps.get(app_id)
        if app is None:
            registered = _registry_lookup(app_id)
            raise ValueError(
                f"unknown app: {app_id}"
                if registered is None
                else f"app {app_id} is owned by another process; resize from"
                " the session that submitted it"
            )
        self._update_app_state(app)
        if is_terminal(app.state):
            raise ValueError(f"cannot resize terminal app {app_id} ({app.state.name})")
        request = app.request
        if request is None or request.app is None:
            raise ValueError(f"app {app_id} has no retained request; cannot resize")
        role = next((r for r in request.app.roles if r.name == role_name), None)
        if role is None:
            raise ValueError(f"app {app_id} has no role {role_name!r}")
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        if role.min_replicas is not None and num_replicas < role.min_replicas:
            raise ValueError(
                f"cannot resize role {role_name!r} to {num_replicas}: below"
                f" its declared min_replicas floor of {role.min_replicas}"
            )
        hosts = (
            role.resource.tpu.hosts
            if role.resource is not None and role.resource.tpu is not None
            else 1
        )
        new_hosts = num_replicas * hosts  # whole slices only, by construction
        if new_hosts == len(app.roles.get(role_name, [])):
            return  # already at the requested size
        attempt = app.num_restarts + 1
        logger.warning(
            "manual resize of %s role %s: %d -> %d replicas (gang restart #%d)",
            app_id,
            role_name,
            len(app.roles.get(role_name, [])),
            new_hosts,
            attempt,
        )
        self._teardown_role_gang(app, role_name)
        app.num_restarts = attempt
        try:
            self._launch_role_gang(app, role, new_hosts, attempt, request.cfg)
        except Exception:
            app.kill()
            app.set_state(AppState.FAILED)
            raise
        app.set_state(AppState.RUNNING)

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        app = self._apps.get(app_id)
        if app is not None:
            log_root = app.log_dir
        else:
            external = _registry_lookup(app_id)
            if external is None:
                raise ValueError(f"unknown app: {app_id}")
            log_root = external
        stream = streams or Stream.COMBINED
        fname = {
            Stream.STDOUT: "stdout.log",
            Stream.STDERR: "stderr.log",
            Stream.COMBINED: "combined.log",
        }[stream]
        log_file = os.path.join(log_root, role_name, str(k), fname)
        it: Iterable[str] = LogIterator(self, app_id, log_file, should_tail)
        # combined.log lines are epoch-stamped by the Tee: apply the window
        # and strip the stamps. stdout/stderr are the raw process FDs — no
        # stamps, so windows cannot apply there; say so instead of silently
        # returning the full log.
        if stream is Stream.COMBINED:
            it = window_stamped_lines(it, since, until)
        elif since or until:
            logger.warning(
                "since/until only apply to the local combined stream"
                " (stdout/stderr are raw process files with no line"
                " timestamps); showing the full %s log",
                stream.value,
            )
        if regex:
            it = filter_regex(regex, it)
        return it

    def close(self) -> None:
        for app in self._apps.values():
            if not is_terminal(app.state):
                app.kill()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def _rotate_attempt_logs(rp: ReplicaParam, attempt: int) -> None:
    """Move the previous attempt's log files aside (``stdout.log`` ->
    ``stdout.log.<attempt-1>``) so log paths stay stable for ``log_iter``
    while history is preserved."""
    error_file = os.path.join(os.path.dirname(rp.stdout), "error.json")
    for path in (rp.stdout, rp.stderr, rp.combined, error_file):
        if os.path.exists(path):
            try:
                os.replace(path, f"{path}.{attempt - 1}")
            except OSError:
                pass


class LogIterator:
    """File-follow log iterator with app-finished detection (reference
    LogIterator, local_scheduler.py:1130-1196)."""

    def __init__(
        self,
        scheduler: LocalScheduler,
        app_id: str,
        log_file: str,
        should_tail: bool,
        poll_interval: float = 0.1,
    ) -> None:
        self._scheduler = scheduler
        self._app_id = app_id
        self._log_file = log_file
        self._should_tail = should_tail
        self._poll = poll_interval
        self._fp: Optional[TextIO] = None
        self._app_finished = False

    def _check_finished(self) -> None:
        resp = self._scheduler.describe(self._app_id)
        self._app_finished = (
            resp is None
            or is_terminal(resp.state)
            or resp.state == AppState.UNKNOWN  # owner process gone
        )

    def __iter__(self):
        # wait for the file to exist (app may still be starting)
        while not os.path.isfile(self._log_file):
            self._check_finished()
            if self._app_finished and not os.path.isfile(self._log_file):
                return
            time.sleep(self._poll)
        with open(self._log_file, errors="replace") as fp:
            while True:
                line = fp.readline()
                if line:
                    if line.endswith("\n"):
                        yield line[:-1]
                    else:
                        yield line
                    continue
                if self._app_finished or not self._should_tail:
                    # one final drain already happened (readline returned '')
                    return
                self._check_finished()
                time.sleep(self._poll)


def _pretty_request(req: PopenRequest) -> str:
    lines = [f"app_id: {req.app_id}", f"log_dir: {req.log_dir}", "roles:"]
    for role, replicas in req.role_params.items():
        lines.append(f"  {role}:")
        for i, rp in enumerate(replicas):
            lines.append(f"    [{i}] cmd: {' '.join(rp.args)}")
    return "\n".join(lines)


def create_scheduler(session_name: str, **kwargs: Any) -> LocalScheduler:
    known = {"image_provider", "cache_size", "extra_paths"}
    return LocalScheduler(
        session_name=session_name,
        **{k: v for k, v in kwargs.items() if k in known},
    )
