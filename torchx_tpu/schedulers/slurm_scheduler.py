"""Slurm scheduler: AppDef -> one heterogeneous sbatch job.

Reference analog: torchx/schedulers/slurm_scheduler.py (931 LoC). Kept
design: every replica is a hetjob group materialized into a bash script
with ``#SBATCH hetjob`` separators and a single ``srun`` with
``:``-separated groups (reference :285-330); the coordinator host is
derived from het-group-0's nodelist (reference rank0 via
``SLURM_JOB_NODELIST_HET_GROUP_0``, :538); retries requeue the job while
``TPX_MAX_RETRIES > SLURM_RESTART_COUNT`` (reference :313-327); describe
goes through ``squeue --json`` falling back to ``sacct --parsable2``
(reference :572-810); per-replica logs land in
``slurm-{jobid}-{role}-{replica}.{out,err}`` with a job-dir registry file
(reference :52,913-931).

TPU twist: a role with a TpuSlice expands to one het group per TPU-VM host
(``tpu_hosts_for_role``), and each group exports the gang identity env the
SPMD bootstrap consumes — Slurm on TPU-VM pools is plain multi-node
CPU scheduling; the chips ride along with the nodes.

All subprocess calls go through ``self._run_cmd`` so tests inject canned
squeue/sacct/sbatch output (reference test strategy:
slurm-squeue-output.json fixtures).
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import subprocess
import tempfile
from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu import settings
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    dquote as _dquote,
    DescribeAppResponse,
    EPOCH_STAMPER,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    tpu_hosts_for_role,
    window_stamped_lines,
)
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    ReplicaStatus,
    Role,
    RoleStatus,
    macros,
    runopts,
)
from torchx_tpu.workspace.dir_workspace import DirWorkspaceMixin

logger = logging.getLogger(__name__)

SLURM_JOB_DIRS_FILE = ".tpxslurmjobdirs"

SLURM_STATE_MAP: dict[str, AppState] = {
    "PENDING": AppState.PENDING,
    "CONFIGURING": AppState.PENDING,
    "REQUEUED": AppState.PENDING,
    "REQUEUE_FED": AppState.PENDING,
    "REQUEUE_HOLD": AppState.PENDING,
    "SUSPENDED": AppState.PENDING,
    "RUNNING": AppState.RUNNING,
    "COMPLETING": AppState.RUNNING,
    "RESIZING": AppState.RUNNING,
    "SIGNALING": AppState.RUNNING,
    "STAGE_OUT": AppState.RUNNING,
    "COMPLETED": AppState.SUCCEEDED,
    "FAILED": AppState.FAILED,
    "BOOT_FAIL": AppState.FAILED,
    "DEADLINE": AppState.FAILED,
    "NODE_FAIL": AppState.FAILED,
    "OUT_OF_MEMORY": AppState.FAILED,
    "TIMEOUT": AppState.FAILED,
    "PREEMPTED": AppState.FAILED,
    "CANCELLED": AppState.CANCELLED,
    "REVOKED": AppState.CANCELLED,
}


def slurm_state(state_str: str) -> AppState:
    # sacct can report "CANCELLED by 12345"; pending rows can be blank
    parts = state_str.split()
    if not parts:
        return AppState.UNKNOWN
    return SLURM_STATE_MAP.get(parts[0].rstrip("+"), AppState.UNKNOWN)


@dataclass
class SlurmReplicaRequest:
    """One hetjob group == one replica (reference :178-271)."""

    name: str  # {role}-{replica}
    sbatch_opts: list[str]
    srun_opts: list[str]
    env: dict[str, str]
    cmd: list[str]


@dataclass
class SlurmBatchRequest:
    cmd: list[str]  # sbatch argv (script path appended at schedule time)
    replicas: list[SlurmReplicaRequest]
    job_dir: Optional[str]
    max_retries: int = 0
    # (min_hosts, max_hosts) for an elastic single-role gang: materialized
    # as one RANGED group (``--nodes=min-max``) instead of het groups, so
    # slurm itself may start — or requeue — the job with any surviving node
    # count in range (the slurm-native analog of torchrun --nnodes min:max)
    elastic_range: Optional[tuple[int, int]] = None
    # hosts per AppDef unit (slice) — the srun step rounds the allocation
    # down to a whole-slice multiple, and TPX_MIN_REPLICAS stays in AppDef
    # units (matching the GKE backend's injection)
    elastic_hosts_per_unit: int = 1

    def script(self) -> str:
        return materialize_script(self)

    def __str__(self) -> str:
        return " ".join(self.cmd) + " <<script>>\n" + self.script()


def _role_to_replicas(
    role: Role, cfg: Mapping[str, CfgVal]
) -> list[SlurmReplicaRequest]:
    out = []
    num = tpu_hosts_for_role(role)
    partition = cfg.get("partition")
    for replica_id in range(num):
        values = macros.Values(
            img_root=role.image,
            app_id="${SLURM_JOB_ID}",
            replica_id=str(replica_id),
            num_replicas=str(num),
            coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
        )
        rrole = values.apply(role)
        # per-group job name: describe() parses {role}-{replica} back out of
        # squeue/sacct JobName (reference slurm_scheduler.py:260)
        sbatch_opts = [
            f"--job-name={role.name}-{replica_id}",
            "--nodes=1",
            "--ntasks-per-node=1",
        ]
        if partition:
            sbatch_opts.append(f"--partition={shlex.quote(str(partition))}")
        if rrole.resource.cpu > 0:
            sbatch_opts.append(f"--cpus-per-task={int(rrole.resource.cpu)}")
        if rrole.resource.memMB > 0 and not cfg.get("nomem"):
            sbatch_opts.append(f"--mem={int(rrole.resource.memMB)}")
        if cfg.get("time"):
            sbatch_opts.append(f"--time={cfg['time']}")
        for cap, val in rrole.resource.capabilities.items():
            if cap == "slurm.constraint":
                sbatch_opts.append(f"--constraint={val}")
        env = dict(rrole.env)
        env[settings.ENV_TPX_REPLICA_ID] = str(replica_id)
        env[settings.ENV_TPX_ROLE_NAME] = role.name
        env[settings.ENV_TPX_NUM_REPLICAS] = str(num)
        if rrole.resource.tpu is not None:
            env["TPX_TPU_ACCELERATOR_TYPE"] = rrole.resource.tpu.accelerator_type
        out.append(
            SlurmReplicaRequest(
                name=f"{role.name}-{replica_id}",
                sbatch_opts=sbatch_opts,
                srun_opts=["--kill-on-bad-exit=1", "--wait=60"],
                env=env,
                cmd=[rrole.entrypoint, *rrole.args],
            )
        )
    return out


def _elastic_replica(role: Role, cfg: Mapping[str, CfgVal]) -> SlurmReplicaRequest:
    """Template for the single RANGED group of an elastic gang.

    Identity env cannot be baked per-replica (the started size is only
    known at run time), so the macros defer to ``TPX_REPLICA_ID`` /
    ``TPX_NUM_REPLICAS``, which the per-task wrapper derives from
    ``SLURM_PROCID`` / ``SLURM_NTASKS`` (see :func:`materialize_script`).
    """
    values = macros.Values(
        img_root=role.image,
        app_id="${SLURM_JOB_ID}",
        replica_id="${TPX_REPLICA_ID}",
        num_replicas="${TPX_NUM_REPLICAS}",
        coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
    )
    rrole = values.apply(role)
    partition = cfg.get("partition")
    sbatch_opts = [
        f"--job-name={role.name}-0",  # describe() parses {role}-{replica}
        "--ntasks-per-node=1",
    ]
    if partition:
        sbatch_opts.append(f"--partition={shlex.quote(str(partition))}")
    if rrole.resource.cpu > 0:
        sbatch_opts.append(f"--cpus-per-task={int(rrole.resource.cpu)}")
    if rrole.resource.memMB > 0 and not cfg.get("nomem"):
        sbatch_opts.append(f"--mem={int(rrole.resource.memMB)}")
    if cfg.get("time"):
        sbatch_opts.append(f"--time={cfg['time']}")
    for cap, val in rrole.resource.capabilities.items():
        if cap == "slurm.constraint":
            sbatch_opts.append(f"--constraint={val}")
    env = dict(rrole.env)
    env[settings.ENV_TPX_ROLE_NAME] = role.name
    if rrole.resource.tpu is not None:
        env["TPX_TPU_ACCELERATOR_TYPE"] = rrole.resource.tpu.accelerator_type
    return SlurmReplicaRequest(
        name=role.name,
        sbatch_opts=sbatch_opts,
        srun_opts=["--kill-on-bad-exit=1", "--wait=60"],
        env=env,
        cmd=[rrole.entrypoint, *rrole.args],
    )


# Task-side stamping wrapper for het groups: a FIXED single-quoted
# ``bash -c`` body (no per-group escaping) whose positional params are the
# already-batch-shell-expanded command argv. Lines gain an epoch-millis
# prefix (shared ``EPOCH_STAMPER``) which ``log_iter`` strips on read and
# uses for since/until windows — the slurm analog of the tpu_vm remote
# wrapper and kubelet's ``timestamps=true``.
# pipelines, not process substitutions: bash waits for every pipeline
# member before exiting, so the stampers are fully drained when slurmstepd
# reaps the task (procsubs are NOT waited on — a crash's final traceback
# lines would race the reaper and vanish from the .err file); pipefail
# propagates the command's exit status through both pipelines
_STAMP_WRAPPER = (
    "'set -o pipefail;"
    " { (\"$@\") 2>&1 1>&3 | python3 -u -c \"$TPX_STAMP\" >&2; } 3>&1"
    " | python3 -u -c \"$TPX_STAMP\"' tpx"
)


def materialize_script(req: SlurmBatchRequest) -> str:
    """The full sbatch script: SBATCH headers (hetjob groups, or one ranged
    group for an elastic gang), coordinator export, requeue-on-failure
    logic, and the single srun line."""
    if req.elastic_range is not None:
        return _materialize_elastic_script(req)
    lines = ["#!/bin/bash"]
    for i, rep in enumerate(req.replicas):
        if i > 0:
            lines.append("#SBATCH hetjob")
        lines.extend(f"#SBATCH {opt}" for opt in rep.sbatch_opts)
    lines += [
        "",
        "set -e",
        "# coordinator = first node of het group 0 (role-0/replica-0)",
        'export TPX_COORDINATOR_HOST=$(scontrol show hostnames'
        ' "${SLURM_JOB_NODELIST_HET_GROUP_0:-$SLURM_JOB_NODELIST}" | head -n 1)',
        f"export TPX_APP_ID=tpx-${{SLURM_JOB_ID}}",
        f"export TPX_STAMP={shlex.quote(EPOCH_STAMPER)}",
        "",
    ]
    if req.max_retries > 0:
        lines += [
            f"export TPX_MAX_RETRIES={req.max_retries}",
            "tpx_requeue() {",
            '  if [ "${SLURM_RESTART_COUNT:-0}" -lt "$TPX_MAX_RETRIES" ]; then',
            '    scontrol requeue "$SLURM_JOB_ID"',
            "  fi",
            "}",
            "trap tpx_requeue ERR",
            "",
        ]
    srun_groups = []
    for i, rep in enumerate(req.replicas):
        # _dquote (not shlex single-quotes) so runtime macros like
        # ${SLURM_JOB_ID} and $TPX_COORDINATOR_HOST still expand; and
        # ${SLURM_JOB_ID} (the het-leader id, uniform across groups) in the
        # log file names rather than %j (which expands to each het
        # component's own id, breaking log_iter lookup for groups > 0)
        env_prefix = " ".join(
            f"{k}={_dquote(v)}" for k, v in sorted(rep.env.items())
        )
        group = " ".join(
            [
                f"--het-group={i}" if len(req.replicas) > 1 else "",
                *rep.srun_opts,
                f"--output=slurm-${{SLURM_JOB_ID}}-{rep.name}.out",
                f"--error=slurm-${{SLURM_JOB_ID}}-{rep.name}.err",
                ("env " + env_prefix) if env_prefix else "env",
                "bash -c " + _STAMP_WRAPPER,
                " ".join(_dquote(c) for c in rep.cmd),
            ]
        ).strip()
        srun_groups.append(group)
    lines.append("srun " + " : ".join(srun_groups))
    lines.append("")
    return "\n".join(lines)


def _materialize_elastic_script(req: SlurmBatchRequest) -> str:
    """Elastic gang: ONE ranged group (``--nodes=min-max``) instead of het
    groups — slurm may start the job with any node count in range, and a
    ``scontrol requeue`` after a node failure restarts it with whatever
    survives (still >= min), which is the slurm-native shrink-and-restart:
    the analog of the local scheduler's elastic restart and torchrun's
    ``--nnodes min:max`` rendezvous. Each task derives its identity from
    ``SLURM_PROCID``/``SLURM_NTASKS`` at run time, so the restarted world
    re-forms coherently at the new size and user code resumes from its
    checkpoint."""
    assert req.elastic_range is not None
    min_hosts, max_hosts = req.elastic_range
    hpu = max(1, req.elastic_hosts_per_unit)
    # TPX_MIN_REPLICAS is in AppDef units (slices for TPU roles) to match
    # the GKE backend's injection — in-job bootstrap logic shares it
    min_units = max(1, min_hosts // hpu)
    (rep,) = req.replicas
    lines = ["#!/bin/bash"]
    lines.append(f"#SBATCH --nodes={min_hosts}-{max_hosts}")
    lines.extend(f"#SBATCH {opt}" for opt in rep.sbatch_opts)
    lines += [
        "",
        "set -e",
        'export TPX_COORDINATOR_HOST=$(scontrol show hostnames'
        ' "$SLURM_JOB_NODELIST" | head -n 1)',
        f"export TPX_APP_ID=tpx-${{SLURM_JOB_ID}}",
        f"export TPX_STAMP={shlex.quote(EPOCH_STAMPER)}",
        f"export {settings.ENV_TPX_MIN_REPLICAS}={min_units}",
        f"export TPX_HOSTS_PER_UNIT={hpu}",
        "# slurm may start/requeue the ranged job with any node count in",
        "# range; a TPU gang only works in whole-slice multiples, so the",
        "# srun step is clamped to the largest usable multiple and spare",
        "# hosts idle until the next requeue",
        f'TPX_USABLE_NODES=$(( SLURM_JOB_NUM_NODES / {hpu} * {hpu} ))',
        f'if [ "$TPX_USABLE_NODES" -lt {min_units * hpu} ]; then',
        f'  echo "tpx: $SLURM_JOB_NUM_NODES nodes < {min_units * hpu} usable minimum" >&2',
        "  exit 1",
        "fi",
        "",
    ]
    if req.max_retries > 0:
        lines += [
            f"export TPX_MAX_RETRIES={req.max_retries}",
            "tpx_requeue() {",
            '  if [ "${SLURM_RESTART_COUNT:-0}" -lt "$TPX_MAX_RETRIES" ]; then',
            "    # ranged --nodes: the requeued job may restart smaller",
            '    scontrol requeue "$SLURM_JOB_ID"',
            "  fi",
            "}",
            "trap tpx_requeue ERR",
            "",
        ]
    env_prefix = " ".join(
        f"{k}={_dquote(v)}" for k, v in sorted(rep.env.items())
    )
    # the wrapper runs ON each task node (bash -c under srun), where
    # SLURM_PROCID/SLURM_NTASKS are set; single-quoting via shlex defers
    # all expansion from the batch shell to the task shell
    # same drained-pipeline stamping as _STAMP_WRAPPER (see comment there)
    inner = (
        'export TPX_REPLICA_ID="$SLURM_PROCID"'
        ' TPX_NUM_REPLICAS="$SLURM_NTASKS"; set -o pipefail; '
        + "{ ("
        + (("env " + env_prefix + " ") if env_prefix else "")
        + " ".join(_dquote(c) for c in rep.cmd)
        + ') 2>&1 1>&3 | python3 -u -c "$TPX_STAMP" >&2; } 3>&1'
        + ' | python3 -u -c "$TPX_STAMP"'
    )
    lines.append(
        "srun "
        + " ".join(rep.srun_opts)
        # clamp the step to the whole-slice node count computed above
        + ' --nodes="$TPX_USABLE_NODES" --ntasks="$TPX_USABLE_NODES"'
        + f" --output=slurm-${{SLURM_JOB_ID}}-{rep.name}-%t.out"
        + f" --error=slurm-${{SLURM_JOB_ID}}-{rep.name}-%t.err"
        + f" bash -c {shlex.quote(inner)}"
    )
    lines.append("")
    return "\n".join(lines)


# Feature profile for the preflight analyzer (torchx_tpu.analyze): sbatch
# carries multi-role het jobs and exports a TPX_MAX_RETRIES restart budget,
# and sacct requeue records classify preemption — but there is no mount
# materialization, no delete(), and no in-place resize.
CAPABILITIES = SchedulerCapabilities(
    mounts=False,
    multi_role=True,
    multislice=False,
    delete=False,
    resize=False,
    logs=True,
    native_retries=True,
    concrete_resources=True,
    classifies_preemption=True,
    # compute nodes share the cluster network with the control daemon
    metricz_scrape=True,
)


class SlurmScheduler(DirWorkspaceMixin, Scheduler[SlurmBatchRequest]):
    """Submits AppDefs as heterogeneous sbatch jobs."""

    capabilities = CAPABILITIES
    supports_log_windows = True  # wrapper-stamped log lines (_STAMP_WRAPPER)

    def __init__(self, session_name: str) -> None:
        super().__init__(backend="slurm", session_name=session_name)
        self._mem_probe_cache: dict[str, bool] = {}

    def _run_cmd(self, cmd: list[str], **kwargs: Any) -> subprocess.CompletedProcess:
        """Single subprocess seam — tests monkeypatch this. Call sites go
        through :meth:`Scheduler._cmd` so every slurm CLI call gets the
        control-plane deadline, classified retries, and the backend
        breaker."""
        return subprocess.run(cmd, capture_output=True, text=True, **kwargs)

    def run_opts(self) -> runopts:
        opts = runopts()
        opts.add("partition", type_=str, help="slurm partition", default=None)
        opts.add(
            "time", type_=str, help="job time limit (e.g. 2:00:00)", default=None
        )
        opts.add(
            "nomem",
            type_=bool,
            help="do not pass --mem (for clusters with RealMemory"
            " misconfigured; reference analog of the partition mem probe)",
            default=False,
        )
        opts.add(
            "comment", type_=str, help="sbatch --comment metadata", default=None
        )
        return opts | self.workspace_opts()

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[SlurmBatchRequest]:
        cfg = dict(cfg)
        if not cfg.get("nomem") and not self._partition_supports_mem(
            cfg.get("partition")
        ):
            # partitions with unset RealMemory reject --mem outright
            # (reference analog: the aws slurm partition memory probe)
            logger.info(
                "partition %s reports no usable RealMemory; dropping --mem",
                cfg.get("partition") or "<default>",
            )
            cfg["nomem"] = True
        elastic_role = next(
            (r for r in app.roles if r.min_replicas is not None), None
        )
        elastic_range: Optional[tuple[int, int]] = None
        if elastic_role is not None:
            if len(app.roles) != 1:
                raise ValueError(
                    "slurm elastic gangs (min_replicas) require a"
                    " single-role app: the ranged --nodes allocation is"
                    " job-wide — split other roles into their own apps"
                )
            # min_replicas is in AppDef units (slices for TPU roles);
            # slurm nodes are hosts, and TPU gangs shrink in whole slices
            hosts_per_unit = (
                elastic_role.resource.tpu.hosts
                if elastic_role.resource is not None
                and elastic_role.resource.tpu is not None
                else 1
            )
            elastic_range = (
                max(1, elastic_role.min_replicas) * hosts_per_unit,
                tpu_hosts_for_role(elastic_role),
            )
            replicas = [_elastic_replica(elastic_role, cfg)]
        else:
            replicas = []
            for role in app.roles:
                replicas.extend(_role_to_replicas(role, cfg))
        cmd = ["sbatch", "--parsable"]
        if cfg.get("comment"):
            cmd.append(f"--comment={cfg['comment']}")
        req = SlurmBatchRequest(
            cmd=cmd,
            replicas=replicas,
            job_dir=str(cfg["job_dir"]) if cfg.get("job_dir") else None,
            max_retries=max((r.max_retries for r in app.roles), default=0),
            elastic_range=elastic_range,
            elastic_hosts_per_unit=(
                hosts_per_unit if elastic_range is not None else 1
            ),
        )
        return AppDryRunInfo(req)

    def _partition_supports_mem(self, partition: Optional[CfgVal]) -> bool:
        """Probe ``sinfo`` for the partition's configured node memory:
        RealMemory=1 (slurm's unset marker) means ``--mem`` requests can
        never be satisfied and must be dropped. Probe failures (no slurm
        on PATH, standalone dryruns) keep --mem. Cached per partition."""
        key = str(partition) if partition else ""
        if key in self._mem_probe_cache:
            return self._mem_probe_cache[key]
        cmd = ["sinfo", "--noheader", "--format=%m"]
        if partition:
            cmd += ["--partition", str(partition)]
        try:
            proc = self._cmd(cmd, op="probe")
        except (OSError, subprocess.SubprocessError):
            self._mem_probe_cache[key] = True
            return True
        if proc.returncode != 0:
            ok = True  # can't probe: keep --mem
        else:
            vals = [v.strip().rstrip("+") for v in proc.stdout.split()]
            ok = not vals or any(v.isdigit() and int(v) > 1 for v in vals)
        self._mem_probe_cache[key] = ok
        return ok

    def schedule(self, dryrun_info: AppDryRunInfo[SlurmBatchRequest]) -> str:
        req = dryrun_info.request
        job_dir = req.job_dir or tempfile.mkdtemp(prefix="tpx_slurm_")
        script_path = os.path.join(job_dir, "tpx_sbatch.sh")
        with open(script_path, "w") as f:
            f.write(req.script())
        proc = self._cmd(
            [*req.cmd, script_path],
            op="submit",
            policy=NON_IDEMPOTENT,
            cwd=job_dir,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"sbatch failed (rc={proc.returncode}):\n{proc.stderr}"
            )
        job_id = proc.stdout.strip().split(";")[0]
        _save_job_dir(job_id, job_dir)
        return job_id

    # -- monitoring --------------------------------------------------------

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        resp = self._describe_squeue(app_id)
        if resp is not None:
            return resp
        return self._describe_sacct(app_id)

    def _describe_squeue(self, app_id: str) -> Optional[DescribeAppResponse]:
        proc = self._cmd(["squeue", "--json", "-j", app_id], op="describe")
        if proc.returncode != 0:
            return None
        try:
            payload = json.loads(proc.stdout)
        except json.JSONDecodeError:
            return None
        jobs = payload.get("jobs") or []
        if not jobs:
            return None
        return _describe_from_squeue_jobs(app_id, jobs)

    def _describe_sacct(self, app_id: str) -> Optional[DescribeAppResponse]:
        proc = self._cmd(
            ["sacct", "--parsable2", "-j", app_id, "--format", "JobID,JobName,State"],
            op="describe",
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            return None
        lines = proc.stdout.strip().splitlines()
        if len(lines) < 2:
            return None
        header = lines[0].split("|")
        roles: dict[str, RoleStatus] = {}
        app_state = AppState.UNKNOWN
        for line in lines[1:]:
            row = dict(zip(header, line.split("|")))
            job_id = row.get("JobID", "")
            if "." in job_id:  # step rows
                continue
            state = slurm_state(row.get("State", ""))
            name = row.get("JobName", "")
            if job_id.split("+")[0] == app_id:
                app_state = state if app_state == AppState.UNKNOWN else app_state
                if _is_worse(state, app_state):
                    app_state = state
            role, _, rep = name.rpartition("-")
            if role and rep.isdigit():
                roles.setdefault(role, RoleStatus(role=role)).replicas.append(
                    ReplicaStatus(id=int(rep), state=state, role=role)
                )
        return DescribeAppResponse(
            app_id=app_id,
            state=app_state,
            roles_statuses=list(roles.values()),
        )

    def list(self) -> list[ListAppResponse]:
        proc = self._cmd(["squeue", "--json", "--me"], op="list")
        if proc.returncode != 0:
            raise RuntimeError(f"squeue failed: {proc.stderr}")
        payload = json.loads(proc.stdout)
        out = []
        for job in payload.get("jobs", []):
            out.append(
                ListAppResponse(
                    app_id=str(job.get("job_id")),
                    state=_squeue_job_state(job),
                    name=job.get("name", ""),
                )
            )
        return out

    def _cancel_existing(self, app_id: str) -> None:
        proc = self._cmd(["scancel", app_id], op="cancel")
        if proc.returncode != 0:
            raise RuntimeError(f"scancel failed: {proc.stderr}")

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        # the batch-script wrapper stamps every line with epoch millis
        # (``_STAMP_WRAPPER``), so since/until windows apply here the same
        # way they do on tpu_vm; stamps are stripped before yielding, and
        # pre-stamping legacy log files pass through unwindowed.
        job_dir = _load_job_dir(app_id)
        if job_dir is None:
            raise RuntimeError(
                f"no job dir recorded for {app_id} in {SLURM_JOB_DIRS_FILE}"
            )
        ext = "err" if streams == Stream.STDERR else "out"
        log_file = os.path.join(job_dir, f"slurm-{app_id}-{role_name}-{k}.{ext}")
        if not os.path.exists(log_file):
            # non-het (single-replica) jobs may write slurm-{id}.out
            fallback = os.path.join(job_dir, f"slurm-{app_id}.{ext}")
            if os.path.exists(fallback):
                log_file = fallback
        lines: Iterable[str] = window_stamped_lines(
            _read_lines(log_file), since, until
        )
        if regex:
            lines = filter_regex(regex, lines)
        return lines


def _read_lines(path: str) -> Iterable[str]:
    if not os.path.exists(path):
        return iter(())
    with open(path, errors="replace") as f:
        return iter(f.read().splitlines())


_STATE_BADNESS = {
    AppState.FAILED: 3,
    AppState.CANCELLED: 2,
    AppState.RUNNING: 1,
}


def _is_worse(a: AppState, b: AppState) -> bool:
    return _STATE_BADNESS.get(a, 0) > _STATE_BADNESS.get(b, 0)


def _squeue_job_nodes(job: Mapping[str, Any]) -> str:
    """Allocated node list across squeue --json format generations:
    pre-23.02 ``job_resources.nodes`` is a string; 24.05 made it an object
    (``{"count": .., "list": [..]}``); some builds use ``allocated_nodes``
    or omit job_resources entirely for pending jobs."""
    res = job.get("job_resources") or {}
    if not isinstance(res, Mapping):
        return ""
    nodes = res.get("nodes", res.get("allocated_nodes", ""))
    if isinstance(nodes, Mapping):
        node_list = nodes.get("list")
        if isinstance(node_list, list):
            return ",".join(str(n) for n in node_list)
        return str(nodes.get("nodes", "") or "")
    if isinstance(nodes, list):  # allocated_nodes: [{"nodename": ...}]
        return ",".join(
            str(n.get("nodename", n) if isinstance(n, Mapping) else n)
            for n in nodes
        )
    return str(nodes or "")


def _squeue_job_state(job: Mapping[str, Any]) -> AppState:
    js = job.get("job_state")
    if isinstance(js, list):
        js = js[0] if js else "UNKNOWN"
    return slurm_state(str(js))


def _describe_from_squeue_jobs(
    app_id: str, jobs: list[Mapping[str, Any]]
) -> DescribeAppResponse:
    roles: dict[str, RoleStatus] = {}
    app_state = AppState.UNKNOWN
    for job in jobs:
        state = _squeue_job_state(job)
        if app_state == AppState.UNKNOWN or _is_worse(state, app_state):
            app_state = state
        name = str(job.get("name", ""))
        role, _, rep = name.rpartition("-")
        if role and rep.isdigit():
            roles.setdefault(role, RoleStatus(role=role)).replicas.append(
                ReplicaStatus(
                    id=int(rep),
                    state=state,
                    role=role,
                    hostname=_squeue_job_nodes(job),
                )
            )
    if not roles:
        # single sbatch job (not hetjob-split): synthesize one role from name
        name = str(jobs[0].get("name", "job"))
        roles[name] = RoleStatus(
            role=name,
            replicas=[ReplicaStatus(id=0, state=app_state, role=name)],
        )
    return DescribeAppResponse(
        app_id=app_id, state=app_state, roles_statuses=list(roles.values())
    )


# =========================================================================
# Job-dir registry (reference :52,913-931)
# =========================================================================


def _registry_path() -> str:
    return os.path.join(os.path.expanduser("~"), SLURM_JOB_DIRS_FILE)


def _save_job_dir(job_id: str, job_dir: str) -> None:
    from torchx_tpu.util import registry

    registry.record(_registry_path(), job_id, job_dir, keep=os.path.isdir)


def _load_job_dir(job_id: str) -> Optional[str]:
    from torchx_tpu.util import registry

    return registry.lookup(_registry_path(), job_id)


def create_scheduler(session_name: str, **kwargs: Any) -> SlurmScheduler:
    return SlurmScheduler(session_name=session_name)
