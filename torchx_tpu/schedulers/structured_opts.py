"""StructuredOpts: dataclass sugar over runopts.

Reference analog: torchx/schedulers/api.py:79-315. Scheduler authors declare
a dataclass whose fields (with attribute docstrings) define the run config;
``to_runopts()`` generates the equivalent :class:`runopts` (docstrings become
help text, harvested from source — attribute docstrings don't exist at
runtime), and ``from_cfg`` parses a resolved cfg mapping back into a typed
instance. Nested dataclass fields flatten with dots (``k8s.context``).
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
import textwrap
import typing
from typing import Any, Mapping, Optional, TypeVar, Union

from torchx_tpu.specs.api import CfgVal, runopts

S = TypeVar("S", bound="StructuredOpts")


def _attr_docs(cls: type) -> dict[str, str]:
    """Attribute docstrings via AST: a string literal immediately following
    an annotated assignment (the convention sphinx documents)."""
    docs: dict[str, str] = {}
    try:
        src = textwrap.dedent(inspect.getsource(cls))
    except (OSError, TypeError):
        return docs
    tree = ast.parse(src)
    cls_node = tree.body[0]
    if not isinstance(cls_node, ast.ClassDef):
        return docs
    prev_name: Optional[str] = None
    for node in cls_node.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            prev_name = node.target.id
        elif (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and prev_name
        ):
            docs[prev_name] = " ".join(node.value.value.split())
            prev_name = None
        else:
            prev_name = None
    return docs


def _unwrap_optional(t: Any) -> tuple[Any, bool]:
    origin = typing.get_origin(t)
    if origin is Union or origin is getattr(__import__("types"), "UnionType", None):
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
    return t, False


def _base_type(t: Any) -> type:
    t, _ = _unwrap_optional(t)
    origin = typing.get_origin(t)
    if origin is not None:
        return origin if origin in (list, dict) else origin
    return t if isinstance(t, type) else str


@dataclasses.dataclass
class StructuredOpts:
    """Base class for typed scheduler run configs."""

    @classmethod
    def to_runopts(cls) -> runopts:
        opts = runopts()
        docs = _attr_docs(cls)
        hints = typing.get_type_hints(cls)
        for f in dataclasses.fields(cls):
            if not f.init:
                continue
            ftype = hints.get(f.name, f.type)
            inner, _ = _unwrap_optional(ftype)
            if dataclasses.is_dataclass(inner) and issubclass(inner, StructuredOpts):
                # nested group: flatten as group.key
                for key, opt in inner.to_runopts():
                    opts.add(
                        f"{f.name}.{key}",
                        type_=opt.opt_type,
                        help=opt.help,
                        default=opt.default,
                        required=opt.is_required,
                    )
                continue
            default: CfgVal
            required = False
            if f.default is not dataclasses.MISSING:
                default = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                default = f.default_factory()  # type: ignore[misc]
            else:
                default = None
                required = True
            opts.add(
                f.name,
                type_=_base_type(ftype),
                help=docs.get(f.name, f.name),
                default=default if not required else None,
                required=required,
            )
        return opts

    @classmethod
    def from_cfg(cls: type[S], cfg: Mapping[str, CfgVal]) -> S:
        """Build a typed instance from a resolved cfg mapping (unknown keys
        ignored; nested groups gathered from dotted keys)."""
        hints = typing.get_type_hints(cls)
        kwargs: dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            if not f.init:
                continue
            ftype = hints.get(f.name, f.type)
            inner, _ = _unwrap_optional(ftype)
            if dataclasses.is_dataclass(inner) and issubclass(inner, StructuredOpts):
                prefix = f.name + "."
                sub = {
                    k[len(prefix) :]: v for k, v in cfg.items() if k.startswith(prefix)
                }
                kwargs[f.name] = inner.from_cfg(sub)
                continue
            if f.name in cfg and cfg[f.name] is not None:
                kwargs[f.name] = cfg[f.name]
        return cls(**kwargs)

    # Mapping-ish access for backward compat with dict-style cfg handling
    def __getitem__(self, key: str) -> Any:
        try:
            return getattr(self, key)
        except AttributeError:
            raise KeyError(key) from None

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)
