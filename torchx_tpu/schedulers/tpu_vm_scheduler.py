"""Cloud TPU VM scheduler: drive slices directly through the gcloud CLI.

The reference ships cloud-CLI/SDK backends for its native clouds (AWS Batch
at aws_batch_scheduler.py:854, SageMaker at aws_sagemaker_scheduler.py:696).
The TPU equivalent is Cloud TPU's own control plane: **queued resources** —
``gcloud compute tpus queued-resources`` — which allocate whole slices
(optionally spot) without any Kubernetes layer, and per-host command
execution over ``gcloud compute tpus tpu-vm ssh --worker=all``.

Mapping:

* role.resource.tpu -> ``--accelerator-type`` (+ ``--runtime-version``);
* submit = create a queued resource with a startup script that exports the
  gang env (TPX_REPLICA_ID from the TPU worker id, coordinator = worker 0)
  and runs the role's entrypoint on every host;
* describe = queued-resource state (WAITING/PROVISIONING/ACTIVE/FAILED...)
  mapped onto AppState;
* cancel/delete = queued-resource delete (slices are all-or-nothing);
* logs = ``gcloud ... ssh --worker=N --command='tail ...'`` on the remote
  log file the startup script tees into.

Single-role apps only — a queued resource is one slice; use the GKE
backend for multi-role apps. All gcloud calls go through ``self._run_cmd``
so tests inject canned JSON (reference test strategy).
"""

from __future__ import annotations

import json
import logging
import re
import shlex
import subprocess
from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu import settings
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    dquote as _dquote,
    DescribeAppResponse,
    EPOCH_STAMPER,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    parse_epoch_stamp,
)
from torchx_tpu.schedulers.ids import make_unique
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    FailureClass,
    ReplicaStatus,
    RoleStatus,
    macros,
    runopts,
)

logger = logging.getLogger(__name__)

REMOTE_LOG_DIR = "/tmp/tpx"
REMOTE_STDOUT = f"{REMOTE_LOG_DIR}/stdout.log"
REMOTE_STDERR = f"{REMOTE_LOG_DIR}/stderr.log"
# legacy combined path (pre-timestamped-stream layout); still read as a
# fallback so logs of jobs launched by older launchers stay reachable
REMOTE_LOG = f"{REMOTE_LOG_DIR}/job.log"

# each log line is prefixed "<epoch.millis> " by the stamper below, which
# is what makes since/until filtering and combined-stream merging possible
# without a cloud logging dependency
_STAMPER = EPOCH_STAMPER  # shared with the slurm batch-script wrapper

QR_STATE_MAP: dict[str, AppState] = {
    "CREATING": AppState.PENDING,
    "ACCEPTED": AppState.PENDING,
    "WAITING_FOR_RESOURCES": AppState.PENDING,
    "PROVISIONING": AppState.PENDING,
    "ACTIVE": AppState.RUNNING,
    "SUSPENDING": AppState.RUNNING,
    "SUSPENDED": AppState.PENDING,
    "DELETING": AppState.CANCELLED,
    "FAILED": AppState.FAILED,
}

# default TPU VM runtime image per generation
RUNTIME_VERSIONS = {
    "v4": "tpu-ubuntu2204-base",
    "v5e": "v2-alpha-tpuv5-lite",
    "v5p": "v2-alpha-tpuv5",
    "v6e": "v2-alpha-tpuv6e",
}


@dataclass
class TpuVmRequest:
    """Materialized gcloud queued-resource create invocation."""

    name: str
    zone: str
    project: Optional[str]
    accelerator_type: str
    runtime_version: str
    startup_script: str
    spot: bool = False
    reserved: bool = False

    def create_cmd(self) -> list[str]:
        cmd = [
            "gcloud",
            "compute",
            "tpus",
            "queued-resources",
            "create",
            self.name,
            f"--zone={self.zone}",
            f"--accelerator-type={self.accelerator_type}",
            f"--runtime-version={self.runtime_version}",
            f"--node-id={self.name}",
            "--metadata",
            f"startup-script={self.startup_script}",
            "--format=json",
        ]
        if self.project:
            cmd.insert(5, f"--project={self.project}")
        if self.spot:
            cmd.append("--spot")
        if self.reserved:
            cmd.append("--reserved")
        return cmd

    def __str__(self) -> str:
        return " ".join(
            shlex.quote(c) if "startup-script" not in c else "'startup-script=...'"
            for c in self.create_cmd()
        ) + f"\n--- startup script ---\n{self.startup_script}"


def make_startup_script(role, app_id: str, num_hosts: int) -> str:  # noqa: ANN001
    """Per-host boot script: export gang env (worker id -> replica id,
    worker-0 hostname -> coordinator), run the entrypoint, tee logs."""
    env_exports = "\n".join(
        f"export {k}={_dquote(v)}" for k, v in sorted(role.env.items())
    )
    cmd = " ".join(_dquote(c) for c in [role.entrypoint, *role.args])
    return f"""#!/bin/bash
mkdir -p /tmp/tpx
# gang identity from the TPU VM metadata server (agent-worker-number) and
# worker 0's hostname as coordinator
WORKER_ID=$(curl -s -H 'Metadata-Flavor: Google' \
  'http://metadata.google.internal/computeMetadata/v1/instance/attributes/agent-worker-number' || echo 0)
export {settings.ENV_TPX_REPLICA_ID}=$WORKER_ID
export {settings.ENV_TPX_NUM_REPLICAS}={num_hosts}
export {settings.ENV_TPX_COORDINATOR_HOST}=$(getent hosts {shlex.quote(app_id)}-0 | awk '{{print $1}}' || hostname -i)
export {settings.ENV_TPX_APP_ID}={shlex.quote(app_id)}
export {settings.ENV_TPX_ROLE_NAME}={shlex.quote(role.name)}
export {settings.ENV_TPX_ERROR_FILE}=/tmp/tpx/error.json
{env_exports}
STAMP={shlex.quote(_STAMPER)}
({cmd}) \
  > >(python3 -u -c "$STAMP" >> {REMOTE_STDOUT}) \
  2> >(python3 -u -c "$STAMP" >> {REMOTE_STDERR})
echo $? > /tmp/tpx/exitcode
"""


# Feature profile for the preflight analyzer (torchx_tpu.analyze): queued
# resources are exactly one TPU role per job — no mounts, no multi-slice,
# no native retries (resubmission is the supervisor's job, and spot
# reclamation is classified from the QR state for it).
CAPABILITIES = SchedulerCapabilities(
    mounts=False,
    multi_role=False,
    requires_tpu=True,
    multislice=False,
    delete=True,
    resize=False,
    logs=True,
    native_retries=False,
    concrete_resources=False,
    classifies_preemption=True,
)


class TpuVmScheduler(Scheduler[TpuVmRequest]):
    capabilities = CAPABILITIES
    supports_log_windows = True  # stamped remote log lines
    def __init__(self, session_name: str) -> None:
        super().__init__("tpu_vm", session_name)

    def _run_cmd(self, cmd: list[str], **kwargs: Any) -> subprocess.CompletedProcess:
        """Raw gcloud seam (monkeypatched in tests); production calls go
        through :meth:`Scheduler._cmd` for deadlines/retries/breakers."""
        return subprocess.run(cmd, capture_output=True, text=True, **kwargs)

    def run_opts(self) -> runopts:
        opts = runopts()
        opts.add("zone", type_=str, help="GCE zone, e.g. us-east5-a", required=True)
        opts.add("project", type_=str, help="GCP project", default=None)
        opts.add(
            "runtime_version",
            type_=str,
            help="TPU VM runtime version (default per generation)",
            default=None,
        )
        opts.add("spot", type_=bool, help="use spot (preemptible) capacity", default=False)
        opts.add(
            "reserved", type_=bool, help="use reserved capacity", default=False
        )
        return opts

    def _validate(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> None:
        if len(app.roles) != 1:
            raise ValueError(
                "tpu_vm schedules exactly one role per app (one queued"
                " resource == one slice); use the gke scheduler for"
                " multi-role apps"
            )
        if app.roles[0].resource.tpu is None:
            raise ValueError("tpu_vm requires a TPU resource on the role")

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[TpuVmRequest]:
        self._validate(app, cfg)
        role = app.roles[0]
        tpu = role.resource.tpu
        assert tpu is not None
        app_id = make_unique(app.name)
        values = macros.Values(
            img_root="",
            app_id=app_id,
            replica_id="$WORKER_ID",  # resolved per host by the startup script
            num_replicas=str(tpu.hosts),
            coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
        )
        srole = values.apply(role)
        req = TpuVmRequest(
            name=app_id,
            zone=str(cfg["zone"]),
            project=cfg.get("project"),  # type: ignore[arg-type]
            accelerator_type=tpu.accelerator_type,
            runtime_version=str(
                cfg.get("runtime_version")
                or RUNTIME_VERSIONS.get(tpu.accelerator, "tpu-ubuntu2204-base")
            ),
            startup_script=make_startup_script(srole, app_id, tpu.hosts),
            spot=bool(cfg.get("spot")),
            reserved=bool(cfg.get("reserved")),
        )
        return AppDryRunInfo(req)

    def schedule(self, dryrun_info: AppDryRunInfo[TpuVmRequest]) -> str:
        req = dryrun_info.request
        proc = self._cmd(req.create_cmd(), op="submit", policy=NON_IDEMPOTENT)
        if proc.returncode != 0:
            raise RuntimeError(
                f"queued-resource create failed (rc={proc.returncode}):"
                f"\n{proc.stderr}"
            )
        return f"{req.zone}:{req.name}"

    @staticmethod
    def _parse_app_id(app_id: str) -> tuple[str, str]:
        zone, _, name = app_id.partition(":")
        if not name:
            raise ValueError(f"invalid tpu_vm app id {app_id!r}; expected zone:name")
        return zone, name

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        zone, name = self._parse_app_id(app_id)
        proc = self._cmd(
            [
                "gcloud",
                "compute",
                "tpus",
                "queued-resources",
                "describe",
                name,
                f"--zone={zone}",
                "--format=json",
            ],
            op="describe",
        )
        if proc.returncode != 0:
            return None
        try:
            data = json.loads(proc.stdout)
        except json.JSONDecodeError:
            return None
        return describe_queued_resource(app_id, data)

    def list(self) -> list[ListAppResponse]:
        proc = self._cmd(
            ["gcloud", "compute", "tpus", "queued-resources", "list", "--format=json"],
            op="list",
        )
        if proc.returncode != 0:
            raise RuntimeError(f"queued-resources list failed: {proc.stderr}")
        out = []
        for item in json.loads(proc.stdout or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            zone = "-".join(
                item.get("name", "").split("/locations/")[-1].split("/")[0:1]
            )
            state = (item.get("state") or {}).get("state", "")
            out.append(
                ListAppResponse(
                    app_id=f"{zone}:{name}",
                    state=QR_STATE_MAP.get(state, AppState.UNKNOWN),
                    name=name,
                )
            )
        return out

    def _cancel_existing(self, app_id: str) -> None:
        zone, name = self._parse_app_id(app_id)
        proc = self._cmd(
            [
                "gcloud",
                "compute",
                "tpus",
                "queued-resources",
                "delete",
                name,
                f"--zone={zone}",
                "--force",
                "--quiet",
            ],
            op="cancel",
        )
        if proc.returncode != 0:
            raise RuntimeError(f"queued-resource delete failed: {proc.stderr}")

    def delete(self, app_id: str) -> None:
        self._cancel_existing(app_id)

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Worker logs over ssh, properly: per-stream files with epoch
        prefixes enable since/until windows and a merged COMBINED view,
        and tailing advances a byte offset per file across repeated ssh
        invocations instead of re-fetching the whole log each poll — this
        is what survives a multi-hour job."""
        stream = streams or Stream.COMBINED
        files = {
            Stream.STDOUT: [REMOTE_STDOUT],
            Stream.STDERR: [REMOTE_STDERR],
            Stream.COMBINED: [REMOTE_STDOUT, REMOTE_STDERR, REMOTE_LOG],
        }[stream]
        it: Iterable[str] = _RemoteLogIterator(
            self, app_id, k, files, since, until, should_tail
        )
        if regex:
            it = filter_regex(regex, it)
        return it

    def _fetch_log_windows(
        self, app_id: str, worker: int, offsets: Mapping[str, int]
    ) -> tuple[dict[str, str], Optional[str]]:
        """ONE ssh round-trip for all files: -> ({path: new bytes},
        exitcode-or-None). Byte-exact framing ("<path> <nbytes>" header
        lines followed by exactly nbytes of payload) makes the protocol
        immune to log-content collisions; missing files read as empty
        (workers boot at different times). The exitcode file is the
        authoritative job-finished signal — the queued resource itself
        stays ACTIVE after the startup script exits."""
        zone, name = self._parse_app_id(app_id)
        spec = ";".join(f"{p}:{o}" for p, o in offsets.items())
        remote = (
            "import os,sys\n"
            f"spec={spec!r}\n"
            "out=sys.stdout\n"
            "for item in spec.split(';'):\n"
            "    p,_,off=item.rpartition(':')\n"
            "    try:\n"
            "        f=open(p,'rb'); f.seek(int(off)-1); data=f.read(); f.close()\n"
            "    except OSError: data=b''\n"
            "    out.write(f'{p} {len(data)}\\n'); out.flush()\n"
            "    out.buffer.write(data); out.buffer.flush()\n"
            "ec=''\n"
            "try: ec=open('/tmp/tpx/exitcode').read().strip()\n"
            "except OSError: pass\n"
            "out.write(f'__exitcode__ {ec}\\n')\n"
        )
        proc = self._cmd(
            [
                "gcloud",
                "compute",
                "tpus",
                "tpu-vm",
                "ssh",
                name,
                f"--zone={zone}",
                f"--worker={worker}",
                "--command",
                f"python3 -c {shlex.quote(remote)}",
            ],
            op="logs",
        )
        if proc.returncode != 0:
            raise RuntimeError(f"log fetch failed: {proc.stderr}")
        return _parse_log_frames(proc.stdout, list(offsets))


# stamp parsing is shared with the local Tee (same wire format)
_parse_stamp = parse_epoch_stamp


def _parse_log_frames(
    raw: str, paths: list[str]
) -> tuple[dict[str, str], Optional[str]]:
    """Decode the byte-framed multi-file payload from the remote reader."""
    data = raw.encode()
    chunks: dict[str, str] = {}
    exitcode: Optional[str] = None
    pos = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        if nl < 0:
            break
        header = data[pos:nl].decode(errors="replace")
        pos = nl + 1
        name, _, arg = header.rpartition(" ")
        if name == "__exitcode__":
            exitcode = arg or None
            continue
        if name in paths and arg.isdigit():
            n = int(arg)
            if n > 0:
                chunks[name] = data[pos : pos + n].decode(errors="replace")
            pos += n
        # anything else (ssh banners/warnings) is skipped line-by-line
    return chunks, exitcode


class _RemoteLogIterator:
    """Merged, windowed, incrementally-tailed view of remote log files.

    Tracks a byte offset and a partial-line buffer per file; each poll
    fetches only NEW bytes (one ssh per file), merges complete lines by
    their epoch stamp, applies the since/until window, and strips the
    stamp before yielding. Tailing stops after one final drain once the
    queued resource reaches a terminal state.
    """

    def __init__(
        self,
        scheduler: "TpuVmScheduler",
        app_id: str,
        worker: int,
        files: list[str],
        since: Optional[float],
        until: Optional[float],
        should_tail: bool,
        poll_interval: float = 10.0,
    ) -> None:
        self._sched = scheduler
        self._app_id = app_id
        self._worker = worker
        self._files = files
        self._since = since
        self._until = until
        self._should_tail = should_tail
        self._poll = poll_interval
        self._offsets = {f: 1 for f in files}  # seek offsets are 1-based
        self._buffers = {f: "" for f in files}
        self._exited = False  # remote exitcode file observed
        self._describe_failures = 0

    def _poll_once(self) -> list[tuple[Optional[float], str]]:
        """ONE ssh round-trip for every file + the exitcode sentinel."""
        chunks, exitcode = self._sched._fetch_log_windows(
            self._app_id, self._worker, dict(self._offsets)
        )
        if exitcode is not None:
            self._exited = True
        out: list[tuple[Optional[float], str]] = []
        for f in self._files:
            chunk = chunks.get(f, "")
            if not chunk:
                continue
            self._offsets[f] += len(chunk.encode())
            data = self._buffers[f] + chunk
            lines = data.split("\n")
            self._buffers[f] = lines.pop()  # possibly-partial tail
            out.extend(_parse_stamp(ln) for ln in lines)
        # merge streams chronologically; unstamped lines sort first, which
        # keeps legacy logs in file order
        out.sort(key=lambda p: p[0] if p[0] is not None else float("-inf"))
        return out

    def _drain_buffers(self) -> list[tuple[Optional[float], str]]:
        out = [
            _parse_stamp(buf) for buf in self._buffers.values() if buf
        ]
        self._buffers = {f: "" for f in self._files}
        return out

    def _in_window(self, ts: Optional[float]) -> bool:
        if ts is None:
            return True
        if self._since is not None and ts < self._since:
            return False
        if self._until is not None and ts > self._until:
            return False
        return True

    def _app_finished(self) -> bool:
        """The worker's exitcode file is the primary signal (the queued
        resource stays ACTIVE after the startup script exits). Queued-
        resource state is the backstop; one failed describe is a transient
        (network blip), only repeated failures end the tail."""
        if self._exited:
            return True
        from torchx_tpu.specs.api import is_terminal

        try:
            desc = self._sched.describe(self._app_id)
        except Exception:
            desc = None
        if desc is None:
            self._describe_failures += 1
            return self._describe_failures >= 3
        self._describe_failures = 0
        return is_terminal(desc.state)

    def __iter__(self):
        import time as _time

        while True:
            batch = self._poll_once()
            if not self._should_tail:
                batch.extend(self._drain_buffers())
            for ts, line in batch:
                if self._in_window(ts):
                    yield line
            if not self._should_tail:
                return
            if self._app_finished():
                for ts, line in self._poll_once() + self._drain_buffers():
                    if self._in_window(ts):
                        yield line
                return
            _time.sleep(self._poll)


# spot reclamation / host-event markers in queued-resource error messages
_QR_PREEMPTION_RE = re.compile(
    r"preempt|reclaim|spot\s+(instance|capacity|vm).*(terminat|delet)|maintenance event",
    re.I,
)
def _qr_is_spot(data: Mapping[str, Any]) -> bool:
    """Whether the queued resource runs on reclaimable capacity (created
    with --spot / best-effort, or nodes with a preemptible/spot
    schedulingConfig)."""
    if "spot" in data or "bestEffort" in data or "best_effort" in data:
        return True
    for spec in (data.get("tpu") or {}).get("nodeSpec") or []:
        sc = ((spec.get("node") or {}).get("schedulingConfig")) or {}
        if sc.get("spot") or sc.get("preemptible"):
            return True
    return False


def _qr_error_message(data: Mapping[str, Any]) -> str:
    """Flatten every error message the QR state carries (state.failedData
    plus per-node provisioningData errors) into one searchable string."""
    state = data.get("state") or {}
    parts = []
    failed = state.get("failedData") or {}
    err = failed.get("error") or {}
    if err.get("message"):
        parts.append(str(err["message"]))
    for key in ("stateInitiator", "state_initiator"):
        if state.get(key):
            parts.append(str(state[key]))
    return " | ".join(parts)


def classify_queued_resource(
    data: Mapping[str, Any],
) -> tuple[AppState, Optional[FailureClass]]:
    """-> (AppState, FailureClass) for a queued-resource describe payload.

    The TPU-specific failure semantics:

    * a **spot** QR collapsing to SUSPENDING/SUSPENDED after being ACTIVE
      means Cloud TPU reclaimed the capacity — that attempt is over
      (PREEMPTED), not merely pending;
    * a FAILED QR is a *control-plane* outcome (provisioning never
      succeeded — the user workload cannot fail the QR), so the default
      class is INFRA, upgraded to PREEMPTION when the error message names
      a reclamation.
    """
    state_str = ((data.get("state") or {}).get("state")) or ""
    state = QR_STATE_MAP.get(state_str, AppState.UNKNOWN)
    if state_str in ("SUSPENDING", "SUSPENDED") and _qr_is_spot(data):
        return AppState.PREEMPTED, FailureClass.PREEMPTION
    if state_str == "FAILED":
        msg = _qr_error_message(data)
        if _QR_PREEMPTION_RE.search(msg):
            return AppState.PREEMPTED, FailureClass.PREEMPTION
        return state, FailureClass.INFRA
    return state, None


def describe_queued_resource(
    app_id: str, data: Mapping[str, Any]
) -> DescribeAppResponse:
    state_str = ((data.get("state") or {}).get("state")) or ""
    state, failure_class = classify_queued_resource(data)
    role = RoleStatus(role="tpu")
    nodes = (data.get("tpu") or {}).get("nodeSpec") or []
    for i, _ in enumerate(nodes or [None]):
        role.replicas.append(ReplicaStatus(id=i, state=state, role="tpu"))
    msg = state_str
    err = _qr_error_message(data)
    if err:
        msg = f"{state_str}: {err}" if state_str else err
    return DescribeAppResponse(
        app_id=app_id,
        state=state,
        msg=msg,
        roles_statuses=[role],
        failure_class=failure_class,
    )


def create_scheduler(session_name: str, **kwargs: Any) -> TpuVmScheduler:
    return TpuVmScheduler(session_name=session_name)
