"""Vertex AI custom-training scheduler: managed TPU training jobs.

The managed-training backend analog of the reference's SageMaker scheduler
(torchx/schedulers/aws_sagemaker_scheduler.py:407-421 submits a
``CreateTrainingJob`` request materialized from the AppDef) — re-thought
for GCP: an AppDef materializes into a Vertex AI ``CustomJob`` whose
worker pools carry TPU ``machineSpec``s (ct5p/ct5lp/ct6e machine types +
``tpuTopology``), submitted through ``google-cloud-aiplatform``.

Design notes (TPU-first):
- A TPU role is ONE worker pool: Vertex models a whole (possibly
  multi-host) slice as a single logical replica with a ``tpuTopology``;
  the TPU runtime on the VMs provides per-host identity
  (``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``), which
  :func:`torchx_tpu.distributed.gang_info` already consumes as its
  fallback — so the same user code runs unchanged under gke/tpu_vm/vertex.
- Everything up to ``schedule()`` is pure materialization: ``dryrun``
  produces the complete CustomJob dict and is fully testable without the
  google-cloud-aiplatform SDK or a GCP project.
- The SDK import is deferred and the client injectable, mirroring the
  docker/gke schedulers' testability contract.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu import settings
from torchx_tpu.resilience.call import resilient_call
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    DescribeAppResponse,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    rfc3339 as _rfc3339,
)
from torchx_tpu.schedulers.ids import make_unique
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    ReplicaStatus,
    Role,
    RoleStatus,
    macros,
    runopts,
)
from torchx_tpu.workspace.docker_workspace import DockerWorkspaceMixin

logger = logging.getLogger(__name__)

# TPU generation -> Vertex machine-type family. The chip count per host
# picks the -Nt suffix for single-host shapes (reference for the naming:
# cloud.google.com/vertex-ai/docs/training/configure-compute#tpu).
VERTEX_TPU_MACHINE_TYPES = {
    "v4": "ct4p-hightpu-4t",
    "v5p": "ct5p-hightpu-4t",
    "v5e": "ct5lp-hightpu-{chips}t",
    "v6e": "ct6e-standard-{chips}t",
}

# Vertex JobState -> AppState (JOB_STATE_* enum names / numbers)
VERTEX_STATE_MAP = {
    "JOB_STATE_QUEUED": AppState.PENDING,
    "JOB_STATE_PENDING": AppState.PENDING,
    "JOB_STATE_RUNNING": AppState.RUNNING,
    "JOB_STATE_SUCCEEDED": AppState.SUCCEEDED,
    "JOB_STATE_FAILED": AppState.FAILED,
    "JOB_STATE_CANCELLING": AppState.CANCELLED,
    "JOB_STATE_CANCELLED": AppState.CANCELLED,
    "JOB_STATE_PAUSED": AppState.PENDING,
    "JOB_STATE_EXPIRED": AppState.FAILED,
}

LABEL_APP_NAME = "tpx-app-name"
LABEL_SESSION = "tpx-session"

VERTEX_JOBS_FILE = ".tpx_vertex_jobs"


def tpu_machine_spec(role: Role) -> dict[str, Any]:
    tpu = role.resource.tpu
    family = VERTEX_TPU_MACHINE_TYPES.get(tpu.accelerator)
    if family is None:
        raise ValueError(
            f"TPU generation {tpu.accelerator!r} has no Vertex AI machine"
            f" type (supported: {sorted(VERTEX_TPU_MACHINE_TYPES)})"
        )
    machine_type = family.format(chips=tpu.chips_per_host)
    spec: dict[str, Any] = {"machineType": machine_type}
    if tpu.hosts > 1:
        spec["tpuTopology"] = tpu.default_topology()
    return spec


def cpu_machine_spec(role: Role) -> dict[str, Any]:
    """Machine spec for non-TPU roles: an explicit ``gce.machine_type``
    capability wins (heterogeneous-fleet catalog, named_resources_gcp);
    GPU roles add acceleratorType/Count from the devices dict; otherwise
    the smallest n2-standard covering the cpu/mem ask."""
    caps = role.resource.capabilities
    gpus = int(role.resource.devices.get("nvidia.com/gpu", 0))
    machine = caps.get("gce.machine_type")
    if machine is None:
        cpu = max(1, int(role.resource.cpu or 1))
        mem_gb = max(1, (int(role.resource.memMB or 0) + 1023) // 1024)
        machine = "n2-standard-128"
        for vcpus in (2, 4, 8, 16, 32, 48, 64, 80, 96, 128):
            if vcpus >= cpu and vcpus * 4 >= mem_gb:  # n2-standard: 4 GB/vCPU
                machine = f"n2-standard-{vcpus}"
                break
    spec: dict[str, Any] = {"machineType": str(machine)}
    if gpus:
        # Vertex accelerator enums are UPPER_SNAKE of the GKE label
        accel = str(caps.get("gke.accelerator", "nvidia-tesla-t4"))
        spec["acceleratorType"] = accel.upper().replace("-", "_")
        spec["acceleratorCount"] = gpus
    return spec


def role_to_worker_pool(role: Role, app_name: str) -> dict[str, Any]:
    tpu = role.resource.tpu
    values = macros.Values(
        img_root="",
        app_id=app_name,
        # a TPU role is one slice = one Vertex replica; per-host identity
        # comes from the TPU runtime at run time, not from materialization
        replica_id="0",
        num_replicas=str(role.num_replicas),
        coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
    )
    srole = values.apply(role)
    env = [
        {"name": settings.ENV_TPX_APP_ID, "value": app_name},
        {"name": settings.ENV_TPX_ROLE_NAME, "value": role.name},
        {
            "name": settings.ENV_TPX_NUM_REPLICAS,
            "value": str(tpu.hosts if tpu else role.num_replicas),
        },
        {"name": settings.ENV_TPX_ERROR_FILE, "value": "/tmp/tpx_error.json"},
        *({"name": k, "value": v} for k, v in srole.env.items()),
    ]
    return {
        "machineSpec": tpu_machine_spec(role) if tpu else cpu_machine_spec(role),
        "replicaCount": 1 if tpu else role.num_replicas,
        "containerSpec": {
            "imageUri": srole.image,
            "command": [srole.entrypoint],
            "args": list(srole.args),
            "env": env,
        },
    }


def app_to_custom_job(
    app: AppDef,
    app_name: str,
    session_name: str,
    service_account: Optional[str] = None,
    network: Optional[str] = None,
    staging_bucket: Optional[str] = None,
) -> dict[str, Any]:
    """AppDef -> Vertex AI CustomJob resource dict (pure, dryrun-testable)."""
    job_spec: dict[str, Any] = {
        "workerPoolSpecs": [
            role_to_worker_pool(role, app_name) for role in app.roles
        ],
    }
    if service_account:
        job_spec["serviceAccount"] = service_account
    if network:
        job_spec["network"] = network
    if staging_bucket:
        job_spec["baseOutputDirectory"] = {"outputUriPrefix": staging_bucket}
    from torchx_tpu.specs.api import RetryPolicy

    # Vertex restarts the whole job on worker failure when enabled — that
    # matches APPLICATION/ROLE (gang) retry semantics only; REPLICA-scoped
    # retries must NOT trigger a whole-job restart (the same contract the
    # local scheduler enforces)
    if any(
        r.max_retries > 0 and r.retry_policy != RetryPolicy.REPLICA
        for r in app.roles
    ):
        job_spec["scheduling"] = {"restartJobOnWorkerRestart": True}
    return {
        "displayName": app_name,
        "jobSpec": job_spec,
        "labels": {LABEL_APP_NAME: app_name, LABEL_SESSION: session_name},
    }


@dataclass
class VertexJob:
    """Materialized request: CustomJob dict + where to create it."""

    project: str
    region: str
    custom_job: dict[str, Any]
    images_to_push: dict[str, tuple[str, str]] = field(default_factory=dict)

    def __str__(self) -> str:
        return json.dumps(self.custom_job, indent=2, default=str)

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.region}"


# Feature profile for the preflight analyzer (torchx_tpu.analyze): worker
# pools map multi-role apps and machine specs are concrete, but CustomJobs
# have no mounts, no delete()/resize(), and a TPU role is limited to a
# single slice (num_replicas == 1).
CAPABILITIES = SchedulerCapabilities(
    mounts=False,
    multi_role=True,
    multislice=False,
    delete=False,
    resize=False,
    logs=True,
    native_retries=True,
    concrete_resources=True,
    classifies_preemption=False,
)


class VertexScheduler(DockerWorkspaceMixin, Scheduler[VertexJob]):
    """Submits AppDefs as Vertex AI CustomJobs (managed TPU training)."""

    capabilities = CAPABILITIES

    # since/until become server-side Cloud Logging timestamp filters
    supports_log_windows = True

    def __init__(
        self,
        session_name: str,
        client: Optional[Any] = None,
        docker_client: Optional[Any] = None,
    ) -> None:
        super().__init__(
            docker_client=docker_client,
            backend="vertex",
            session_name=session_name,
        )
        self.__client = client

    def _run_cmd(self, cmd: list, **kwargs: Any) -> Any:
        """Raw gcloud seam (monkeypatched in tests); production calls go
        through :meth:`Scheduler._cmd` for deadlines/retries/breakers."""
        import subprocess

        return subprocess.run(cmd, capture_output=True, text=True, **kwargs)

    @property
    def _client(self) -> Any:
        if self.__client is None:
            try:
                from google.cloud import aiplatform_v1
            except ImportError as e:
                raise ModuleNotFoundError(
                    "the vertex scheduler needs google-cloud-aiplatform:"
                    " pip install google-cloud-aiplatform"
                ) from e
            self.__client = aiplatform_v1.JobServiceClient()
        return self.__client

    def run_opts(self) -> runopts:
        opts = super().workspace_opts()
        opts.add("project", type_=str, required=True, help="GCP project id")
        opts.add(
            "region", type_=str, default="us-central1", help="Vertex AI region"
        )
        opts.add(
            "service_account",
            type_=str,
            default=None,
            help="service account email the job runs as",
        )
        opts.add(
            "network",
            type_=str,
            default=None,
            help="full VPC network name for private connectivity",
        )
        opts.add(
            "staging_bucket",
            type_=str,
            default=None,
            help="gs:// prefix for job outputs (baseOutputDirectory)",
        )
        return opts

    def _validate(self, app: AppDef, cfg: Mapping[str, CfgVal]) -> None:
        for role in app.roles:
            tpu = role.resource.tpu if role.resource is not None else None
            if tpu is not None and role.num_replicas > 1:
                raise ValueError(
                    "Vertex AI custom jobs run ONE slice per TPU role"
                    " (no multi-slice DCN support); use the gke scheduler"
                    f" for multi-slice (role {role.name!r} asks for"
                    f" {role.num_replicas} slices)"
                )

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[VertexJob]:
        # Scheduler.submit() does not route through the Runner's _validate
        # call, so enforce the backend constraints here (same pattern as
        # tpu_vm_scheduler)
        self._validate(app, cfg)
        app_name = make_unique(app.name)
        req = VertexJob(
            project=str(cfg.get("project")),
            region=str(cfg.get("region") or "us-central1"),
            custom_job=app_to_custom_job(
                app,
                app_name,
                self.session_name,
                service_account=cfg.get("service_account"),  # type: ignore[arg-type]
                network=cfg.get("network"),  # type: ignore[arg-type]
                staging_bucket=cfg.get("staging_bucket"),  # type: ignore[arg-type]
            ),
        )
        req.images_to_push = self.dryrun_push_images(app, dict(cfg))
        # role images may have been re-pointed at pushed tags after the
        # worker pools were materialized — re-point the pool specs too
        for pool, role in zip(req.custom_job["jobSpec"]["workerPoolSpecs"], app.roles):
            pool["containerSpec"]["imageUri"] = role.image
        return AppDryRunInfo(req, fmt=lambda r: str(r))

    def schedule(self, dryrun_info: AppDryRunInfo[VertexJob]) -> str:
        req = dryrun_info.request
        self.push_images(req.images_to_push)
        job = resilient_call(
            lambda: self._client.create_custom_job(
                parent=req.parent, custom_job=req.custom_job
            ),
            backend=self.backend,
            op="submit",
            policy=NON_IDEMPOTENT,
        )
        # resource name: projects/{p}/locations/{r}/customJobs/{numeric id}
        name = getattr(job, "name", "") or ""
        app_id = req.custom_job["displayName"]
        _save_job_name(app_id, name)
        return app_id

    # -- monitoring --------------------------------------------------------

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        name = _load_job_name(app_id)
        if name is None:
            return None
        try:
            job = resilient_call(
                lambda: self._client.get_custom_job(name=name),
                backend=self.backend,
                op="describe",
            )
        except Exception as e:
            # only a definitive NotFound maps to "no such app"; transport
            # or auth errors must surface so status pollers don't mistake a
            # live job for a deleted one (matched by name: the google SDK
            # is an optional dependency)
            if type(e).__name__ == "NotFound":
                return None
            raise
        return describe_custom_job(app_id, _job_to_dict(job))

    def list(self) -> list[ListAppResponse]:
        raise NotImplementedError(
            "vertex scheduler list() needs a project/region-scoped query;"
            " use `gcloud ai custom-jobs list` or describe(app_id)"
        )

    def _cancel_existing(self, app_id: str) -> None:
        name = _load_job_name(app_id)
        if name is not None:
            resilient_call(
                lambda: self._client.cancel_custom_job(name=name),
                backend=self.backend,
                op="cancel",
            )

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Worker logs land in Cloud Logging; fetched via gcloud so the
        scheduler needs no logging SDK (same pattern as tpu_vm ssh logs).
        since/until map to server-side ``timestamp`` filters; Vertex keeps
        one combined stream per job, so stream selection raises."""
        if streams not in (None, Stream.COMBINED):
            raise ValueError(
                f"vertex job logs are a single combined Cloud Logging"
                f" stream; selecting {streams} is not supported"
            )
        name = _load_job_name(app_id)
        if name is None:
            raise ValueError(f"unknown app: {app_id}")
        # name = projects/{project}/locations/{region}/customJobs/{id}:
        # scope the query to the JOB's project, not the gcloud default
        parts = name.split("/")
        project = parts[1] if len(parts) > 3 else ""
        job_id = parts[-1]
        filt = f'resource.labels.job_id="{job_id}"'
        if since is not None:
            filt += f' AND timestamp>="{_rfc3339(since)}"'
        if until is not None:
            filt += f' AND timestamp<="{_rfc3339(until)}"'
        proc = self._cmd(
            [
                "gcloud",
                "logging",
                "read",
                filt,
                *(["--project", project] if project else []),
                "--format=value(textPayload)",
                "--order=asc",
                "--freshness=30d",
            ],
            op="logs",
        )
        if proc.returncode != 0:
            raise RuntimeError(f"gcloud logging read failed: {proc.stderr}")
        lines: Iterable[str] = iter(proc.stdout.splitlines())
        if regex:
            lines = filter_regex(regex, lines)
        return lines


def _job_to_dict(job: Any) -> dict[str, Any]:
    """Accept proto messages, SDK objects, or plain dicts."""
    if isinstance(job, Mapping):
        return dict(job)
    state = getattr(job, "state", "")
    state = getattr(state, "name", state)  # proto enum -> name
    err = getattr(job, "error", None)
    return {
        "state": state,
        "error": {"message": getattr(err, "message", "")} if err else None,
    }


def describe_custom_job(
    app_id: str, job: Mapping[str, Any]
) -> DescribeAppResponse:
    raw_state = str(job.get("state") or "")
    state = VERTEX_STATE_MAP.get(raw_state, AppState.UNKNOWN)
    err = job.get("error") or {}
    return DescribeAppResponse(
        app_id=app_id,
        state=state,
        structured_error_msg=str(err.get("message", "")) if err else "",
        roles_statuses=[
            RoleStatus(
                role="worker",
                replicas=[ReplicaStatus(id=0, state=state, role="worker")],
            )
        ],
    )


# -- app_id -> CustomJob resource-name registry (cross-process, same
#    pattern as the slurm job-dir registry) --------------------------------


def _registry_path() -> str:
    return os.path.join(os.path.expanduser("~"), VERTEX_JOBS_FILE)


def _save_job_name(app_id: str, name: str) -> None:
    from torchx_tpu.util import registry

    registry.record(_registry_path(), app_id, name)


def _load_job_name(app_id: str) -> Optional[str]:
    from torchx_tpu.util import registry

    return registry.lookup(_registry_path(), app_id)


def create_scheduler(session_name: str, **kwargs: Any) -> VertexScheduler:
    known = {"client", "docker_client"}
    return VertexScheduler(
        session_name=session_name,
        **{k: v for k, v in kwargs.items() if k in known},
    )
