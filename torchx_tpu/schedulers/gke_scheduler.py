"""GKE scheduler: gang-schedule TPU pod slices via JobSet (+ optional Kueue).

Reference analog: torchx/schedulers/kubernetes_scheduler.py (1131 LoC),
which maps AppDef -> Volcano Job CRD. The TPU-first redesign maps AppDef ->
**JobSet** (jobset.x-k8s.io/v1alpha2), the stack GKE documents for TPU
training:

* one ReplicatedJob per role; for TPU roles each Job is an **Indexed Job**
  with ``completions = parallelism = slice.hosts`` (one pod per TPU-VM
  host) — the all-or-nothing unit GKE's TPU node pools expose;
* ``Role.num_replicas`` > 1 on a TPU role means N slices (multi-slice DCN
  training): ``replicatedJob.replicas = N`` and megascale env wiring;
* TPU placement via node selectors ``cloud.google.com/gke-tpu-accelerator``
  + ``cloud.google.com/gke-tpu-topology`` and the ``google.com/tpu``
  resource limit (chips per host) — the role the Volcano task + nvidia.com
  /gpu limits play in the reference (kubernetes_scheduler.py:330-381);
* gang semantics come from JobSet's failure policy (any pod failure
  restarts the whole set, up to ``max_retries``) plus optional Kueue queue
  admission (``kueue.x-k8s.io/queue-name`` label) in place of Volcano
  gang scheduling (reference :553-569);
* rendezvous: JobSet's per-job headless service gives pods stable DNS;
  the coordinator address is the role-0/job-0/pod-0 DNS name injected as
  ``TPX_COORDINATOR_HOST`` (analog of ``VC_{role}_0_HOSTS``, reference
  :524). ``macros.replica_id`` substitutes to ``$(TPX_REPLICA_ID)`` which
  kubelet expands from the Job completion index at runtime.

The kubernetes client import is deferred and injectable: all request
materialization is plain dicts, so dryrun tests run with no cluster
(reference test strategy, kubernetes_scheduler_test.py).
"""

from __future__ import annotations

import copy
import json
import logging
import math
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator, Mapping, Optional, TYPE_CHECKING

from torchx_tpu import settings
from torchx_tpu.resilience.call import resilient_call
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    safe_int as _safe_int,
    DescribeAppResponse,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
)
from torchx_tpu.schedulers.ids import cleanup, make_unique, sanitize_name
from torchx_tpu.util.strings import normalize_str
from torchx_tpu.schedulers.structured_opts import StructuredOpts
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    BindMount,
    CfgVal,
    DeviceMount,
    FailureClass,
    ReplicaStatus,
    Role,
    RoleStatus,
    VolumeMount,
    is_terminal,
    macros,
    runopts,
)
from torchx_tpu.specs.overlays import apply_overlay, get_overlay
from torchx_tpu.workspace.docker_workspace import DockerWorkspaceMixin

if TYPE_CHECKING:
    from kubernetes.client import ApiClient

logger = logging.getLogger(__name__)

JOBSET_GROUP = "jobset.x-k8s.io"
JOBSET_VERSION = "v1alpha2"
JOBSET_PLURAL = "jobsets"

# accelerator node-selector values per generation (GKE naming)
GKE_TPU_ACCELERATORS = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}

# node overhead subtracted from requests so pods fit on the node after
# kubelet reservations (reference kubernetes_scheduler.py:155-161)
RESERVED_MILLICPU = 100
RESERVED_MEMMB = 1024

# JobSet condition type -> AppState (reference state maps :203-254)
JOBSET_STATE_MAP = {
    "Completed": AppState.SUCCEEDED,
    "Failed": AppState.FAILED,
    "Suspended": AppState.PENDING,
    "StartupPolicyCompleted": AppState.RUNNING,
}

POD_STATE_MAP = {
    "Pending": AppState.PENDING,
    "Running": AppState.RUNNING,
    "Succeeded": AppState.SUCCEEDED,
    "Failed": AppState.FAILED,
    "Unknown": AppState.UNKNOWN,
}

LABEL_APP_NAME = "tpx.sh/app-name"
LABEL_ROLE_NAME = "tpx.sh/role-name"
# elastic floor, surfaced to autoscalers and enforced by resize
ANNOTATION_MIN_REPLICAS = "tpx.sh/min-replicas"
LABEL_VERSION = "tpx.sh/version"
ANNOTATION_APP = "tpx.sh/appdef"


@dataclass
class GKEOpts(StructuredOpts):
    """Typed run config for the gke scheduler (StructuredOpts generates the
    runopts schema from these fields + attribute docstrings)."""

    namespace: str = "default"
    """k8s namespace to submit into."""

    queue: Optional[str] = None
    """Kueue LocalQueue name for gang admission (jobs submit suspended and
    Kueue unsuspends when the full slice fits)."""

    service_account: Optional[str] = None
    """k8s service account for the pods."""

    coordinator_port: int = settings.TPX_COORDINATOR_PORT
    """jax.distributed coordinator port."""

    elastic_controller: bool = False
    """run the elastic shrink controller as an in-cluster Job (survives
    operator disconnect; requires a role with min_replicas, a
    service_account with jobset get/delete/create + batch/v1 RBAC, and a
    role image with the ``kubernetes`` extra installed —
    ``pip install torchx-tpu[kubernetes]``)."""


@dataclass
class GKEJob:
    """Materialized request: the JobSet resource + images to push."""

    namespace: str
    resource: dict[str, Any]
    images_to_push: dict[str, tuple[str, str]] = field(default_factory=dict)
    # in-cluster elastic controller Job (``elastic_controller=True``):
    # created alongside the JobSet so slice-failure shrink keeps working
    # after the operator's `tpx watch` process is gone
    controller: Optional[dict[str, Any]] = None

    def __str__(self) -> str:
        payload = self.resource
        if self.controller is not None:
            payload = {"jobset": self.resource, "controller": self.controller}
        return json.dumps(payload, indent=2, default=str)


# =========================================================================
# Request materialization (pure functions -> testable without a cluster)
# =========================================================================


def role_to_container(role: Role) -> dict[str, Any]:
    tpu = role.resource.tpu
    limits: dict[str, Any] = {}
    requests: dict[str, Any] = {}
    if role.resource.cpu > 0:
        mcpu = int(role.resource.cpu * 1000)
        limits["cpu"] = f"{mcpu}m"
        requests["cpu"] = f"{max(0, mcpu - RESERVED_MILLICPU)}m"
    if role.resource.memMB > 0:
        limits["memory"] = f"{role.resource.memMB}M"
        requests["memory"] = f"{max(0, role.resource.memMB - RESERVED_MEMMB)}M"
    if tpu is not None:
        limits["google.com/tpu"] = tpu.chips_per_host
        requests["google.com/tpu"] = tpu.chips_per_host
    for dev, count in role.resource.devices.items():
        limits[dev] = count
        requests[dev] = count

    volume_mounts = []
    for i, m in enumerate(role.mounts):
        if isinstance(m, BindMount):
            volume_mounts.append(
                {"name": f"mount-{i}", "mountPath": m.dst_path, "readOnly": m.read_only}
            )
        elif isinstance(m, VolumeMount):
            volume_mounts.append(
                {"name": f"mount-{i}", "mountPath": m.dst_path, "readOnly": m.read_only}
            )
        elif isinstance(m, DeviceMount):
            volume_mounts.append(
                {
                    "name": f"mount-{i}",
                    "mountPath": m.dst_path,
                    "readOnly": "w" not in m.permissions,
                }
            )
    # /dev/shm tmpfs for framework IPC (reference :370-381)
    volume_mounts.append({"name": "dshm", "mountPath": "/dev/shm"})

    env = [{"name": k, "value": v} for k, v in role.env.items()]
    ports = [
        {"name": name[:15], "containerPort": port}
        for name, port in role.port_map.items()
    ]
    return {
        "name": sanitize_name(role.name),
        "image": role.image,
        "command": [role.entrypoint, *role.args],
        "env": env,
        "ports": ports,
        "resources": {"limits": limits, "requests": requests},
        "volumeMounts": volume_mounts,
    }


def role_to_pod_template(
    role: Role,
    app_name: str,
    coordinator_host: str,
    coordinator_port: int,
    service_account: Optional[str],
    num_slices: int = 1,
) -> dict[str, Any]:
    """Pod template for one TPU-VM host (or CPU replica) of the role.

    Gang identity follows the canonical JobSet multi-slice pattern: the pod
    template is shared by all child Jobs (slices), so per-slice identity must
    come from fieldRefs at pod start. Kubelet env expansion is
    substitution-only, so for ``num_slices > 1`` we inject the
    (TPX_SLICE_ID, TPX_HOST_ID, TPX_HOSTS_PER_SLICE) decomposition and the
    spmd bootstrap derives the global TPX_REPLICA_ID — matching
    ``role_replica_env`` so every backend forms one global world of
    ``hosts * num_slices`` processes.
    """
    tpu = role.resource.tpu
    num_hosts = tpu.hosts if tpu else role.num_replicas

    container = role_to_container(role)
    # gang identity: completion index -> host index; kubelet expands
    # $(JOB_COMPLETION_INDEX) references in env/args at pod start
    identity: list[dict[str, Any]] = [
        {
            "name": "JOB_COMPLETION_INDEX",
            "valueFrom": {
                "fieldRef": {
                    "fieldPath": "metadata.annotations['batch.kubernetes.io/job-completion-index']"
                }
            },
        },
    ]
    if tpu is not None and num_slices > 1:
        identity += [
            {
                "name": "JOB_INDEX",
                "valueFrom": {
                    "fieldRef": {
                        "fieldPath": "metadata.annotations['jobset.sigs.k8s.io/job-index']"
                    }
                },
            },
            {"name": settings.ENV_TPX_SLICE_ID, "value": "$(JOB_INDEX)"},
            {"name": settings.ENV_TPX_HOST_ID, "value": "$(JOB_COMPLETION_INDEX)"},
            {"name": settings.ENV_TPX_HOSTS_PER_SLICE, "value": str(num_hosts)},
            {
                "name": settings.ENV_TPX_NUM_REPLICAS,
                "value": str(num_hosts * num_slices),
            },
            {"name": settings.ENV_MEGASCALE_SLICE_ID, "value": "$(JOB_INDEX)"},
        ]
        if settings.ENV_MEGASCALE_NUM_SLICES not in role.env:
            # early in the env list so later $() references expand; a
            # user-provided override in role.env still wins downstream
            identity.append(
                {
                    "name": settings.ENV_MEGASCALE_NUM_SLICES,
                    "value": str(num_slices),
                }
            )
    else:
        identity += [
            {
                "name": settings.ENV_TPX_REPLICA_ID,
                "value": "$(JOB_COMPLETION_INDEX)",
            },
            {"name": settings.ENV_TPX_NUM_REPLICAS, "value": str(num_hosts)},
        ]
    container["env"] = [
        *identity,
        {"name": settings.ENV_TPX_ROLE_NAME, "value": role.name},
        {"name": settings.ENV_TPX_COORDINATOR_HOST, "value": coordinator_host},
        {"name": settings.ENV_TPX_APP_ID, "value": app_name},
        {"name": settings.ENV_TPX_ERROR_FILE, "value": "/tmp/tpx_error.json"},
        *container["env"],
    ]

    volumes: list[dict[str, Any]] = []
    for i, m in enumerate(role.mounts):
        if isinstance(m, BindMount):
            volumes.append(
                {"name": f"mount-{i}", "hostPath": {"path": m.src_path}}
            )
        elif isinstance(m, VolumeMount):
            volumes.append(
                {
                    "name": f"mount-{i}",
                    "persistentVolumeClaim": {"claimName": m.src},
                }
            )
        elif isinstance(m, DeviceMount):
            volumes.append(
                {"name": f"mount-{i}", "hostPath": {"path": m.src_path}}
            )
    volumes.append({"name": "dshm", "emptyDir": {"medium": "Memory"}})

    spec: dict[str, Any] = {
        "restartPolicy": "Never",
        "containers": [container],
        "volumes": volumes,
    }
    if service_account:
        spec["serviceAccountName"] = service_account
    if tpu is not None:
        spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": GKE_TPU_ACCELERATORS.get(
                tpu.accelerator, f"tpu-{tpu.accelerator}-slice"
            ),
            "cloud.google.com/gke-tpu-topology": tpu.default_topology(),
        }
        # TPU nodes are tainted; tolerate the dedicated taint
        spec["tolerations"] = [
            {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
        ]
    else:
        # heterogeneous node pools for CPU / GPU roles on mixed clusters:
        # GPU roles (Resource.devices nvidia.com/gpu) pin their accelerator
        # pool and tolerate GKE's GPU taint; a gce.machine_type capability
        # pins the instance type for either kind of role
        selector: dict[str, str] = {}
        accel = role.resource.capabilities.get("gke.accelerator")
        if accel:
            selector["cloud.google.com/gke-accelerator"] = str(accel)
        machine = role.resource.capabilities.get("gce.machine_type")
        if machine:
            selector["node.kubernetes.io/instance-type"] = str(machine)
        if selector:
            spec["nodeSelector"] = selector
        if role.resource.devices.get("nvidia.com/gpu"):
            spec["tolerations"] = [
                {
                    "key": "nvidia.com/gpu",
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ]

    return {
        "metadata": {
            "labels": {
                LABEL_APP_NAME: app_name,
                # the UN-truncated cleaned role name: pod-name selectors and
                # describe() key off this label, so it must be derivable from
                # role.name alone (the replicatedJob name may carry a
                # budget-truncation suffix that cannot be recomputed without
                # the whole AppDef)
                LABEL_ROLE_NAME: normalize_str(cleanup(role.name)),
            },
        },
        "spec": spec,
    }


def app_to_jobset(
    app: AppDef,
    app_name: str,
    namespace: str,
    queue: Optional[str],
    service_account: Optional[str],
    coordinator_port: int = settings.TPX_COORDINATOR_PORT,
) -> dict[str, Any]:
    """AppDef -> JobSet resource dict."""
    replicated_jobs = []
    max_retries = max((r.max_retries for r in app.roles), default=0)

    # Pod names are {jobset}-{replicatedJob}-{jobIndex}-{podIndex}, capped
    # at 63 chars by k8s — budget each role's sanitized name against the
    # app name AND its index suffixes, and compute it ONCE so every
    # consumer (rj name, coordinator DNS) sees the same budgeted string.
    role_names: dict[str, str] = {}
    for role in app.roles:
        r_tpu = role.resource.tpu
        r_hosts = r_tpu.hosts if r_tpu else 1
        n_jobs = role.num_replicas if r_tpu else 1
        n_pods = r_hosts if r_tpu else role.num_replicas
        suffix = len(str(max(n_jobs, 1) - 1)) + len(str(max(n_pods, 1) - 1)) + 3
        budget = 63 - len(app_name) - suffix
        if budget < 8:
            # both in-tree callers cap app_name at 40 chars, which always
            # leaves >= 8; a silent floor here would emit pods k8s rejects
            # at admission (the failure mode the reference checks for at
            # kubernetes_scheduler.py:862-889)
            raise ValueError(
                f"app name {app_name!r} ({len(app_name)} chars) leaves"
                f" {budget} chars for role {role.name!r} in the 63-char"
                " pod-name cap; shorten the app name to <= 40 chars"
            )
        role_names[role.name] = sanitize_name(role.name, max_len=min(53, budget))

    for role in app.roles:
        role_name = role_names[role.name]
        tpu = role.resource.tpu
        hosts = tpu.hosts if tpu else 1
        # For TPU roles: one Job per slice (replicas=num_replicas), each an
        # indexed job over the slice's hosts. CPU roles: one job, indexed
        # over num_replicas pods.
        if tpu:
            job_replicas, completions = role.num_replicas, hosts
        else:
            job_replicas, completions = 1, role.num_replicas

        # JobSet DNS: {jobset}-{replicatedJob}-{jobIndex}-{podIndex}.{jobset}
        role0 = role_names[app.roles[0].name]
        coordinator_host = f"{app_name}-{role0}-0-0.{app_name}"

        multislice = bool(tpu) and role.num_replicas > 1
        values = macros.Values(
            img_root="",
            app_id=app_name,
            # multi-slice: an AppDef "replica" is a slice, so the macro is
            # the slice id (TPX_SLICE_ID resolves from the JobSet job index)
            replica_id=f"$({settings.ENV_TPX_SLICE_ID})"
            if multislice
            else f"$({settings.ENV_TPX_REPLICA_ID})",
            # deferred to kubelet env expansion rather than baked as a
            # literal so a `resize` that rewrites the env var propagates to
            # every arg that referenced the macro (for multislice roles the
            # convention is that the macro means the slice count, which
            # resize keeps equal to MEGASCALE_NUM_SLICES)
            num_replicas=f"$({settings.ENV_MEGASCALE_NUM_SLICES})"
            if multislice
            else f"$({settings.ENV_TPX_NUM_REPLICAS})",
            coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
        )
        srole = values.apply(role)
        if multislice:
            # DCN identity: slice id comes from the JobSet job-index fieldRef
            # in the pod template (MEGASCALE_NUM_SLICES itself is injected
            # early in the identity env block so $() references expand);
            # megascale coordinator = slice 0's host 0
            srole.env.setdefault(
                settings.ENV_MEGASCALE_COORDINATOR_ADDRESS,
                f"{coordinator_host}:{coordinator_port + 1}",
            )

        pod_template = role_to_pod_template(
            srole,
            app_name,
            coordinator_host,
            coordinator_port,
            service_account,
            num_slices=role.num_replicas if multislice else 1,
        )

        job_spec: dict[str, Any] = {
            "parallelism": completions,
            "completions": completions,
            "completionMode": "Indexed",
            "backoffLimit": 0,  # gang: restarts are JobSet-level
            "template": pod_template,
        }
        rj: dict[str, Any] = {
            "name": role_name,
            "replicas": job_replicas,
            "template": {"spec": job_spec},
        }
        if role.min_replicas is not None:
            # elastic lower bound. SPMD worlds resize by restart (checkpoint
            # resume + warm compile cache make that cheap); the bound maps to
            # the real admission mechanism available per role shape:
            #  - CPU roles are one Indexed Job over num_replicas pods -> Kueue
            #    partial admission (job-min-parallelism) can admit the Job
            #    with fewer pods when the queue is tight
            #  - TPU roles are one Job per slice; Kueue has no partial
            #    admission for JobSet children, so the floor rides
            #    tpx.sh/min-replicas for external autoscalers AND is injected
            #    as TPX_MIN_REPLICAS so in-job bootstrap logic knows how far
            #    the world may legally shrink on restart
            annotations = {ANNOTATION_MIN_REPLICAS: str(role.min_replicas)}
            if not tpu:
                annotations["kueue.x-k8s.io/job-min-parallelism"] = str(
                    role.min_replicas
                )
            rj["template"]["metadata"] = {"annotations": annotations}
            container = pod_template["spec"]["containers"][0]
            container["env"].insert(
                0,
                {
                    "name": settings.ENV_TPX_MIN_REPLICAS,
                    "value": str(role.min_replicas),
                },
            )
        replicated_jobs.append(rj)

    jobset_spec: dict[str, Any] = {
        "replicatedJobs": replicated_jobs,
        "successPolicy": {"operator": "All", "targetReplicatedJobs": []},
    }
    if max_retries > 0:
        jobset_spec["failurePolicy"] = {"maxRestarts": max_retries}

    metadata: dict[str, Any] = {
        "name": app_name,
        "namespace": namespace,
        "labels": {LABEL_APP_NAME: app_name},
    }
    if queue:
        metadata.setdefault("labels", {})["kueue.x-k8s.io/queue-name"] = queue
        jobset_spec["suspend"] = True  # Kueue admits by unsuspending

    resource = {
        "apiVersion": f"{JOBSET_GROUP}/{JOBSET_VERSION}",
        "kind": "JobSet",
        "metadata": metadata,
        "spec": jobset_spec,
    }

    # per-role raw-request overlays (reference :164-192)
    for role in app.roles:
        overlay = get_overlay(role, "gke")
        if overlay:
            resource = apply_overlay(resource, overlay)
    return resource


def resize_jobset(
    jobset: Mapping[str, Any], role_name: str, num_replicas: int
) -> Optional[dict[str, Any]]:
    """Rewrite a live JobSet to a coherent ``num_replicas``-sized world for
    one role; returns a fresh body ready for re-creation, or ``None`` when
    the role is already at the requested size (no restart warranted).

    AppDef units: slices for TPU roles, pod replicas for CPU roles. Every
    world-size-derived value is rewritten together (Job replicas or
    parallelism/completions, TPX_NUM_REPLICAS, MEGASCALE_NUM_SLICES — and
    args that referenced ``macros.num_replicas`` follow automatically,
    since materialization defers that macro to kubelet ``$(VAR)``
    expansion of these env vars) so the restarted gang agrees on its size
    — the GKE analog of the local scheduler's elastic rebuild, where env
    is re-derived rather than patched piecemeal. Floors declared via the
    ``tpx.sh/min-replicas`` annotation are enforced.
    """
    if num_replicas < 1:
        raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
    body = copy.deepcopy(dict(jobset))
    # strip server-managed fields so the body is valid for re-creation
    body.pop("status", None)
    meta = body.get("metadata", {})
    for k in ("resourceVersion", "uid", "creationTimestamp", "generation", "managedFields"):
        meta.pop(k, None)

    want = normalize_str(cleanup(role_name))
    for rj in body.get("spec", {}).get("replicatedJobs", []):
        job_spec = rj.get("template", {}).get("spec", {})
        pod_template = job_spec.get("template", {})
        labels = pod_template.get("metadata", {}).get("labels", {})
        if labels.get(LABEL_ROLE_NAME) != want:
            continue
        annotations = rj.get("template", {}).get("metadata", {}).get("annotations", {})
        floor = annotations.get(ANNOTATION_MIN_REPLICAS)
        if floor is not None and num_replicas < int(floor):
            raise ValueError(
                f"cannot resize role {role_name!r} to {num_replicas}:"
                f" below its declared min_replicas floor of {floor}"
            )
        container = pod_template.get("spec", {}).get("containers", [{}])[0]
        limits = container.get("resources", {}).get("limits", {})
        is_tpu = "google.com/tpu" in limits
        current = (
            int(rj.get("replicas", 1))
            if is_tpu
            else int(job_spec.get("parallelism", 1))
        )
        if num_replicas == current:
            return None  # already at the requested size: no restart
        if is_tpu:
            # slice units: one child Job per slice; hosts-per-slice fixed
            if num_replicas > int(rj.get("replicas", 1)) and not any(
                e.get("name") == settings.ENV_TPX_SLICE_ID
                for e in container.get("env", [])
            ):
                # a single-slice template carries no slice-id fieldRef
                # decomposition, so pods of a grown set could not derive
                # global replica ids — growth needs a fresh submit
                raise ValueError(
                    f"role {role_name!r} was submitted single-slice; its pod"
                    " template has no multi-slice identity wiring, so it can"
                    " only shrink (resubmit the app to grow)"
                )
            hosts = int(job_spec.get("completions", 1))
            rj["replicas"] = num_replicas
            world = hosts * num_replicas
        else:
            job_spec["parallelism"] = num_replicas
            job_spec["completions"] = num_replicas
            world = num_replicas
        for env in container.get("env", []):
            if env.get("name") == settings.ENV_TPX_NUM_REPLICAS:
                env["value"] = str(world)
            elif env.get("name") == settings.ENV_MEGASCALE_NUM_SLICES:
                env["value"] = str(num_replicas)
        break
    else:
        raise ValueError(
            f"role {role_name!r} not found in jobset"
            f" {meta.get('name', '<unnamed>')}"
        )

    if (body.get("metadata", {}).get("labels", {})).get("kueue.x-k8s.io/queue-name"):
        # resubmit suspended: Kueue re-admits when the resized gang fits —
        # this is what makes shrink-to-fit under queue pressure work
        body["spec"]["suspend"] = True
    return body


def plan_elastic_shrink(
    jobset: Mapping[str, Any],
) -> Optional[tuple[str, Optional[int]]]:
    """Decide whether a failing elastic gang should shrink, from the raw
    JobSet dict (pure function -> fixture-testable, like jobset_state).

    Scans roles carrying the ``tpx.sh/min-replicas`` floor annotation for
    failed child Jobs (one child Job == one slice for TPU roles). Returns
    ``(role_name, new_size)`` to shrink to the surviving slice count,
    ``(role_name, None)`` when survivors are below the floor (un-rescuable),
    or ``None`` when nothing relevant failed. CPU roles are left to Kueue's
    ``job-min-parallelism`` — slice-granular shrink is a TPU-gang concern.
    """
    status = jobset.get("status") or {}
    by_name = {
        str(s.get("name")): s for s in status.get("replicatedJobsStatus") or []
    }
    for rj in jobset.get("spec", {}).get("replicatedJobs", []):
        tmpl = rj.get("template", {})
        annotations = tmpl.get("metadata", {}).get("annotations", {}) or {}
        floor = annotations.get(ANNOTATION_MIN_REPLICAS)
        if floor is None:
            continue
        st = by_name.get(str(rj.get("name"))) or {}
        failed = int(st.get("failed") or 0)
        if failed <= 0:
            continue
        pod_labels = (
            tmpl.get("spec", {})
            .get("template", {})
            .get("metadata", {})
            .get("labels", {})
            or {}
        )
        role_name = pod_labels.get(LABEL_ROLE_NAME) or str(rj.get("name"))
        current = int(rj.get("replicas", 1))
        new_size = current - failed
        if new_size < max(1, int(floor)):
            return role_name, None
        return role_name, new_size
    return None


CONTROLLER_SUFFIX = "-tpx-watch"
LABEL_CONTROLLER_FOR = "tpx.sh/controller-for"


def elastic_controller_job(
    app_name: str,
    namespace: str,
    image: str,
    service_account: Optional[str],
    session_name: str,
    max_restarts: int = 3,
) -> dict[str, Any]:
    """In-cluster elastic controller: a plain batch/v1 Job running
    ``tpx watch gke://...`` against its own JobSet, so slice-failure
    shrink (:func:`plan_elastic_shrink` via :meth:`GKEScheduler.resize`)
    keeps working when the operator's terminal is gone — the in-cluster
    analog of the local scheduler's in-process elastic restart.

    Deliberately NOT a child of the JobSet (resize deletes + re-creates
    the set; the controller must survive that) and not owner-referenced;
    it exits when the app reaches a terminal state, GCs itself via
    ``ttlSecondsAfterFinished``, and cancel/delete remove it eagerly.
    The pod authenticates via the mounted ``service_account`` token
    (``load_incluster_config`` fallback in ``_api_client``), which needs
    get/delete/create on jobsets. The shrink budget is process-local: a
    controller pod restart (restartPolicy OnFailure, e.g. after a
    transient apiserver error) starts a fresh budget, and once
    ``backoffLimit`` is spent the app keeps running without elastic
    protection — `tpx watch` client-side remains available as a backstop.
    """
    handle = f"gke://{session_name}/{namespace}:{app_name}"
    pod_spec: dict[str, Any] = {
        "restartPolicy": "OnFailure",
        "containers": [
            {
                "name": "tpx-elastic-controller",
                "image": image,
                "command": [
                    "python",
                    "-u",
                    "-m",
                    "torchx_tpu.cli.main",
                    "watch",
                    handle,
                    "--max-restarts",
                    str(max_restarts),
                ],
                # binary units (Mi/Gi), and 1Gi of limit headroom: the
                # watch path imports no jax (the launcher layers are
                # accelerator-free), but role images bundle heavyweight
                # libraries whose import-time cost we don't control, and
                # an OOMKill loop here burns backoffLimit until elastic
                # protection silently lapses — describe() surfaces that
                # state, the headroom avoids it
                "resources": {
                    "limits": {"cpu": "250m", "memory": "1Gi"},
                    "requests": {"cpu": "100m", "memory": "256Mi"},
                },
            }
        ],
    }
    if service_account:
        pod_spec["serviceAccountName"] = service_account
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"{app_name}{CONTROLLER_SUFFIX}",
            "namespace": namespace,
            "labels": {LABEL_CONTROLLER_FOR: app_name},
        },
        "spec": {
            "backoffLimit": 6,
            # a cleanly-finished app leaves no one to call delete(): let
            # the cluster GC the completed controller Job + pod
            "ttlSecondsAfterFinished": 3600,
            "template": {
                "metadata": {"labels": {LABEL_CONTROLLER_FOR: app_name}},
                "spec": pod_spec,
            },
        },
    }


# =========================================================================
# Scheduler
# =========================================================================


# Feature profile for the preflight analyzer (torchx_tpu.analyze): the most
# capable backend — JobSet multi-role, multi-slice DCN, volume mounts,
# failurePolicy restarts, node-disruption preemption classification, and
# concrete resource requests from cpu/memMB.
CAPABILITIES = SchedulerCapabilities(
    mounts=True,
    multi_role=True,
    multislice=True,
    delete=True,
    resize=True,
    logs=True,
    native_retries=True,
    concrete_resources=True,
    classifies_preemption=True,
    # native event source: kubectl's streaming watch (see
    # GKEScheduler.watch); degrades to the poll adapter without kubectl
    watch=True,
    # pod IPs resolve over cluster DNS; /metricz is scrapeable in-cluster
    metricz_scrape=True,
)


class GKEScheduler(DockerWorkspaceMixin, Scheduler[GKEJob]):
    """Submits AppDefs as JobSets to a GKE (or any JobSet-enabled) cluster."""

    capabilities = CAPABILITIES

    def __init__(
        self,
        session_name: str,
        client: Optional["ApiClient"] = None,
        docker_client: Optional[Any] = None,
    ) -> None:
        super().__init__(docker_client=docker_client, backend="gke", session_name=session_name)
        self._client = client

    # -- k8s clients (deferred import; injectable) -------------------------

    def _api_client(self) -> "ApiClient":
        if self._client is None:
            from kubernetes import client as k8s_client, config as k8s_config

            try:
                k8s_config.load_kube_config()
            except Exception:  # noqa: BLE001 - in-cluster fallback
                k8s_config.load_incluster_config()
            self._client = k8s_client.ApiClient()
        return self._client

    def _custom_objects_api(self):  # noqa: ANN202
        from kubernetes.client import CustomObjectsApi

        return CustomObjectsApi(self._api_client())

    def _core_api(self):  # noqa: ANN202
        from kubernetes.client import CoreV1Api

        return CoreV1Api(self._api_client())

    def _batch_api(self):  # noqa: ANN202
        from kubernetes.client import BatchV1Api

        return BatchV1Api(self._api_client())

    # -- runopts ----------------------------------------------------------

    def run_opts(self) -> runopts:
        return GKEOpts.to_runopts() | self.workspace_opts()

    # -- dryrun / schedule -------------------------------------------------

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[GKEJob]:
        opts = GKEOpts.from_cfg(cfg)
        namespace = opts.namespace or "default"  # '' from `-cfg namespace=`
        # 40-char app budget leaves room in the 63-char pod-name cap for a
        # >=8-char role name plus multi-digit job/pod index suffixes
        app_name = sanitize_name(make_unique(app.name), max_len=40)
        images_to_push = self.dryrun_push_images(app, cfg)
        resource = app_to_jobset(
            app,
            app_name,
            namespace=namespace,
            queue=opts.queue,
            service_account=opts.service_account,
            coordinator_port=opts.coordinator_port,
        )
        controller: Optional[dict[str, Any]] = None
        if opts.elastic_controller:
            elastic_role = next(
                (r for r in app.roles if r.min_replicas is not None), None
            )
            if elastic_role is None:
                raise ValueError(
                    "elastic_controller=True requires a role with a"
                    " min_replicas floor (e.g. dist.spmd -j min:max)"
                )
            # the role image carries torchx_tpu (its entrypoint is
            # `python -m torchx_tpu.apps...`), so the controller reuses it
            controller = elastic_controller_job(
                app_name,
                namespace=namespace,
                image=elastic_role.image,
                service_account=opts.service_account,
                session_name=self.session_name,
                max_restarts=max(1, elastic_role.max_retries or 3),
            )
        req = GKEJob(
            namespace=namespace,
            resource=resource,
            images_to_push=images_to_push,
            controller=controller,
        )
        return AppDryRunInfo(req)

    def schedule(self, dryrun_info: AppDryRunInfo[GKEJob]) -> str:
        req = dryrun_info.request
        self.push_images(req.images_to_push)
        from kubernetes.client.rest import ApiException

        try:
            resilient_call(
                lambda: self._custom_objects_api().create_namespaced_custom_object(
                    group=JOBSET_GROUP,
                    version=JOBSET_VERSION,
                    namespace=req.namespace,
                    plural=JOBSET_PLURAL,
                    body=req.resource,
                ),
                backend=self.backend,
                op="submit",
                policy=NON_IDEMPOTENT,
            )
        except ApiException as e:
            if e.status == 409:
                raise ValueError(
                    f"jobset {req.resource['metadata']['name']} already exists"
                ) from e
            raise
        app_id = f"{req.namespace}:{req.resource['metadata']['name']}"
        if req.controller is not None:
            # the JobSet is already live: a controller-create failure must
            # not raise (the caller would lose the handle of a running,
            # capacity-consuming app) — degrade to unprotected + loud
            try:
                self._batch_api().create_namespaced_job(
                    namespace=req.namespace, body=req.controller
                )
            except Exception as e:  # noqa: BLE001 - degrade, don't orphan
                logger.error(
                    "%s: elastic controller Job creation failed (%s);"
                    " the app is RUNNING but NOT elastic-protected —"
                    " run `tpx watch gke://%s/%s` client-side as a backstop",
                    app_id,
                    e,
                    self.session_name,
                    app_id,
                )
        return app_id

    # -- monitoring --------------------------------------------------------

    @staticmethod
    def _parse_app_id(app_id: str) -> tuple[str, str]:
        namespace, _, name = app_id.partition(":")
        if not name:
            raise ValueError(f"invalid gke app id {app_id!r}; expected namespace:name")
        return namespace, name

    def watch(self, app_ids=(), interval=None):
        """Native event stream: one ``kubectl get jobsets -w`` subprocess
        per watched namespace (shared by every JobSet in it), with
        terminal lines confirmed through :meth:`describe` so preemption
        classification stays authoritative. Falls back to the generic
        poll scan for namespaces where kubectl cannot be spawned."""
        from torchx_tpu.control.watch import KubectlWatcher

        return KubectlWatcher(self, app_ids, interval=interval)

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        namespace, name = self._parse_app_id(app_id)
        from kubernetes.client.rest import ApiException

        try:
            jobset = resilient_call(
                lambda: self._custom_objects_api().get_namespaced_custom_object(
                    group=JOBSET_GROUP,
                    version=JOBSET_VERSION,
                    namespace=namespace,
                    plural=JOBSET_PLURAL,
                    name=name,
                ),
                backend=self.backend,
                op="describe",
            )
        except ApiException as e:
            if e.status == 404:
                return None
            raise
        resp = describe_jobset(jobset, self._list_pods(namespace, name))
        note = self._controller_health_note(namespace, name)
        if note:
            resp.msg = f"{resp.msg}; {note}" if resp.msg else note
        return resp

    def _controller_health_note(self, namespace: str, name: str) -> str:
        """Non-empty when the in-cluster elastic controller Job has failed
        (backoffLimit exhausted — e.g. an OOMKill loop): from that point
        the app runs WITHOUT elastic protection, which an operator reading
        ``tpx status`` must see rather than discover at the next slice
        failure (advisor r4). Best-effort: no controller Job, no note."""
        try:
            job = self._batch_api().read_namespaced_job(
                name=f"{name}{CONTROLLER_SUFFIX}", namespace=namespace
            )
            status = getattr(job, "status", None)
            conditions = list(getattr(status, "conditions", None) or [])
            for cond in conditions:
                if (
                    getattr(cond, "type", "") == "Failed"
                    and getattr(cond, "status", "") == "True"
                ):
                    reason = getattr(cond, "reason", "") or "Failed"
                    return (
                        "elastic controller FAILED "
                        f"({reason}): slice-failure shrink is no longer "
                        "armed — run `tpx watch` client-side or resubmit"
                    )
        except Exception:  # noqa: BLE001 - health note is best-effort
            return ""
        return ""

    def _list_pods(self, namespace: str, name: str) -> list[dict[str, Any]]:
        try:
            pods = self._core_api().list_namespaced_pod(
                namespace=namespace,
                label_selector=f"jobset.sigs.k8s.io/jobset-name={name}",
            )
            return [p.to_dict() if hasattr(p, "to_dict") else p for p in pods.items]
        except Exception:  # noqa: BLE001 - pod detail is best-effort
            return []

    def list(self) -> list[ListAppResponse]:
        out = []
        jobsets = resilient_call(
            lambda: self._custom_objects_api().list_cluster_custom_object(
                group=JOBSET_GROUP, version=JOBSET_VERSION, plural=JOBSET_PLURAL
            ),
            backend=self.backend,
            op="list",
        )
        for js in jobsets.get("items", []):
            meta = js.get("metadata", {})
            out.append(
                ListAppResponse(
                    app_id=f"{meta.get('namespace')}:{meta.get('name')}",
                    state=jobset_state(js),
                    name=meta.get("name", ""),
                )
            )
        return out

    def _cancel_existing(self, app_id: str) -> None:
        """Suspend (preserves spec + logs) rather than delete (reference
        cancel=abort-preserving-spec, :901-934). The elastic controller
        Job (if any) is removed — a suspended set must not be 'rescued'."""
        namespace, name = self._parse_app_id(app_id)
        resilient_call(
            lambda: self._custom_objects_api().patch_namespaced_custom_object(
                group=JOBSET_GROUP,
                version=JOBSET_VERSION,
                namespace=namespace,
                plural=JOBSET_PLURAL,
                name=name,
                body={"spec": {"suspend": True}},
            ),
            backend=self.backend,
            op="cancel",
        )
        self._delete_controller(namespace, name)

    def _delete_controller(self, namespace: str, name: str) -> None:
        """Remove the in-cluster elastic controller Job, if one exists.

        Best-effort: this runs for EVERY cancel/delete (the scheduler
        can't know whether the app was submitted with a controller), so
        an RBAC denial on batch/v1 must not break cancel/delete of apps
        that never had one."""
        try:
            self._batch_api().delete_namespaced_job(
                name=f"{name}{CONTROLLER_SUFFIX}",
                namespace=namespace,
                propagation_policy="Background",
            )
        except Exception as e:  # noqa: BLE001 - cleanup is advisory
            status = getattr(e, "status", None)
            if status != 404:
                logger.warning(
                    "could not delete elastic controller %s%s in %s: %s",
                    name,
                    CONTROLLER_SUFFIX,
                    namespace,
                    e,
                )

    def delete(self, app_id: str) -> None:
        namespace, name = self._parse_app_id(app_id)
        from kubernetes.client.rest import ApiException

        try:
            resilient_call(
                lambda: self._custom_objects_api().delete_namespaced_custom_object(
                    group=JOBSET_GROUP,
                    version=JOBSET_VERSION,
                    namespace=namespace,
                    plural=JOBSET_PLURAL,
                    name=name,
                ),
                backend=self.backend,
                op="delete",
            )
        except ApiException as e:
            if e.status != 404:
                raise
        self._delete_controller(namespace, name)

    # seconds between deletion polls during resize (tests set this to 0)
    resize_poll_interval: float = 1.0

    def resize(self, app_id: str, role_name: str, num_replicas: int) -> None:
        """Resize one role's gang by replace: JobSet pod templates are
        immutable and a JobSet-level restart would reuse the stale world
        env, so the resize primitive is delete + re-create of the rewritten
        set under the same name. With a Kueue queue the new set goes back
        suspended and Kueue re-admits when the resized gang fits; user code
        resumes from its checkpoint (warm compile cache makes the restart
        cheap — docs/performance.md)."""
        namespace, name = self._parse_app_id(app_id)
        from kubernetes.client.rest import ApiException

        api = self._custom_objects_api()
        common = dict(
            group=JOBSET_GROUP,
            version=JOBSET_VERSION,
            namespace=namespace,
            plural=JOBSET_PLURAL,
            name=name,
        )
        try:
            jobset = api.get_namespaced_custom_object(**common)
        except ApiException as e:
            if e.status == 404:
                raise ValueError(f"app {app_id} does not exist") from e
            raise
        body = resize_jobset(jobset, role_name, num_replicas)
        if body is None:
            logger.info(
                "%s role %s is already %d wide; not restarting the gang",
                app_id,
                role_name,
                num_replicas,
            )
            return
        # Rescue the rewritten body to disk BEFORE the delete: the
        # delete/poll/create window is up to 120 polls long, and if this
        # process dies inside it the app would otherwise be gone with
        # nothing to resubmit. `kubectl apply -f <path>` recovers.
        rescue_path = self._write_resize_rescue(name, body)
        logger.info(
            "resize %s: rewritten body saved to %s (kubectl apply -f it"
            " if this process dies mid-resize)",
            app_id,
            rescue_path,
        )
        # foreground propagation: the JobSet object only 404s once its
        # child Jobs/pods are gone too, so the poll below doubles as
        # waiting for the old gang's TPU capacity to actually free up
        api.delete_namespaced_custom_object(
            **common, propagation_policy="Foreground"
        )
        for _ in range(120):
            try:
                api.get_namespaced_custom_object(**common)
            except ApiException as e:
                if e.status == 404:
                    break
                raise
            time.sleep(self.resize_poll_interval)
        else:
            raise RuntimeError(
                f"jobset {name} was not deleted in time; resize aborted"
                f" before re-creation (re-run once the deletion finishes,"
                f" or `kubectl apply -f {rescue_path}`)"
            )
        try:
            api.create_namespaced_custom_object(
                group=JOBSET_GROUP,
                version=JOBSET_VERSION,
                namespace=namespace,
                plural=JOBSET_PLURAL,
                body=body,
            )
        except Exception:
            # the old set is gone; the pre-delete rescue file is the
            # operator's path to resubmission
            logger.error(
                "re-creation of jobset %s failed AFTER deletion; the"
                " resized body was saved to %s — fix the rejection and"
                " `kubectl apply -f` it",
                name,
                rescue_path,
            )
            raise
        else:
            try:
                os.unlink(rescue_path)
            except OSError:
                pass

    @staticmethod
    def _write_resize_rescue(name: str, body: dict) -> str:
        import tempfile

        fd, path = tempfile.mkstemp(prefix=f"tpx-resize-{name}-", suffix=".json")
        with open(fd, "w") as f:
            json.dump(body, f, indent=2, default=str)
        return path

    def watch_elastic(
        self,
        app_id: str,
        poll_interval: float = 10.0,
        timeout: Optional[float] = None,
        max_restarts: int = 3,
    ) -> int:
        """Failure-driven elastic controller: the GKE analog of the local
        scheduler's ``_try_elastic_restart`` (local_scheduler.py), run
        operator-side because JobSet has no in-cluster shrink semantics.

        Polls the JobSet; when a slice of a role carrying the
        ``tpx.sh/min-replicas`` floor fails, shrinks the gang to the
        surviving slice count via :meth:`resize` (delete + re-create; user
        code resumes from its checkpoint exactly as with the manual
        ``resize`` verb — under Kueue the resized set re-enters the queue
        suspended). Returns the number of shrink-restarts performed.
        Stops on: terminal app state, survivors below the floor, restart
        budget exhausted, or ``timeout`` seconds elapsed.
        """
        namespace, name = self._parse_app_id(app_id)
        from kubernetes.client.rest import ApiException

        api = self._custom_objects_api()
        deadline = time.monotonic() + timeout if timeout else None
        restarts = 0
        while True:
            try:
                jobset = api.get_namespaced_custom_object(
                    group=JOBSET_GROUP,
                    version=JOBSET_VERSION,
                    namespace=namespace,
                    plural=JOBSET_PLURAL,
                    name=name,
                )
            except ApiException as e:
                if e.status == 404:
                    return restarts  # deleted out from under the watcher
                raise
            state = jobset_state(jobset)
            plan = plan_elastic_shrink(jobset)
            if plan is not None:
                role_name, new_size = plan
                if new_size is None:
                    logger.error(
                        "%s role %s: survivors below the min-replicas floor;"
                        " not rescuable by shrinking",
                        app_id,
                        role_name,
                    )
                    return restarts
                if restarts >= max_restarts:
                    logger.error(
                        "%s: shrink budget (%d) exhausted", app_id, max_restarts
                    )
                    return restarts
                logger.info(
                    "%s role %s: slice failure detected; shrinking to %d",
                    app_id,
                    role_name,
                    new_size,
                )
                self.resize(app_id, role_name, new_size)
                restarts += 1
            elif is_terminal(state):
                return restarts
            if deadline is not None and time.monotonic() >= deadline:
                return restarts
            time.sleep(poll_interval)

    supports_log_windows = True  # since via since_seconds, until via stamps

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Pod logs with real window fidelity: ``since`` maps to the API's
        ``since_seconds``, ``until`` is applied client-side from kubelet
        RFC3339 line stamps (``timestamps=True``; stamps are stripped
        before yielding so output is byte-identical to the unwindowed
        path), and stdout/stderr selection raises — the kubelet keeps one
        combined stream per container (reference analog:
        kubernetes_scheduler.py:1025-1045)."""
        if streams not in (None, Stream.COMBINED):
            raise ValueError(
                f"kubernetes pod logs are a single combined stream;"
                f" selecting {streams} is not supported on gke"
            )
        namespace, name = self._parse_app_id(app_id)
        pod_name = self._resolve_pod_name(namespace, name, role_name, k)
        core = self._core_api()
        kwargs: dict[str, Any] = {}
        if since is not None:
            age = time.time() - since
            if age <= 0:
                return iter(())  # window entirely in the future: nothing
            # ceil keeps the window inclusive (int() would start it up to
            # 1s late and drop in-window lines)
            kwargs["since_seconds"] = max(1, math.ceil(age))
        if until is not None:
            kwargs["timestamps"] = True
        resp = core.read_namespaced_pod_log(
            name=pod_name,
            namespace=namespace,
            follow=should_tail,
            _preload_content=False,
            **kwargs,
        )
        lines = (ln.decode("utf-8", errors="replace").rstrip("\n") for ln in resp)
        if until is not None:
            lines = _strip_until(lines, until)
        if regex:
            lines = filter_regex(regex, lines)
        return lines

    def _resolve_pod_name(
        self, namespace: str, name: str, role_name: str, k: int
    ) -> str:
        """Job-created pods carry a random suffix, so the name cannot be
        computed — resolve replica ``k`` by listing the jobset's pods for
        the role and ordering by (job index, completion index); across
        multi-slice jobs ``k`` counts hosts globally."""
        # select by our own role label, not the replicatedJob name: that
        # name is budget-truncated against the 63-char pod cap inside
        # app_to_jobset and cannot be recomputed from role_name alone
        pods = self._core_api().list_namespaced_pod(
            namespace=namespace,
            label_selector=(
                f"jobset.sigs.k8s.io/jobset-name={name},"
                f"{LABEL_ROLE_NAME}={normalize_str(cleanup(role_name))}"
            ),
        )
        indexed: list[tuple[int, int, str]] = []
        for pod in pods.items:
            meta = pod.metadata
            labels = meta.labels or {}
            annotations = meta.annotations or {}
            job_index = int(labels.get("jobset.sigs.k8s.io/job-index", 0))
            completion_index = int(
                annotations.get("batch.kubernetes.io/job-completion-index", 0)
            )
            indexed.append((job_index, completion_index, meta.name))
        indexed.sort()
        if k >= len(indexed):
            raise ValueError(
                f"replica {k} of role {role_name} not found"
                f" ({len(indexed)} pods exist for jobset {name})"
            )
        return indexed[k][2]


def _strip_until(lines: Iterable[str], until: float) -> Iterator[str]:
    """Drop lines stamped after ``until`` and strip the kubelet RFC3339
    timestamp prefix from the rest. Unstamped lines (shouldn't happen with
    ``timestamps=True``, but be permissive) pass through whole."""
    from datetime import datetime

    for line in lines:
        stamp, _, payload = line.partition(" ")
        try:
            # kubelet stamps are RFC3339Nano with trailing zeros trimmed
            # (Go time formatting); Python 3.10's fromisoformat accepts
            # only 3 or 6 fractional digits, so normalize to exactly 6
            norm = re.sub(
                r"\.(\d+)",
                lambda m: "." + (m.group(1) + "000000")[:6],
                stamp.replace("Z", "+00:00"),
            )
            ts = datetime.fromisoformat(norm).timestamp()
        except ValueError:
            yield line
            continue
        if ts > until:
            return
        yield payload


# =========================================================================
# Status mapping (pure functions over dicts -> fixture-testable)
# =========================================================================


def jobset_state(jobset: Mapping[str, Any]) -> AppState:
    status = jobset.get("status") or {}
    conditions = status.get("conditions") or []
    for cond in reversed(conditions):
        if cond.get("status") == "True" and cond.get("type") in JOBSET_STATE_MAP:
            return JOBSET_STATE_MAP[cond["type"]]
    if jobset.get("spec", {}).get("suspend"):
        return AppState.PENDING
    if status.get("replicatedJobsStatus"):
        return AppState.RUNNING
    return AppState.PENDING if status else AppState.SUBMITTED


# Pod DisruptionTarget reasons that mean the NODE (not the app) ended the
# pod: spot/preemptible reclaim, node drain, taint eviction, shutdown.
# Any DisruptionTarget=True condition is infra-initiated; the reason set
# here is what GKE emits for TPU spot reclaim and maintenance drains.
_DISRUPTION_REASONS = frozenset(
    {
        "PreemptionByScheduler",
        "PreemptionByKubeScheduler",
        "TerminationByKubelet",
        "DeletionByTaintManager",
        "EvictionByEvictionAPI",
        "NodeShutdown",
    }
)


def _pod_disruption_reason(pods: Iterable[Mapping[str, Any]]) -> Optional[str]:
    """First node-disruption condition found across the app's pods, or None.

    GKE marks pods killed by spot reclaim / node drain with a
    ``DisruptionTarget`` condition (status=True); the reason distinguishes
    scheduler preemption from kubelet/node-shutdown termination. Pod dicts
    come from the k8s client's ``to_dict()`` (snake_case) or raw watch
    events (camelCase); both shapes are read."""
    for pod in pods:
        status = pod.get("status") or {}
        for cond in status.get("conditions") or []:
            if cond.get("type") != "DisruptionTarget":
                continue
            if str(cond.get("status")) != "True":
                continue
            return str(cond.get("reason") or "DisruptionTarget")
    return None


def classify_jobset_failure(
    jobset: Mapping[str, Any], pods: list[Mapping[str, Any]]
) -> tuple[AppState, Optional[FailureClass], str]:
    """-> (state, failure_class, note) for a FAILED JobSet.

    A JobSet reports Failed for both "the container exited 1" and "the
    spot node under it vanished"; the retry decision needs them apart.
    Node-disruption pod conditions (and preemption-shaped Failed-condition
    messages) reclassify to PREEMPTED/PREEMPTION; everything else stays
    FAILED with the conservative APP class."""
    reason = _pod_disruption_reason(pods)
    if reason is not None:
        return (
            AppState.PREEMPTED,
            FailureClass.PREEMPTION,
            f"node disruption: {reason}",
        )
    for cond in (jobset.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Failed" and cond.get("status") == "True":
            text = f"{cond.get('reason', '')} {cond.get('message', '')}"
            if re.search(r"preempt|node (was )?(deleted|shut ?down)|spot", text, re.I):
                return AppState.PREEMPTED, FailureClass.PREEMPTION, text.strip()
    return AppState.FAILED, FailureClass.APP, ""


def _role_completions(jobset: Mapping[str, Any]) -> dict[str, int]:
    """replicatedJob name -> completions (hosts per slice), from the spec."""
    out: dict[str, int] = {}
    for rj in (jobset.get("spec") or {}).get("replicatedJobs") or []:
        name = rj.get("name")
        spec = ((rj.get("template") or {}).get("spec")) or {}
        if name:
            out[str(name)] = _safe_int(spec.get("completions"), 1) or 1
    return out


def describe_jobset(
    jobset: Mapping[str, Any], pods: list[Mapping[str, Any]]
) -> DescribeAppResponse:
    state = jobset_state(jobset)
    failure_class: Optional[FailureClass] = None
    failure_note = ""
    if state == AppState.FAILED:
        state, failure_class, failure_note = classify_jobset_failure(jobset, pods)
    status = jobset.get("status") or {}
    completions = _role_completions(jobset)
    roles: dict[str, RoleStatus] = {}
    for pod in pods:
        meta = pod.get("metadata") or {}
        labels = meta.get("labels") or {}
        role = labels.get(LABEL_ROLE_NAME) or labels.get(
            "jobset.sigs.k8s.io/replicatedjob-name", "unknown"
        )
        # completions are keyed by replicatedJob name in the spec, which can
        # be a budget-truncated variant of the display role name — look up
        # via the pod's jobset-controller label, not the display name
        rj_name = labels.get("jobset.sigs.k8s.io/replicatedjob-name", str(role))
        annotations = meta.get("annotations") or {}
        host_idx = _safe_int(
            annotations.get("batch.kubernetes.io/job-completion-index")
        )
        # multi-slice: two slices' pods share completion indexes; the global
        # replica id folds in the JobSet job index (slice) when present
        slice_idx = _safe_int(
            labels.get("jobset.sigs.k8s.io/job-index")
            or annotations.get("jobset.sigs.k8s.io/job-index")
        )
        idx = slice_idx * completions.get(rj_name, 1) + host_idx
        phase = ((pod.get("status") or {}).get("phase")) or "Unknown"
        pod_ip = (pod.get("status") or {}).get("pod_ip") or (
            pod.get("status") or {}
        ).get("podIP", "")
        roles.setdefault(role, RoleStatus(role=role)).replicas.append(
            ReplicaStatus(
                id=idx,
                state=POD_STATE_MAP.get(phase, AppState.UNKNOWN),
                role=role,
                hostname=pod_ip or meta.get("name", ""),
            )
        )
    for rs in roles.values():
        rs.replicas.sort(key=lambda r: r.id)
    restarts = _safe_int(status.get("restarts"))
    return DescribeAppResponse(
        app_id=f"{jobset.get('metadata', {}).get('namespace')}:"
        f"{jobset.get('metadata', {}).get('name')}",
        state=state,
        num_restarts=restarts,
        msg=failure_note,
        roles_statuses=sorted(roles.values(), key=lambda r: r.role),
        failure_class=failure_class,
    )


def create_scheduler(session_name: str, **kwargs: Any) -> GKEScheduler:
    known = {"client", "docker_client"}
    return GKEScheduler(
        session_name=session_name,
        **{k: v for k, v in kwargs.items() if k in known},
    )
