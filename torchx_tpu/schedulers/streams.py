"""Tee — fan-in stdout+stderr files into one combined log.

Reference analog: torchx/schedulers/streams.py:16-71. A background thread
tails the two source files and appends interleaved lines to the combined
file until closed.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import IO


class Tee:
    def __init__(self, combined: Path, stdout: Path, stderr: Path) -> None:
        self._combined: IO[bytes] = open(combined, "ab")
        self._sources = [open(stdout, "rb"), open(stderr, "rb")]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        while True:
            wrote = False
            for src in self._sources:
                line = src.readline()
                while line:
                    self._combined.write(line)
                    wrote = True
                    line = src.readline()
            if wrote:
                self._combined.flush()
            if self._stop.is_set() and not wrote:
                break
            if not wrote:
                time.sleep(0.05)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # pump still draining: leave the files to it (daemon thread dies
            # with the process) rather than closing them out from under it
            return
        for src in self._sources:
            src.close()
        self._combined.close()
