"""Tee — fan-in stdout+stderr files into one combined log.

Reference analog: torchx/schedulers/streams.py:16-71. A background thread
tails the two source files and appends interleaved lines to the combined
file until closed.

Each combined line is prefixed with an epoch stamp (``<epoch.millis> ``,
the same wire format as the tpu_vm remote stamper) at the moment the Tee
observes it, which is what lets the local scheduler honor ``--since`` /
``--until`` log windows. Readers strip the stamp before display.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import IO


class Tee:
    def __init__(self, combined: Path, stdout: Path, stderr: Path) -> None:
        self._combined: IO[bytes] = open(combined, "ab")
        self._sources = [open(stdout, "rb"), open(stderr, "rb")]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        # Per-source partial-line buffers: only COMPLETE lines are stamped
        # and written, so a writer caught mid-line (progress bars, unbuffered
        # prints) never gets a stamp injected into the middle of its payload.
        partial = [b"" for _ in self._sources]
        while True:
            wrote = False
            for i, src in enumerate(self._sources):
                data = src.read()
                if not data:
                    continue
                lines = (partial[i] + data).split(b"\n")
                partial[i] = lines.pop()  # trailing partial (or b"")
                for line in lines:
                    self._combined.write(f"{time.time():.3f} ".encode())
                    self._combined.write(line + b"\n")
                    wrote = True
            if wrote:
                self._combined.flush()
            if self._stop.is_set() and not wrote:
                break
            if not wrote:
                time.sleep(0.05)
        # final drain: a process whose last write had no newline still gets
        # its tail into the combined log
        for i, tail in enumerate(partial):
            if tail:
                self._combined.write(f"{time.time():.3f} ".encode() + tail + b"\n")
        self._combined.flush()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # pump still draining: leave the files to it (daemon thread dies
            # with the process) rather than closing them out from under it
            return
        for src in self._sources:
            src.close()
        self._combined.close()
