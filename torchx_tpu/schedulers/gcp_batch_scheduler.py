"""GCP Batch scheduler: multi-node gang jobs via ``gcloud batch``.

Reference analog: torchx/schedulers/aws_batch_scheduler.py (854 LoC), which
maps AppDef -> an AWS Batch multi-node-parallel job (roles -> node-groups
with targetNodes ranges at :196-291, job registration + submit at
:500-520). The GCP-native counterpart maps AppDef -> a **Batch Job**
(batch.googleapis.com) JSON config:

* one **taskGroup per role**: ``taskCount`` = gang hosts, one task per VM
  (``taskCountPerNode: 1``), ``requireHostsFile`` + ``permissiveSsh`` for
  in-gang rendezvous — the role node-groups play in the reference;
* gang identity is derived *in the task*, not baked per-replica: Batch
  injects ``BATCH_TASK_INDEX`` (≙ the job completion index on GKE) and
  writes the taskgroup hosts file, so the bootstrap exports
  ``TPX_REPLICA_ID``/``TPX_COORDINATOR_HOST`` from those — same contract
  as every other backend (schedulers/api.py role_replica_env);
* TPU slices ride Batch's TPU-VM machine families (``ct5lp-hightpu-4t``
  etc.) via ``allocationPolicy.instances[].policy.machineType``, the role
  EFA devices + instance types play at the reference's :330-358;
* retries: ``taskSpec.maxRetryCount`` (REPLICA scope) or Batch-level task
  rescheduling; structured state from ``status.state`` +
  ``status.taskGroups[].counts``.

All gcloud calls go through ``self._run_cmd`` so tests inject canned JSON
(the reference's mock-client strategy, aws_batch_scheduler_test.py); the
job config materialization is a pure function over dicts, asserted on by
dryrun tests with no cloud.
"""

from __future__ import annotations

import json
import logging
import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu import settings
from torchx_tpu.resilience.policy import NON_IDEMPOTENT
from torchx_tpu.schedulers.api import (
    DescribeAppResponse,
    dquote as _dquote,
    safe_int as _safe_int,
    ListAppResponse,
    Scheduler,
    SchedulerCapabilities,
    Stream,
    filter_regex,
    rfc3339 as _rfc3339,
    tpu_hosts_for_role,
)
from torchx_tpu.schedulers.ids import cleanup, make_unique, sanitize_name
from torchx_tpu.schedulers.structured_opts import StructuredOpts
from torchx_tpu.specs.api import (
    AppDef,
    AppDryRunInfo,
    AppState,
    CfgVal,
    ReplicaStatus,
    Role,
    RoleStatus,
    macros,
    runopts,
)
from torchx_tpu.workspace.docker_workspace import DockerWorkspaceMixin

logger = logging.getLogger(__name__)

# TPU generation -> Batch TPU-VM machine family. ``{chips}`` is filled from
# ``TpuSlice.chips_per_host``, which is shape-dependent on v5e/v6e: a
# single-host v5litepod-8 is one ct5lp-hightpu-8t VM, while any multi-host
# v5e slice is built from ct5lp-hightpu-4t VMs (taskCount scales the hosts,
# mirroring how the GKE path scales via Indexed-Job completions).
TPU_MACHINE_TYPES = {
    "v4": "ct4p-hightpu-4t",
    "v5e": "ct5lp-hightpu-{chips}t",
    "v5p": "ct5p-hightpu-4t",
    "v6e": "ct6e-standard-{chips}t",
}

# Batch job state -> AppState (``gcloud batch jobs describe`` status.state)
BATCH_STATE_MAP: dict[str, AppState] = {
    "STATE_UNSPECIFIED": AppState.UNKNOWN,
    "QUEUED": AppState.PENDING,
    "SCHEDULED": AppState.PENDING,
    "RUNNING": AppState.RUNNING,
    "SUCCEEDED": AppState.SUCCEEDED,
    "FAILED": AppState.FAILED,
    "CANCELLATION_IN_PROGRESS": AppState.CANCELLED,
    "CANCELLED": AppState.CANCELLED,
    "DELETION_IN_PROGRESS": AppState.CANCELLED,
}

# where Batch writes the taskgroup hosts file on the VM (and where we
# mount it inside container runnables)
HOSTS_FILE = "/etc/cloudbatch-taskgroup-hosts"


@dataclass
class GCPBatchOpts(StructuredOpts):
    """Typed run config for the gcp_batch scheduler."""

    project: Optional[str] = None
    """GCP project id (defaults to the gcloud configured project)."""

    location: str = "us-central1"
    """Batch region to submit into."""

    machine_type: str = "e2-standard-4"
    """machine type for CPU roles (TPU roles derive theirs from the slice)."""



@dataclass
class GCPBatchJob:
    """Materialized request: the Batch job config + submit identifiers."""

    name: str
    location: str
    project: Optional[str]
    config: dict[str, Any]
    images_to_push: dict[str, tuple[str, str]] = field(default_factory=dict)

    def __str__(self) -> str:
        return json.dumps(self.config, indent=2, default=str)


def _bootstrap(role: Role, app_id: str, num_hosts: int) -> str:
    """Shell prelude every task runs: derive the gang identity the other
    backends inject as env (role_replica_env) from Batch's own variables,
    then exec the role's entrypoint."""
    env_lines = [
        f"export {settings.ENV_TPX_APP_ID}={shlex.quote(app_id)}",
        f"export {settings.ENV_TPX_ROLE_NAME}={shlex.quote(role.name)}",
        f'export {settings.ENV_TPX_REPLICA_ID}="${{BATCH_TASK_INDEX:-0}}"',
        f"export {settings.ENV_TPX_NUM_REPLICAS}={num_hosts}",
        # rendezvous: host 0 of the taskgroup (first line of the hosts
        # file Batch writes when requireHostsFile is set)
        f'export {settings.ENV_TPX_COORDINATOR_HOST}="$(head -n1 {HOSTS_FILE}'
        ' 2>/dev/null | cut -d" " -f1)"',
        f'[ -n "${settings.ENV_TPX_COORDINATOR_HOST}" ] ||'
        f" export {settings.ENV_TPX_COORDINATOR_HOST}=localhost",
        f"export {settings.ENV_TPX_ERROR_FILE}=/tmp/tpx_error.json",
    ]
    for k, v in sorted(role.env.items()):
        env_lines.append(f"export {k}={_dquote(v)}")
    cmd = " ".join(_dquote(a) for a in [role.entrypoint, *role.args])
    return "\n".join([*env_lines, f"exec {cmd}"])


def role_to_task_group(role: Role, app_id: str) -> dict[str, Any]:
    """One role -> one Batch taskGroup (reference: role -> node-group,
    aws_batch_scheduler.py:196-291)."""
    tpu = role.resource.tpu if role.resource is not None else None
    num_hosts = tpu_hosts_for_role(role)

    values = macros.Values(
        img_root="",
        app_id=app_id,
        # the bootstrap exports the derived id before exec'ing, and args
        # are double-quoted so the reference expands at runtime
        replica_id=f"${settings.ENV_TPX_REPLICA_ID}",
        num_replicas=str(num_hosts),
        coordinator_env=settings.ENV_TPX_COORDINATOR_HOST,
    )
    srole = values.apply(role)
    script = _bootstrap(srole, app_id, num_hosts)

    runnable: dict[str, Any]
    if srole.image:
        runnable = {
            "container": {
                "imageUri": srole.image,
                "entrypoint": "/bin/sh",
                "commands": ["-c", script],
                # the hosts file lives on the VM; containers need it for
                # coordinator derivation
                "volumes": [f"{HOSTS_FILE}:{HOSTS_FILE}:ro"],
            }
        }
    else:
        runnable = {"script": {"text": script}}

    task_spec: dict[str, Any] = {
        "runnables": [runnable],
        "maxRetryCount": srole.max_retries,
    }
    if role.resource is not None and not tpu:
        task_spec["computeResource"] = {
            "cpuMilli": int(role.resource.cpu * 1000),
            "memoryMib": role.resource.memMB,
        }

    group: dict[str, Any] = {
        "taskSpec": task_spec,
        "taskCount": num_hosts,
        "parallelism": num_hosts,  # gang: all hosts at once
        "taskCountPerNode": 1,
        # in-gang rendezvous surface (hosts file + ssh between tasks)
        "requireHostsFile": True,
        "permissiveSsh": True,
    }
    return group


def app_to_batch_job(
    app: AppDef, app_id: str, opts: GCPBatchOpts
) -> dict[str, Any]:
    """AppDef -> Batch Job config dict (pure; dryrun tests assert on it).

    Single-role apps only: the Batch API accepts exactly one taskGroup per
    job and honors one instance policy — multi-role apps belong on the GKE
    backend (same constraint and guidance as tpu_vm)."""
    if len(app.roles) != 1:
        raise ValueError(
            f"gcp_batch supports single-role apps (a Batch job is one"
            f" taskGroup); app {app.name!r} has {len(app.roles)} roles —"
            " use the gke backend for multi-role apps"
        )
    (role,) = app.roles
    task_group = role_to_task_group(role, app_id)
    tpu = role.resource.tpu if role.resource is not None else None
    if tpu:
        family = TPU_MACHINE_TYPES.get(tpu.accelerator)
        if family is None:
            raise ValueError(
                f"no Batch TPU-VM machine family for {tpu.accelerator!r};"
                f" known: {sorted(TPU_MACHINE_TYPES)}"
            )
        machine = family.format(chips=tpu.chips_per_host)
    else:
        # per-role machine pin (heterogeneous catalog) beats the run cfg
        caps = role.resource.capabilities if role.resource is not None else {}
        machine = str(caps.get("gce.machine_type") or opts.machine_type)

    labels = {
        "tpx-app-name": app_id,
        "tpx-role-name": sanitize_name(role.name, max_len=63),
    }
    config: dict[str, Any] = {
        "taskGroups": [task_group],
        "allocationPolicy": {
            "instances": [{"policy": {"machineType": machine}}],
            "labels": dict(labels),
        },
        "labels": dict(labels),
        "logsPolicy": {"destination": "CLOUD_LOGGING"},
    }
    return config


def describe_batch_job(
    name: str, payload: Mapping[str, Any], roles: list[str]
) -> DescribeAppResponse:
    """Map a ``gcloud batch jobs describe`` JSON payload onto AppStatus
    (pure; fixture-testable like describe_jobset)."""
    status = payload.get("status") or {}
    state = BATCH_STATE_MAP.get(str(status.get("state", "")), AppState.UNKNOWN)
    roles_statuses = []
    group_status = status.get("taskGroups") or {}
    for i, role_name in enumerate(roles):
        counts = (group_status.get(f"group{i}") or {}).get("counts") or {}
        replicas = []
        idx = 0
        for batch_state, n in counts.items():
            mapped = BATCH_STATE_MAP.get(batch_state, AppState.UNKNOWN)
            for _ in range(_safe_int(n)):
                replicas.append(
                    ReplicaStatus(
                        id=idx, role=role_name, state=mapped, hostname=""
                    )
                )
                idx += 1
        roles_statuses.append(RoleStatus(role=role_name, replicas=replicas))
    return DescribeAppResponse(
        app_id=name, state=state, roles_statuses=roles_statuses
    )


# Feature profile for the preflight analyzer (torchx_tpu.analyze): Batch
# jobs are single-role (one taskGroup), honor maxRetryCount natively, and
# build concrete machine requests from cpu/memMB.
CAPABILITIES = SchedulerCapabilities(
    mounts=False,
    multi_role=False,
    multislice=False,
    delete=True,
    resize=False,
    logs=True,
    native_retries=True,
    concrete_resources=True,
    classifies_preemption=False,
)


class GCPBatchScheduler(DockerWorkspaceMixin, Scheduler[GCPBatchJob]):
    """Submits AppDefs as GCP Batch jobs through the gcloud CLI."""

    capabilities = CAPABILITIES

    # since/until become server-side Cloud Logging timestamp filters
    supports_log_windows = True

    def __init__(self, session_name: str, docker_client: Optional[Any] = None) -> None:
        super().__init__(
            docker_client=docker_client,
            backend="gcp_batch",
            session_name=session_name,
        )
        # last-submitted run cfg; list() reuses it for project/location scope
        self._session_opts: Optional[GCPBatchOpts] = None

    def _run_cmd(self, cmd: list[str], **kwargs: Any) -> subprocess.CompletedProcess:
        """Raw gcloud subprocess seam (tests monkeypatch this); call sites
        go through :meth:`Scheduler._cmd` for deadlines, classified
        retries, and the backend breaker."""
        return subprocess.run(cmd, capture_output=True, text=True, **kwargs)

    def run_opts(self) -> runopts:
        return GCPBatchOpts.to_runopts() | self.workspace_opts()

    def _gcloud(self, opts_or_job: Any, *args: str) -> list[str]:
        cmd = ["gcloud", "batch", "jobs", *args]
        cmd += ["--location", opts_or_job.location]
        if opts_or_job.project:
            cmd += ["--project", opts_or_job.project]
        return cmd

    # -- dryrun / schedule -------------------------------------------------

    def _submit_dryrun(
        self, app: AppDef, cfg: Mapping[str, CfgVal]
    ) -> AppDryRunInfo[GCPBatchJob]:
        opts = GCPBatchOpts.from_cfg(cfg)
        # Batch job ids and label values cap at 63 chars (hash-suffix
        # truncation keeps derived strings stable, same as the GKE budget)
        app_id = sanitize_name(make_unique(app.name), max_len=60)
        images_to_push = self.dryrun_push_images(app, cfg)
        config = app_to_batch_job(app, app_id, opts)
        req = GCPBatchJob(
            name=app_id,
            location=opts.location,
            project=opts.project,
            config=config,
            images_to_push=images_to_push,
        )
        return AppDryRunInfo(req)

    def schedule(self, dryrun_info: AppDryRunInfo[GCPBatchJob]) -> str:
        req = dryrun_info.request
        # remember where this session actually submits, for list() scoping
        # (set here, not in dryrun: a dryrun that is never scheduled must
        # not retarget list())
        self._session_opts = GCPBatchOpts(
            project=req.project, location=req.location
        )
        self.push_images(req.images_to_push)
        proc = self._cmd(
            self._gcloud(req, "submit", req.name, "--config", "-"),
            op="submit",
            policy=NON_IDEMPOTENT,
            input=json.dumps(req.config),
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"gcloud batch jobs submit failed: {proc.stderr.strip()}"
            )
        # durable scope registry: a FRESH process's list() must still query
        # the project/location this job went to (slurm job-dir pattern).
        # Resolve the gcloud default NOW so the stored scope is canonical —
        # storing None would later dedupe against explicit-project scopes
        # as if they were different projects (duplicate list() rows)
        scope_project = req.project or self._gcloud_project()
        _record_scope(scope_project, req.location)
        # a successful submit proves the scope live again: un-evict it
        _note_scope_result(scope_project, req.location, ok=True)
        if req.project:
            return f"{req.project}:{req.location}:{req.name}"
        return f"{req.location}:{req.name}"

    # -- monitoring --------------------------------------------------------

    @dataclass
    class _Id:
        location: str
        name: str
        project: Optional[str] = None

    @staticmethod
    def _parse_app_id(app_id: str) -> "GCPBatchScheduler._Id":
        """``location:name`` or ``project:location:name`` (the project
        prefix is minted at schedule() time when a project cfg was given,
        so every later verb targets the right project)."""
        parts = app_id.split(":")
        if len(parts) == 2 and all(parts):
            return GCPBatchScheduler._Id(location=parts[0], name=parts[1])
        if len(parts) == 3 and all(parts):
            return GCPBatchScheduler._Id(
                project=parts[0], location=parts[1], name=parts[2]
            )
        raise ValueError(
            f"invalid gcp_batch app id {app_id!r}; expected"
            " [project:]location:name"
        )

    def _describe_json(self, job: "GCPBatchScheduler._Id") -> Optional[dict]:
        """Raw ``gcloud batch jobs describe`` payload, or None when the job
        is unknown / the output is unparseable (shared by describe and the
        log-UID resolution)."""
        proc = self._cmd(
            self._gcloud(job, "describe", job.name, "--format", "json"),
            op="describe",
        )
        if proc.returncode != 0:
            return None
        try:
            payload = json.loads(proc.stdout or "{}")
        except json.JSONDecodeError:
            return None
        return payload if isinstance(payload, dict) else None

    def describe(self, app_id: str) -> Optional[DescribeAppResponse]:
        job = self._parse_app_id(app_id)
        payload = self._describe_json(job)
        if payload is None:
            return None
        # single-role jobs: the real role name rides the job label we set
        # at materialization (Batch taskGroups carry no names)
        role_name = (payload.get("labels") or {}).get("tpx-role-name") or "role0"
        return describe_batch_job(app_id, payload, [role_name])

    def list(self) -> list[ListAppResponse]:
        # Batch listing is location-scoped but list() takes no cfg: union
        # every scope this USER ever submitted to (durable registry, so a
        # fresh CLI process still finds explicit-project jobs) plus the
        # session's last-submitted scope, falling back to the
        # gcloud-configured project + default location when neither exists.
        default_project = self._gcloud_project()
        raw: list[tuple[Optional[str], str]] = []
        if self._session_opts is not None:
            raw.append(
                (self._session_opts.project, self._session_opts.location)
            )
        # eviction filters HERE, not in _known_scopes(): _record_scope
        # uses _known_scopes() as its already-durable check, and an
        # evicted-but-recorded scope must not be re-appended on resubmit
        raw.extend(
            sorted(
                _known_scopes() - _evicted_scopes(),
                key=lambda s: (s[0] or "", s[1]),
            )
        )
        if default_project is not None and (
            default_project,
            GCPBatchOpts.location,
        ) not in _evicted_scopes():
            # default-project jobs (submitted by gcloud directly or by a
            # pre-registry version) must not vanish once any scope exists
            # — but a default scope that keeps failing (revoked project)
            # sits out like any other evicted scope
            raw.append((default_project, GCPBatchOpts.location))
        scopes: list[tuple[Optional[str], str]] = []
        for project, location in raw:
            scope = (project or default_project, location)
            if scope not in scopes:
                scopes.append(scope)
        if not scopes:
            scopes.append((default_project, GCPBatchOpts.location))
        out: list[ListAppResponse] = []
        seen: set[str] = set()
        for project, location in scopes:
            opts = GCPBatchOpts(project=project, location=location)
            proc = self._cmd(
                self._gcloud(opts, "list", "--format", "json"), op="list"
            )
            _note_scope_result(project, location, proc.returncode == 0)
            if proc.returncode != 0:
                continue
            try:
                jobs = json.loads(proc.stdout or "[]")
            except json.JSONDecodeError:
                continue
            # mint ids with the project prefix when known, so describe/
            # cancel/log on a listed id target the same project list()
            # queried
            prefix = f"{project}:{location}" if project else location
            for j in jobs:
                name = str(j.get("name", "")).rsplit("/", 1)[-1]
                app_id = f"{prefix}:{name}"
                if app_id in seen:
                    continue
                seen.add(app_id)
                state = BATCH_STATE_MAP.get(
                    str((j.get("status") or {}).get("state", "")),
                    AppState.UNKNOWN,
                )
                out.append(
                    ListAppResponse(app_id=app_id, state=state, name=name)
                )
        return out

    def _gcloud_project(self) -> Optional[str]:
        """The gcloud-configured default project, or None."""
        proc = self._cmd(["gcloud", "config", "get-value", "project"], op="config")
        if proc.returncode != 0:
            return None
        val = (proc.stdout or "").strip()
        return val if val and val != "(unset)" else None

    def _cancel_existing(self, app_id: str) -> None:
        job = self._parse_app_id(app_id)
        proc = self._cmd(
            self._gcloud(job, "cancel", job.name, "--quiet"), op="cancel"
        )
        if proc.returncode != 0:
            # older gcloud has no `cancel`; deletion also stops the job
            self._cmd(
                self._gcloud(job, "delete", job.name, "--quiet"), op="cancel"
            )

    def delete(self, app_id: str) -> None:
        job = self._parse_app_id(app_id)
        self._cmd(self._gcloud(job, "delete", job.name, "--quiet"), op="delete")

    def log_iter(
        self,
        app_id: str,
        role_name: str,
        k: int = 0,
        regex: Optional[str] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        should_tail: bool = False,
        streams: Optional[Stream] = None,
    ) -> Iterable[str]:
        """Cloud Logging fetch (the CloudWatch analog of the reference's
        aws_batch log_iter); no tail, single page of recent entries.
        since/until map to server-side ``timestamp`` filters; Batch keeps
        one combined log per task, so stream selection raises."""
        if streams not in (None, Stream.COMBINED):
            raise ValueError(
                f"gcp_batch task logs are a single combined Cloud Logging"
                f" stream; selecting {streams} is not supported"
            )
        job = self._parse_app_id(app_id)
        # Batch stamps log entries with the server-generated job UID, not
        # the submitted job id — resolve it via describe first
        payload = self._describe_json(job)
        uid = (payload or {}).get("uid") or job.name
        filt = (
            f'labels.job_uid="{uid}" AND '
            f'labels.task_index="{k}"'
        )
        if since is not None:
            filt += f' AND timestamp>="{_rfc3339(since)}"'
        if until is not None:
            filt += f' AND timestamp<="{_rfc3339(until)}"'
        cmd = [
            "gcloud",
            "logging",
            "read",
            filt,
            "--format",
            "json",
            "--order",
            "asc",
        ]
        if job.project:
            cmd += ["--project", job.project]
        proc = self._cmd(cmd, op="logs")
        if proc.returncode != 0:
            raise RuntimeError(
                f"gcloud logging read failed: {proc.stderr.strip()}"
            )
        try:
            entries = json.loads(proc.stdout or "[]")
        except json.JSONDecodeError:
            entries = []
        lines = (str(e.get("textPayload", "")).rstrip("\n") for e in entries)
        if regex:
            lines = filter_regex(regex, lines)
        return lines


# -- durable scope registry ---------------------------------------------
# one line per DISTINCT submitted scope (``scope = project|location``) in
# the user's home dir, the slurm ``.tpxslurmjobdirs`` pattern: list()
# from a fresh process unions these scopes instead of falling back to the
# gcloud default and missing explicit-project jobs

GCP_BATCH_SCOPES_FILE = ".tpxgcpbatchscopes"


def _scopes_path() -> str:
    import os

    return os.path.join(os.path.expanduser("~"), GCP_BATCH_SCOPES_FILE)


def _dedup_keeper() -> Any:
    """Compaction predicate: keep the first line per distinct scope value
    (staleness can't be probed without gcloud, but duplicates can go)."""
    seen: set[str] = set()

    def keep(value: str) -> bool:
        if value in seen:
            return False
        seen.add(value)
        return True

    return keep


def _record_scope(project: Optional[str], location: str) -> None:
    if (project or None, location) in _known_scopes():
        return  # already durable; keep the file at one line per scope
    from torchx_tpu.util import registry

    registry.record(
        _scopes_path(),
        "scope",
        f"{project or ''}|{location}",
        keep=_dedup_keeper(),
    )


def _known_scopes() -> set[tuple[Optional[str], str]]:
    from torchx_tpu.util import registry

    out: set[tuple[Optional[str], str]] = set()
    for _, value in registry.entries(_scopes_path()):
        project, sep, location = value.partition("|")
        if sep and location:
            out.add((project or None, location))
    return out


# -- scope failure tracking / eviction ----------------------------------
# A recorded scope whose project was deleted or revoked would otherwise
# add one failing gcloud subprocess to EVERY list() forever (advisor r4).
# The bookkeeping is one instance of the shared durable-breaker primitive
# (:class:`torchx_tpu.resilience.breaker.FailureLedger`): each failed
# list per scope counts one unbroken failure, a successful list (or a new
# submit to the scope) clears the streak, and a scope at the threshold is
# skipped by list() until it succeeds again via submit. The file name and
# format predate the primitive and are kept for compatibility.

GCP_BATCH_SCOPE_FAILS_FILE = ".tpxgcpbatchscopefails"
SCOPE_EVICT_FAILURES = 3


def _fails_path() -> str:
    import os

    return os.path.join(os.path.expanduser("~"), GCP_BATCH_SCOPE_FAILS_FILE)


def _scope_key(project: Optional[str], location: str) -> str:
    return f"{project or ''}|{location}"


def _scope_ledger() -> "FailureLedger":
    from torchx_tpu.resilience.breaker import FailureLedger

    return FailureLedger(_fails_path(), threshold=SCOPE_EVICT_FAILURES)


def _scope_failures() -> dict[str, int]:
    return _scope_ledger().failures()


def _note_scope_result(project: Optional[str], location: str, ok: bool) -> None:
    """Best-effort failure bookkeeping (a lost concurrent update costs at
    most one miscounted failure, which the next list corrects)."""
    _scope_ledger().note(_scope_key(project, location), ok)


def _evicted_scopes() -> set[tuple[Optional[str], str]]:
    out: set[tuple[Optional[str], str]] = set()
    for key in _scope_ledger().tripped():
        project, sep, location = key.partition("|")
        if sep and location:
            out.add((project or None, location))
    return out


def create_scheduler(session_name: str, **kwargs: Any) -> GCPBatchScheduler:
    return GCPBatchScheduler(session_name, **kwargs)
