"""Unique app-id generation (reference analog: torchx/schedulers/ids.py)."""

from __future__ import annotations

import random
import string

_ALPHABET = string.ascii_lowercase + string.digits  # base-36


def random_id(length: int = 13) -> str:
    return "".join(random.choices(_ALPHABET, k=length))


def make_unique(name: str) -> str:
    """``trainer`` -> ``trainer-d8se6kyiptu2a`` (collision-safe suffix)."""
    return f"{cleanup(name)}-{random_id()}"


def cleanup(name: str) -> str:
    """Normalize to DNS-1123-ish: lowercase alphanumerics and dashes."""
    from torchx_tpu.util.strings import normalize_str

    return normalize_str(name, max_len=10_000) or "app"


def sanitize_name(name: str, max_len: int = 53) -> str:
    """DNS-1123-ish identifier shortened to ``max_len``: truncation appends
    a suffix derived from a *hash* of the full name so repeated calls
    agree — any derived strings (selectors, DNS names, labels) resolve to
    the same value. Shared by the gke (pod-name budget) and gcp_batch
    (63-char job-id/label cap) schedulers."""
    import hashlib

    name = cleanup(name)
    if len(name) > max_len:
        digest = hashlib.sha1(name.encode()).hexdigest()[:5]
        name = name[: max_len - 6].rstrip("-") + "-" + digest
    return name
