"""Unique app-id generation (reference analog: torchx/schedulers/ids.py)."""

from __future__ import annotations

import random
import string

_ALPHABET = string.ascii_lowercase + string.digits  # base-36


def random_id(length: int = 13) -> str:
    return "".join(random.choices(_ALPHABET, k=length))


def make_unique(name: str) -> str:
    """``trainer`` -> ``trainer-d8se6kyiptu2a`` (collision-safe suffix)."""
    return f"{cleanup(name)}-{random_id()}"


def cleanup(name: str) -> str:
    """Normalize to DNS-1123-ish: lowercase alphanumerics and dashes."""
    from torchx_tpu.util.strings import normalize_str

    return normalize_str(name, max_len=10_000) or "app"
