"""Scheduler registry: name -> deferred factory.

Reference analog: torchx/schedulers/__init__.py:16-68. The first entry is
the default scheduler; plugins can override the whole table.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable

from torchx_tpu.schedulers.api import Scheduler

SchedulerFactory = Callable[..., Scheduler]

# name -> "module:function". Order matters: first is the default.
DEFAULT_SCHEDULER_MODULES: dict[str, str] = {
    "local": "torchx_tpu.schedulers.local_scheduler:create_scheduler",
    "gke": "torchx_tpu.schedulers.gke_scheduler:create_scheduler",
    "slurm": "torchx_tpu.schedulers.slurm_scheduler:create_scheduler",
    "local_docker": "torchx_tpu.schedulers.docker_scheduler:create_scheduler",
    "tpu_vm": "torchx_tpu.schedulers.tpu_vm_scheduler:create_scheduler",
    "vertex": "torchx_tpu.schedulers.vertex_scheduler:create_scheduler",
    "gcp_batch": "torchx_tpu.schedulers.gcp_batch_scheduler:create_scheduler",
}


def _deferred(module_fn: str) -> SchedulerFactory:
    def factory(session_name: str, **kwargs: Any) -> Scheduler:
        mod_name, _, fn_name = module_fn.partition(":")
        mod = importlib.import_module(mod_name)
        return getattr(mod, fn_name)(session_name=session_name, **kwargs)

    return factory


def get_scheduler_factories(
    skip_defaults: bool = False,
) -> dict[str, SchedulerFactory]:
    """Name -> factory for every backend: the built-in seven (deferred
    imports) plus plugin-registered ones, which override by name."""
    factories: dict[str, SchedulerFactory] = {}
    if not skip_defaults:
        factories = {k: _deferred(v) for k, v in DEFAULT_SCHEDULER_MODULES.items()}
    try:
        from torchx_tpu.plugins import get_plugin_schedulers

        factories.update(get_plugin_schedulers())
    except ImportError:
        pass
    return factories


def get_default_scheduler_name() -> str:
    """The first registered backend ("local"), the CLI's default."""
    return next(iter(DEFAULT_SCHEDULER_MODULES))
