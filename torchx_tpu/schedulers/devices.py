"""Named-device -> mount mapping.

Reference analog: torchx/schedulers/devices.py:17-54 — translates the
named devices in ``Resource.devices`` (e.g. the EFA NIC on AWS) into the
DeviceMounts a container backend needs. For TPU roles the docker backend
mounts the host's accel nodes via :func:`local_tpu_device_mounts` (keyed
on ``Resource.tpu``, not the devices dict); the mapping table covers
named host devices like GPUs on mixed clusters.
"""

from __future__ import annotations

import logging
from typing import Callable

from torchx_tpu.specs.api import DeviceMount

logger = logging.getLogger(__name__)


def _nvidia_mounts(count: int) -> list[DeviceMount]:
    return [
        DeviceMount(src_path=f"/dev/nvidia{i}", dst_path=f"/dev/nvidia{i}")
        for i in range(count)
    ] + [
        DeviceMount(src_path="/dev/nvidiactl", dst_path="/dev/nvidiactl"),
        DeviceMount(src_path="/dev/nvidia-uvm", dst_path="/dev/nvidia-uvm"),
    ]


# NOTE: TPU chips are NOT named devices (Resource.tpu owns them; see
# specs/api.py Resource.devices contract) — the docker backend mounts them
# via local_tpu_device_mounts() keyed on Resource.tpu instead.
DEVICE_MAPPINGS: dict[str, Callable[[int], list[DeviceMount]]] = {
    "nvidia.com/gpu": _nvidia_mounts,
}


def local_tpu_device_mounts() -> list[DeviceMount]:
    """Mounts for whatever accel chips THIS host actually has (used by the
    docker scheduler for TPU roles, where the slice is the host's chips).
    Covers both exposure modes the local scheduler counts: /dev/accel*
    and vfio (/dev/vfio/N + the container's /dev/vfio/vfio control node)."""
    import glob

    nodes = sorted(glob.glob("/dev/accel*"))
    vfio = sorted(glob.glob("/dev/vfio/[0-9]*"))
    if not nodes and vfio:
        nodes = ["/dev/vfio/vfio", *vfio]
    return [DeviceMount(src_path=dev, dst_path=dev) for dev in nodes]


def get_device_mounts(devices: dict[str, int]) -> list[DeviceMount]:
    """Resource.devices -> DeviceMounts; unknown names warn and skip
    (backends that understand them natively, like k8s resource limits,
    consume them from Resource.devices directly)."""
    mounts: list[DeviceMount] = []
    for name, count in devices.items():
        mapper = DEVICE_MAPPINGS.get(name)
        if mapper is None:
            logger.warning("no device mount mapping for %r; skipping", name)
            continue
        mounts.extend(mapper(count))
    return mounts
