"""Programmatic plugin registration decorators.

Reference analog: torchx/plugins/_registration.py (434 LoC):

    from torchx_tpu.plugins import register

    @register.scheduler("mysched", alias="ms")
    def create_scheduler(session_name, **kwargs): ...

    @register.named_resource("superpod", fractions=True)
    def superpod() -> Resource: ...

    @register.tracker("mytracker")
    def create_tracker(config): ...

``fractions=True`` on a TPU named resource additionally registers
``<name>_half`` / ``<name>_quarter`` variants whose slices hold half /
quarter of the chips (the TPU analog of the reference's fractional-GPU
shares, _registration.py:36-52): on a multi-tenant TPU-VM host, replicas
with fractional resources share the host's chips via TPU_VISIBLE_CHIPS
partitioning.
"""

from __future__ import annotations

import copy
import enum
from typing import Any, Callable, Optional

_SCHEDULERS: dict[str, Callable[..., Any]] = {}
_NAMED_RESOURCES: dict[str, Callable[[], Any]] = {}
_TRACKERS: dict[str, Callable[[Optional[str]], Any]] = {}


class Share(enum.Enum):
    WHOLE = 1
    HALF = 2
    QUARTER = 4


def _fractional(factory: Callable[[], Any], share: Share) -> Callable[[], Any]:
    def fraction() -> Any:
        from torchx_tpu.specs.api import Resource, TpuSlice

        res: Resource = copy.deepcopy(factory())
        divisor = share.value
        res.cpu = max(1, int(res.cpu // divisor))
        res.memMB = max(1, res.memMB // divisor)
        if res.tpu is not None and res.tpu.chips >= divisor:
            res.tpu = TpuSlice(
                accelerator=res.tpu.accelerator,
                chips=res.tpu.chips // divisor,
            )
        res.tags["tpx.share"] = share.name.lower()
        return res

    return fraction


class register:
    """Decorator namespace (used as ``@register.scheduler(...)``)."""

    @staticmethod
    def scheduler(
        name: str, alias: Optional[str] = None
    ) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
        def deco(factory: Callable[..., Any]) -> Callable[..., Any]:
            _SCHEDULERS[name] = factory
            if alias:
                _SCHEDULERS[alias] = factory
            return factory

        return deco

    @staticmethod
    def named_resource(
        name: str, alias: Optional[str] = None, fractions: bool = False
    ) -> Callable[[Callable[[], Any]], Callable[[], Any]]:
        def deco(factory: Callable[[], Any]) -> Callable[[], Any]:
            _NAMED_RESOURCES[name] = factory
            if alias:
                _NAMED_RESOURCES[alias] = factory
            if fractions:
                _NAMED_RESOURCES[f"{name}_half"] = _fractional(factory, Share.HALF)
                _NAMED_RESOURCES[f"{name}_quarter"] = _fractional(
                    factory, Share.QUARTER
                )
            return factory

        return deco

    @staticmethod
    def tracker(
        name: str, alias: Optional[str] = None
    ) -> Callable[[Callable[[Optional[str]], Any]], Callable[[Optional[str]], Any]]:
        def deco(factory: Callable[[Optional[str]], Any]) -> Callable[[Optional[str]], Any]:
            _TRACKERS[name] = factory
            if alias:
                _TRACKERS[alias] = factory
            return factory

        return deco


def clear_registrations() -> None:
    """Test helper: reset programmatic registrations."""
    _SCHEDULERS.clear()
    _NAMED_RESOURCES.clear()
    _TRACKERS.clear()
