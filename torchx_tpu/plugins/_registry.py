"""Plugin discovery registry.

Reference analog: torchx/plugins/_registry.py (552 LoC). Plugins extend the
launcher with schedulers, named resources, and trackers through two
discovery sources:

1. **Entry points** — groups ``tpx.schedulers``, ``tpx.named_resources``,
   ``tpx.trackers``: each entry loads to a factory (schedulers/trackers) or
   a mapping-returning function (named resources).
2. **Namespace packages** — any importable ``tpx_plugins.<name>`` module
   whose module-level ``register(registry)`` function is called with a
   :class:`PluginRegistrar` to register programmatically (supports implicit
   namespace dirs on sys.path).

$TPX_PLUGINS_SOURCE is a bitmask enabling sources (1 = entry points,
2 = namespace packages; default 3 = both; 0 disables plugins entirely).
Discovery is lazy and cached; a failing plugin is captured — with its
traceback — into the error report rather than breaking the CLI.
"""

from __future__ import annotations

import enum
import importlib
import logging
import os
import pkgutil
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional

from torchx_tpu import settings

logger = logging.getLogger(__name__)

NAMESPACE_PACKAGE = "tpx_plugins"

EP_GROUP_SCHEDULERS = "tpx.schedulers"
EP_GROUP_NAMED_RESOURCES = "tpx.named_resources"
EP_GROUP_TRACKERS = "tpx.trackers"


class PluginType(enum.Enum):
    SCHEDULER = "scheduler"
    NAMED_RESOURCE = "named_resource"
    TRACKER = "tracker"


class PluginSource(enum.IntFlag):
    ENTRY_POINTS = 1
    NAMESPACE = 2
    ALL = 3


@dataclass
class PluginError:
    plugin: str
    error: str
    tb: str


@dataclass
class _Registry:
    schedulers: dict[str, Callable[..., Any]] = field(default_factory=dict)
    named_resources: dict[str, Callable[[], Any]] = field(default_factory=dict)
    trackers: dict[str, Callable[[Optional[str]], Any]] = field(default_factory=dict)
    errors: list[PluginError] = field(default_factory=list)


class PluginRegistrar:
    """Handed to namespace-package ``register(registrar)`` hooks."""

    def __init__(self, registry: _Registry) -> None:
        self._registry = registry

    def scheduler(self, name: str, factory: Callable[..., Any]) -> None:
        self._registry.schedulers[name] = factory

    def named_resource(self, name: str, factory: Callable[[], Any]) -> None:
        self._registry.named_resources[name] = factory

    def tracker(self, name: str, factory: Callable[[Optional[str]], Any]) -> None:
        self._registry.trackers[name] = factory


_registry: Optional[_Registry] = None


def _enabled_sources() -> PluginSource:
    raw = os.environ.get(settings.ENV_TPX_PLUGINS_SOURCE)
    if raw is None:
        return PluginSource.ALL
    try:
        return PluginSource(int(raw))
    except ValueError:
        logger.warning("bad %s=%r; using ALL", settings.ENV_TPX_PLUGINS_SOURCE, raw)
        return PluginSource.ALL


def _capture(registry: _Registry, plugin: str, e: Exception) -> None:
    registry.errors.append(
        PluginError(plugin=plugin, error=str(e), tb=traceback.format_exc())
    )
    logger.warning("plugin %s failed to load: %s", plugin, e)


def _discover_entry_points(registry: _Registry) -> None:
    try:
        from importlib.metadata import entry_points
    except ImportError:  # pragma: no cover
        return
    for group, target in (
        (EP_GROUP_SCHEDULERS, registry.schedulers),
        (EP_GROUP_NAMED_RESOURCES, registry.named_resources),
        (EP_GROUP_TRACKERS, registry.trackers),
    ):
        try:
            eps = entry_points(group=group)
        except Exception as e:  # noqa: BLE001
            _capture(registry, group, e)
            continue
        for ep in eps:
            try:
                loaded = ep.load()
                if group == EP_GROUP_NAMED_RESOURCES and ep.name.endswith(
                    "named_resources"
                ):
                    # catalog convention: an entry named *named_resources
                    # returns a mapping of many factories. Other entries are
                    # single-resource factories and are NOT invoked at
                    # discovery time (they may probe their environment).
                    result = loaded()
                    if isinstance(result, Mapping):
                        registry.named_resources.update(result)
                    else:
                        raise TypeError(
                            f"{ep.name} must return a mapping of factories"
                        )
                else:
                    target[ep.name] = loaded
            except Exception as e:  # noqa: BLE001
                _capture(registry, f"{group}:{ep.name}", e)


def _discover_namespace(registry: _Registry) -> None:
    try:
        ns = importlib.import_module(NAMESPACE_PACKAGE)
    except ImportError:
        return
    registrar = PluginRegistrar(registry)
    paths = list(getattr(ns, "__path__", []))
    for info in pkgutil.iter_modules(paths, NAMESPACE_PACKAGE + "."):
        try:
            module = importlib.import_module(info.name)
            register = getattr(module, "register", None)
            if callable(register):
                register(registrar)
        except Exception as e:  # noqa: BLE001
            _capture(registry, info.name, e)


def get_registry(invalidate_cache: bool = False) -> _Registry:
    global _registry
    if _registry is not None and not invalidate_cache:
        return _registry
    registry = _Registry()
    sources = _enabled_sources()
    if sources & PluginSource.ENTRY_POINTS:
        _discover_entry_points(registry)
    if sources & PluginSource.NAMESPACE:
        _discover_namespace(registry)
    # programmatic registrations (decorators) always apply
    from torchx_tpu.plugins import _registration

    registry.schedulers.update(_registration._SCHEDULERS)
    registry.named_resources.update(_registration._NAMED_RESOURCES)
    registry.trackers.update(_registration._TRACKERS)
    _registry = registry
    if invalidate_cache:
        # downstream caches merged from this registry must refresh too
        try:
            from torchx_tpu.specs import invalidate_named_resources_cache

            invalidate_named_resources_cache()
        except ImportError:
            pass
    return registry


def error_report() -> str:
    """Human-readable report of plugin load failures (YAML-ish)."""
    lines = []
    for err in get_registry().errors:
        lines.append(f"- plugin: {err.plugin}")
        lines.append(f"  error: {err.error}")
    return "\n".join(lines) or "no plugin errors"
