"""Plugin system public interface.

Reference analog: torchx/plugins/__init__.py. Consumed by:

* ``torchx_tpu.schedulers.get_scheduler_factories`` (scheduler plugins),
* ``torchx_tpu.specs.named_resources`` (named-resource plugins),
* ``torchx_tpu.tracker.api`` (tracker plugins).
"""

from typing import Any, Callable, Mapping, Optional

from torchx_tpu.plugins._registration import Share, register  # noqa: F401
from torchx_tpu.plugins._registry import (  # noqa: F401
    PluginRegistrar,
    PluginSource,
    PluginType,
    error_report,
    get_registry,
)


def get_plugin_schedulers() -> Mapping[str, Callable[..., Any]]:
    """Scheduler factories registered by plugins, keyed by backend name
    (override built-ins of the same name)."""
    return dict(get_registry().schedulers)


def get_plugin_named_resources() -> Mapping[str, Callable[[], Any]]:
    """Named-resource factories registered by plugins."""
    return dict(get_registry().named_resources)


def get_plugin_trackers() -> Mapping[str, Callable[[Optional[str]], Any]]:
    """Tracker factories registered by plugins (config-string arg)."""
    return dict(get_registry().trackers)
