"""Synthetic and replayed job traces for the simulator.

Two sources, one shape — a list of job dicts (``job``, ``arrival``,
``klass``, ``tenant``, ``replicas``, ``duration``, ``elastic``) sorted
by arrival:

* :func:`diurnal_trace` — seeded Poisson arrivals whose rate follows a
  diurnal sine (one peak per horizon), the generalization of the
  original ``scripts/bench_fleet.py`` generator with a ``rate_scale``
  knob for 10x-fleet runs;
* :func:`replay_trace` — arrivals reconstructed from a recorded
  :class:`~torchx_tpu.fleet.queue.FleetJournal` (or pipeline journal),
  so a production incident replays against a what-if fleet.
"""

from __future__ import annotations

import math
import random

from torchx_tpu.util.jsonl import iter_jsonl

#: class -> (arrival weight, (min,max) duration seconds, replica choices)
CLASS_MIX = {
    "serve": (0.15, (120.0, 600.0), (1, 2)),
    "interactive": (0.25, (60.0, 300.0), (1, 2)),
    "batch": (0.40, (600.0, 1800.0), (2, 4)),
    "preemptible": (0.20, (600.0, 1800.0), (2, 4)),
}

#: fallback duration for replayed jobs whose journal lacks a terminal
#: entry (the incident cut the recording short).
DEFAULT_REPLAY_DURATION_S = 600.0


def diurnal_trace(
    hours: float,
    seed: int,
    rate_scale: float = 1.0,
    base_interval_s: float = 45.0,
) -> list[dict]:
    """Poisson arrivals with a diurnal rate (one peak per simulated
    'day' compressed into the horizon), seeded -> identical traces for
    identical arguments. ``rate_scale`` multiplies the arrival rate
    (scale it with fleet size to keep pressure comparable)."""
    rng = random.Random(seed)
    horizon = hours * 3600.0
    base_rate = rate_scale / base_interval_s
    jobs = []
    t = 0.0
    i = 0
    while True:
        # thinning: sample at the peak rate, accept by the diurnal curve
        peak = base_rate * 3.25
        t += rng.expovariate(peak)
        if t >= horizon:
            break
        phase = 2.0 * math.pi * (t / horizon)
        rate = base_rate * (1.75 + 1.5 * math.sin(phase))  # 0.25x..3.25x
        if rng.random() > rate / peak:
            continue
        r = rng.random()
        acc = 0.0
        klass = "batch"
        for name, (w, _dur, _reps) in CLASS_MIX.items():
            acc += w
            if r <= acc:
                klass = name
                break
        _w, (dlo, dhi), reps = CLASS_MIX[klass]
        elastic = klass in ("batch", "preemptible")
        replicas = rng.choice(reps)
        jobs.append(
            {
                "job": f"sim-{i:04d}",
                "arrival": t,
                "klass": klass,
                "tenant": rng.choice(("ads", "search", "research")),
                "replicas": replicas,
                "duration": rng.uniform(dlo, dhi),
                "elastic": elastic and replicas > 1,
            }
        )
        i += 1
    return jobs


def replay_trace(journal_path: str) -> list[dict]:
    """Rebuild a job trace from a recorded fleet journal.

    ``submit`` entries give arrival (relative to the first entry's
    stamp), class, tenant and gang shape; each job's duration is the
    span from its first ``place`` to its ``terminal`` entry (falling
    back to :data:`DEFAULT_REPLAY_DURATION_S` when the recording ends
    first). Unparseable lines are skipped — a torn journal tail must not
    kill the replay."""
    submits: dict[str, dict] = {}
    placed: dict[str, float] = {}
    done: dict[str, float] = {}
    t0: float | None = None
    for e in iter_jsonl(journal_path, skip="all"):
        ts = float(e.get("time_usec", 0) or 0) / 1e6
        if t0 is None:
            t0 = ts
        kind, job = e.get("kind"), str(e.get("job", ""))
        if not job:
            continue
        if kind == "submit":
            submits[job] = {
                "job": job,
                "arrival": max(0.0, ts - t0),
                "klass": str(e.get("klass", "batch")),
                "tenant": str(e.get("tenant", "replay")),
                "replicas": int(e.get("replicas", 1)),
                "elastic": bool(e.get("elastic", False)),
            }
        elif kind == "place":
            placed.setdefault(job, ts)
        elif kind == "terminal":
            done.setdefault(job, ts)
    out = []
    for job, doc in submits.items():
        if job in placed and job in done:
            doc["duration"] = max(1.0, done[job] - placed[job])
        else:
            doc["duration"] = DEFAULT_REPLAY_DURATION_S
        out.append(doc)
    out.sort(key=lambda d: (d["arrival"], d["job"]))
    return out
