"""Deterministic virtual time: the clock every sim-hosted component runs on.

The control plane takes time through two injectable seams — a ``clock()
-> float`` callable and a ``sleep(seconds)`` callable — everywhere
(``scripts/lint_internal.py`` bans raw ``time.time()`` / ``time.sleep()``
/ ``time.monotonic()`` calls in the sim-hosted packages). In production
those default to the stdlib; under simulation both are bound to one
:class:`VirtualClock`, so a 2-hour diurnal trace advances in however
long the *decisions* take, and two runs with the same seed traverse the
identical sequence of instants.

Cross-thread determinism is the hard part: the pipeline engine runs
canary promotions on worker threads that ``sleep()`` through their
observation windows. The clock therefore distinguishes the **driver**
thread (the harness event loop, which advances time) from **worker**
threads (which park in :meth:`sleep` until the driver advances past
their deadline). The driver's advance settles every woken worker —
waits until it is parked again or dead — before moving further, so the
interleaving of virtual instants is a pure function of the event times,
never of OS scheduling.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Optional, Protocol, runtime_checkable


@runtime_checkable
class ClockProto(Protocol):
    """The pair of seams a sim-hosted component needs from time."""

    def now(self) -> float:
        """Current time in seconds (monotonic within one clock)."""
        ...

    def sleep(self, seconds: float) -> None:
        """Block the caller for ``seconds`` of this clock's time."""
        ...


class SystemClock:
    """Wall time behind the :class:`ClockProto` seams (production).

    This module is the one approved place the stdlib time functions are
    called directly — everything else takes them through injection.
    """

    def now(self) -> float:
        """Wall time via ``time.monotonic()``."""
        return time.monotonic()

    def __call__(self) -> float:
        return self.now()

    def sleep(self, seconds: float) -> None:
        """Real ``time.sleep``."""
        time.sleep(seconds)


class VirtualClock:
    """Discrete-event virtual time with deterministic cross-thread sleeps.

    The clock is callable (``clock()``), so it drops into every
    ``clock: Callable[[], float]`` parameter in the codebase; pass
    ``clock.sleep`` wherever a ``sleep`` seam is taken.

    One thread — the **driver**, by default the constructing thread — owns
    time: only its :meth:`advance_to` / :meth:`advance` (and its own
    :meth:`sleep`, which advances inline) move ``now``. Any other thread
    calling :meth:`sleep` parks on an event keyed by its virtual deadline;
    the driver's advance pops due sleepers in ``(deadline, seq)`` order,
    wakes each, and *settles* — waits until the woken thread has either
    parked in its next sleep or exited — before waking the next. Virtual
    time is therefore a total order independent of the OS scheduler.
    """

    def __init__(self, start: float = 0.0) -> None:
        self._cond = threading.Condition()
        self._now = float(start)
        self._seq = 0
        # (deadline, seq, wake event, thread) — seq breaks deadline ties
        # in registration order, which is deterministic under settling
        self._sleepers: list[tuple[float, int, threading.Event, threading.Thread]] = []
        self._parked: set[threading.Thread] = set()
        self._woken: set[threading.Thread] = set()
        self._driver = threading.current_thread()

    # -- reading -----------------------------------------------------------

    def now(self) -> float:
        """Current virtual time in seconds."""
        with self._cond:
            return self._now

    def __call__(self) -> float:
        return self.now()

    def next_wake(self) -> Optional[float]:
        """Earliest parked sleeper's virtual deadline (None when idle) —
        the harness merges this into its event heap so worker sleeps are
        first-class events."""
        with self._cond:
            return self._sleepers[0][0] if self._sleepers else None

    # -- driver ------------------------------------------------------------

    def set_driver(self, thread: Optional[threading.Thread] = None) -> None:
        """Re-home the driver role (default: the calling thread)."""
        with self._cond:
            self._driver = thread or threading.current_thread()

    def advance(self, delta: float) -> None:
        """Move time forward by ``delta`` seconds (driver only)."""
        with self._cond:
            self._advance_locked(self._now + max(0.0, float(delta)))

    def advance_to(self, target: float) -> None:
        """Move time to ``target`` (driver only; past targets are no-ops),
        waking and settling every sleeper due on the way."""
        with self._cond:
            self._advance_locked(float(target))

    def _advance_locked(self, target: float) -> None:
        target = max(self._now, target)
        while True:
            self._settle_locked()
            if not self._sleepers or self._sleepers[0][0] > target:
                break
            deadline, _seq, event, thread = heapq.heappop(self._sleepers)
            self._now = max(self._now, deadline)
            if thread.is_alive():
                # un-park here, not in the waker's own sleep() epilogue:
                # settling filters on _parked, and a woken thread still
                # listed there would let the driver race past its wake
                self._woken.add(thread)
                self._parked.discard(thread)
            event.set()
        self._settle_locked()
        self._now = target

    def _settle_locked(self) -> None:
        """Wait until every woken worker is parked again or dead. Workers
        notify the condition when they re-park; the short timed wait only
        covers threads that exit without sleeping again (liveness is
        polled — the outcome does not depend on the poll interval)."""
        while True:
            self._woken = {t for t in self._woken if t.is_alive() and t not in self._parked}
            if not self._woken:
                return
            self._cond.wait(0.002)

    # -- sleeping ----------------------------------------------------------

    def sleep(self, seconds: float) -> None:
        """Sleep ``seconds`` of virtual time.

        From the driver thread this advances time inline (a synchronous
        component sleeping on the event-loop thread must not deadlock).
        From any other thread it parks until the driver advances past the
        deadline."""
        seconds = max(0.0, float(seconds))
        me = threading.current_thread()
        with self._cond:
            if me is self._driver:
                self._advance_locked(self._now + seconds)
                return
            self._seq += 1
            event = threading.Event()
            heapq.heappush(
                self._sleepers, (self._now + seconds, self._seq, event, me)
            )
            self._parked.add(me)
            self._woken.discard(me)
            self._cond.notify_all()
        event.wait()
        with self._cond:
            self._parked.discard(me)

    def wait_parked(
        self, thread: threading.Thread, timeout: float = 30.0
    ) -> bool:
        """Block (wall time, bounded by ``timeout``) until ``thread`` is
        parked in :meth:`sleep` or has exited. The harness calls this on
        freshly spawned promotion threads before advancing, so their
        first sleep registers deterministically."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cond:
            while True:
                if not thread.is_alive():
                    return True
                if thread in self._parked and thread not in self._woken:
                    return True
                if time.monotonic() >= deadline:
                    return False
                self._cond.wait(0.002)
