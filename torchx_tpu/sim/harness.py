"""The simulator harness: the production control plane on virtual time.

:class:`SimHarness` wires the **unmodified** production components — the
:class:`~torchx_tpu.fleet.api.FleetScheduler` (market included), the
:class:`~torchx_tpu.control.reconciler.Reconciler`, the
:class:`~torchx_tpu.obs.slo.SloEngine`, the serve
:class:`~torchx_tpu.serve.pool.Autoscaler`, and the
:class:`~torchx_tpu.pipelines.engine.PipelineEngine` — onto one
:class:`~torchx_tpu.sim.clock.VirtualClock` and one
:class:`~torchx_tpu.sim.executor.SimExecutor`, then runs a scenario
(:mod:`torchx_tpu.sim.scenarios`) as a discrete-event loop::

    arrivals ── fleet.submit ──┐
    finishes ── reconciler.ingest ──> fleet.on_event + engine.on_event
    faults ──── cancel / cordon / resubmit
    ticks ───── metric store ingest ──> slo.evaluate ──> burn signal
    wakes ───── promotion threads sleeping through canary windows

Each loop iteration advances the clock to the earliest pending event and
dispatches it. Everything the run decides lands in one JSONL journal
whose bytes are a pure function of ``(scenario, seed)`` — same seed,
byte-identical journal — which is what makes control-plane changes
regression-testable at fleet scale: diff two journals instead of
squinting at dashboards.

Wall-clock cost is decisions, not sleeps: the 1000-slice
``failure-storm`` scenario (3 virtual hours, ~2700 gangs, a correlated
50-slice loss) runs in seconds (``tpx_sim_speedup`` reports the
virtual/wall ratio).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from torchx_tpu.control.events import StateEvent
from torchx_tpu.control.reconciler import Reconciler
from torchx_tpu.fleet import FleetModel, FleetScheduler, GangRequest
from torchx_tpu.obs import metrics as obs_metrics
from torchx_tpu.obs.slo import SloEngine, parse_slo
from torchx_tpu.obs.telemetry import MetricStore, PromSample
from torchx_tpu.pipelines.dag import PipelineSpec
from torchx_tpu.pipelines.engine import PipelineEngine
from torchx_tpu.serve.pool import AutoscalePolicy, Autoscaler
from torchx_tpu.sim.clock import VirtualClock
from torchx_tpu.sim.executor import SimExecutor
from torchx_tpu.sim.faults import FaultEvent, FaultStorm
from torchx_tpu.sim.traffic import diurnal_trace, replay_trace
from torchx_tpu.specs.api import AppState

#: virtual seconds a slice-lost gang waits before resubmission (modeled
#: supervisor restart-from-checkpoint latency).
SLICE_LOSS_RESTART_S = 30.0
#: virtual seconds a preempted gang waits before requeueing.
PREEMPT_RESTART_S = 15.0
#: cumulative-histogram bucket bounds of the synthetic serve TTFT feed.
TTFT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, float("inf"))


def _fmt_le(le: float) -> str:
    return "+Inf" if le == float("inf") else format(le, "g")


@dataclass
class SimReport:
    """What one run produced, wall facts included (the journal has none —
    wall time would break byte-identity)."""

    scenario: str
    seed: int
    virtual_s: float
    wall_s: float
    speedup: float
    journal_path: str
    journal_sha256: str
    stats: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        """JSON form (``tpx sim run --json``)."""
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "virtual_s": round(self.virtual_s, 6),
            "wall_s": round(self.wall_s, 3),
            "speedup": round(self.speedup, 1),
            "journal": self.journal_path,
            "journal_sha256": self.journal_sha256,
            "stats": self.stats,
        }


class _JournalingExecutor(SimExecutor):
    """SimExecutor that journals each placement (the scheduler calls
    ``schedule`` from inside its loop; hooking here catches market
    reshapes and grow-backs, not just first placements)."""

    def __init__(self, harness: "SimHarness", *args: Any, **kw: Any) -> None:
        super().__init__(*args, **kw)
        self._h = harness

    def schedule(self, job, mesh_spec):  # noqa: ANN001 - FleetExecutor seam
        handle = super().schedule(job, mesh_spec)
        self._h._emit(
            "place",
            job=job.req.job,
            handle=handle,
            replicas=job.cur_replicas,
        )
        return handle


class _SimRouter:
    """Duck-typed pool router the promotion controller shifts weights on."""

    def __init__(self, harness: "SimHarness") -> None:
        self._h = harness

    def set_weight(self, rid: int, weight: float) -> None:
        self._h._emit("router_weight", replica=int(rid), weight=float(weight))


class SimServePool:
    """Duck-typed serve pool for promote stages and the autoscaler:
    ``replicas`` (mutable), ``router.set_weight``, ``rollout_replica``."""

    def __init__(self, harness: "SimHarness", replicas: int = 4) -> None:
        self.replicas = int(replicas)
        self.router = _SimRouter(harness)
        self._h = harness

    def rollout_replica(self, rid: int, ckpt: str) -> bool:
        self._h._emit(
            "replica_roll", replica=int(rid), ckpt=os.path.basename(str(ckpt))
        )
        return True


class SimPipelineExecutor:
    """PipelineEngine stage executor backed by the simulated fleet.

    Train/eval stages become fleet gangs (priority per stage kind, work
    set from ``stage.cfg["sim_duration_s"]``); queued stages resolve
    lazily off watch events, exactly like the daemon's fleet-backed
    executor."""

    def __init__(self, harness: "SimHarness") -> None:
        self._h = harness

    def submit(self, tenant: str, pid: str, stage, args):  # noqa: ANN001
        h = self._h
        job = f"{pid}.{stage.name}"
        h.executor.set_work(job, float(stage.cfg.get("sim_duration_s", 60.0)))
        req = GangRequest(
            job=job,
            tenant=tenant or "pipeline",
            klass=stage.priority,
            replicas=max(1, int(stage.replicas)),
            elastic=False,
        )
        h._pipeline_jobs.add(job)
        h._requests[job] = req
        reply = h.fleet.submit(req)
        h._stats["submitted"] += 1
        h._emit(
            "submit",
            job=job,
            klass=req.klass,
            replicas=req.replicas,
            status=reply["status"],
            pipeline=pid,
            stage=stage.name,
        )
        if reply["status"] == "placed":
            return {"handle": reply["handle"]}
        if reply["status"] == "queued":
            return {"queued": True, "fleet_job": job}
        raise RuntimeError(
            f"stage gang infeasible: {reply.get('reason', 'unknown')}"
        )

    def resolve(self, fleet_job: str) -> str:
        j = self._h.fleet.job(fleet_job)
        return j.handle if j is not None and j.state == "running" else ""

    def cancel(self, handle: str) -> None:
        self._h.executor.cancel(handle)


class SimHarness:
    """One scenario run over the production control plane; see the module
    docstring for the event-loop shape.

    Args:
        scenario: a scenario dict (:func:`~torchx_tpu.sim.scenarios
            .get_scenario`).
        seed: overrides the scenario's ``seed`` (trace + fault-storm +
            victim-selection randomness all derive from it).
        state_dir: where component journals and artifacts land (a fresh
            temp dir when omitted — they are throwaway; only the
            harness's own journal is the deterministic record).
        journal_path: where the run journal is written (default
            ``<state_dir>/sim_journal.jsonl``).
    """

    def __init__(
        self,
        scenario: dict,
        seed: Optional[int] = None,
        state_dir: Optional[str] = None,
        journal_path: Optional[str] = None,
    ) -> None:
        # the sim is headless: gang traces would hit the event sink, and
        # the per-event metrics-textfile flush (render + replace + fsync)
        # dominates wall time at fleet scale — disable tracing unless the
        # operator explicitly asked for it. run() restores whatever we
        # set here, so a harness in a larger process (tests) leaves no
        # env residue
        self._env_set: list[str] = []
        for key, val in (
            ("TPX_EVENT_DESTINATION", "null"),
            ("TPX_TRACE", "0"),
        ):
            if key not in os.environ:
                os.environ[key] = val
                self._env_set.append(key)
        self.scenario = dict(scenario)
        self.seed = int(self.scenario.get("seed", 0) if seed is None else seed)
        if state_dir is None:
            # throwaway journals: prefer tmpfs so the fleet/pipeline
            # journals' per-decision fsync is memory-speed, not disk
            shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
            state_dir = tempfile.mkdtemp(prefix="tpx-sim-", dir=shm)
        self.state_dir = state_dir
        self.journal_path = journal_path or os.path.join(
            self.state_dir, "sim_journal.jsonl"
        )
        self.clock = VirtualClock()
        self.model = FleetModel.from_spec(str(self.scenario["fleet"]))
        self.executor = _JournalingExecutor(
            self,
            self.clock,
            launch_latency_s=float(self.scenario.get("launch_latency_s", 0.0)),
            complete_latency_s=float(
                self.scenario.get("complete_latency_s", 0.0)
            ),
        )
        self.fleet = FleetScheduler(self.model, self.state_dir, clock=self.clock)
        self.fleet.bind(self.executor)
        self.reconciler = Reconciler(clock=self.clock)
        self.reconciler.subscribe(self.fleet.on_event)
        self.store = MetricStore(clock=self.clock)
        serve_cfg = self.scenario.get("serve") or {}
        self._serve_cfg = serve_cfg
        specs = [parse_slo(s) for s in serve_cfg.get("slos", [])]
        self.slo = SloEngine(self.store, specs, clock=self.clock)
        if specs:
            self.fleet.set_slo_signal(self.slo.max_burn)
        self._serve_pool = SimServePool(
            self, replicas=int(serve_cfg.get("replicas", 4))
        )
        self.autoscaler: Optional[Autoscaler] = None
        if serve_cfg.get("autoscale"):
            policy_doc = dict(serve_cfg["autoscale"])
            policy_doc.pop("replicas", None)
            self.autoscaler = Autoscaler(
                AutoscalePolicy(**policy_doc), clock=self.clock
            )
        self.engine: Optional[PipelineEngine] = None
        if self.scenario.get("pipelines"):
            self.engine = PipelineEngine(
                os.path.join(self.state_dir, "pipelines.jsonl"),
                executor=SimPipelineExecutor(self),
                reconciler=self.reconciler,
                slo_signal=self.slo.max_burn if specs else None,
                pool_provider=lambda stage: self._serve_pool,
                clock=self.clock,
                sleep=self.clock.sleep,
            )
            self.reconciler.subscribe(self.engine.on_event)
        # -- run state -------------------------------------------------------
        self._rows: list[str] = []
        self._rows_lock = threading.Lock()
        self._requests: dict[str, GangRequest] = {}
        self._pipeline_jobs: set[str] = set()
        self._timers: list[tuple[float, int, str, Any]] = []
        self._timer_seq = 0
        self._flap_until = 0.0
        self._drains: dict[str, dict] = {}  # pool -> {"uids", "sentinel"}
        self._degraded: list[tuple[float, float]] = []  # serve TTFT windows
        self._rng = random.Random(self.seed ^ 0x51ED)  # victim selection
        self._buckets = {le: 0 for le in TTFT_BUCKETS}
        self._ttft_count = 0
        self._ttft_sum = 0.0
        self._stats = {
            "submitted": 0,
            "completed": 0,
            "infeasible": 0,
            "resubmitted": 0,
            "faults": 0,
            "slo_alerts": 0,
            "autoscales": 0,
        }

    # -- journaling ----------------------------------------------------------

    def _emit(self, kind: str, **fields: Any) -> None:
        """Append one journal row at the current virtual instant. Called
        from the driver and (via pool/router seams) from settled
        promotion workers — both orderings are deterministic under the
        clock's settle protocol."""
        row = {"t": round(self.clock(), 6), "kind": kind}
        row.update(fields)
        line = json.dumps(row, sort_keys=True)
        with self._rows_lock:
            self._rows.append(line)

    def _timer(self, t: float, kind: str, payload: Any = None) -> None:
        import heapq

        self._timer_seq += 1
        heapq.heappush(self._timers, (t, self._timer_seq, kind, payload))

    # -- the run -------------------------------------------------------------

    def run(self) -> SimReport:
        """Execute the scenario to quiescence; returns the report."""
        try:
            return self._run()
        finally:
            # undo the headless-mode env defaults __init__ installed so
            # a host process (the test suite) sees its own config again
            for key in self._env_set:
                os.environ.pop(key, None)
            self._env_set = []

    def _run(self) -> SimReport:
        wall0 = time.perf_counter()
        sc = self.scenario
        if sc.get("replay_journal"):
            trace = replay_trace(str(sc["replay_journal"]))
        else:
            trace = diurnal_trace(
                float(sc.get("hours", 1.0)),
                self.seed,
                rate_scale=float(sc.get("rate_scale", 1.0)),
            )
        horizon = float(sc.get("hours", 1.0)) * 3600.0
        max_virtual = horizon * 10.0 + 86400.0
        storm = FaultStorm.from_spec(sc.get("faults", []), self.seed)
        for ev in storm:
            self._timer(ev.t, "fault", ev)
        pipes = sorted(
            sc.get("pipelines", []), key=lambda p: float(p.get("at", 0.0))
        )
        tick_s = float(sc.get("metrics_interval_s", 60.0))
        next_tick = tick_s
        self._emit(
            "begin",
            scenario=str(sc.get("name", "")),
            seed=self.seed,
            fleet=str(sc["fleet"]),
            slices=len(self.model.units()),
            trace_jobs=len(trace),
            faults=len(storm),
        )
        arr_i = 0
        pipe_i = 0
        import heapq

        while True:
            cands: list[tuple[float, int, str]] = []
            if arr_i < len(trace):
                cands.append((float(trace[arr_i]["arrival"]), 0, "arrival"))
            if pipe_i < len(pipes):
                cands.append((float(pipes[pipe_i].get("at", 0.0)), 1, "pipeline"))
            if self._timers:
                cands.append((self._timers[0][0], 2, "timer"))
            nf = self.executor.next_finish()
            if nf is not None:
                cands.append((nf, 3, "finish"))
            nw = self.clock.next_wake()
            if nw is not None:
                cands.append((nw, 4, "wake"))
            threads_alive = self.engine is not None and any(
                t.is_alive() for t in self.engine.active_threads()
            )
            if self._serve_cfg and (cands or threads_alive):
                cands.append((next_tick, 5, "tick"))
            if not cands:
                break
            t, _prio, kind = min(cands)
            if t > max_virtual:
                self._emit("guard_tripped", budget=max_virtual)
                break
            self.clock.advance_to(t)
            if kind == "arrival":
                doc = trace[arr_i]
                arr_i += 1
                if self.clock() < self._flap_until:
                    self._timer(self._flap_until, "late_arrival", doc)
                else:
                    self._submit(doc)
            elif kind == "pipeline":
                entry = pipes[pipe_i]
                pipe_i += 1
                self._submit_pipeline(entry)
            elif kind == "timer":
                _t, _seq, tkind, payload = heapq.heappop(self._timers)
                self._dispatch_timer(tkind, payload)
            elif kind == "finish":
                self._finish_one()
            elif kind == "tick":
                self._metrics_tick()
                next_tick += tick_s
            # "wake": advance_to already woke and settled the sleeper

        virtual_s = self.clock()
        self._stats["queued_end"] = len(
            [
                j
                for j in (self.fleet.job(k) for k in sorted(self._requests))
                if j is not None and j.state == "queued"
            ]
        )
        self._stats["kills"] = self.fleet.kills
        self._stats["reshapes"] = self.fleet.reshapes
        self._stats["grows"] = self.fleet.grows
        total = len(self.model.units())
        self._stats["utilization"] = round(
            self.executor.busy_integral / (total * virtual_s), 4
        ) if virtual_s > 0 else 0.0
        if self.engine is not None:
            doc = self.engine.status()
            self._stats["pipelines"] = {
                p["pipeline"]: p["state"] for p in doc.get("pipelines", [])
            }
        self._emit("end", virtual_s=round(virtual_s, 6), **self._stats)
        wall_s = time.perf_counter() - wall0
        return self._finalize(virtual_s, wall_s)

    def _finalize(self, virtual_s: float, wall_s: float) -> SimReport:
        with self._rows_lock:
            payload = ("\n".join(self._rows) + "\n").encode()
        os.makedirs(os.path.dirname(self.journal_path) or ".", exist_ok=True)
        with open(self.journal_path, "wb") as f:
            f.write(payload)
        digest = hashlib.sha256(payload).hexdigest()
        speedup = virtual_s / wall_s if wall_s > 0 else 0.0
        kinds: dict[str, int] = {}
        for line in self._rows:
            k = json.loads(line)["kind"]
            kinds[k] = kinds.get(k, 0) + 1
        for k, n in sorted(kinds.items()):
            obs_metrics.SIM_EVENTS.inc(n, kind=k)
        obs_metrics.SIM_VIRTUAL_SECONDS.set(virtual_s)
        obs_metrics.SIM_WALL_SECONDS.set(wall_s)
        obs_metrics.SIM_SPEEDUP.set(speedup)
        return SimReport(
            scenario=str(self.scenario.get("name", "")),
            seed=self.seed,
            virtual_s=virtual_s,
            wall_s=wall_s,
            speedup=speedup,
            journal_path=self.journal_path,
            journal_sha256=digest,
            stats=dict(self._stats),
        )

    # -- event handlers ------------------------------------------------------

    def _submit(self, doc: dict) -> None:
        req = GangRequest(
            job=str(doc["job"]),
            tenant=str(doc.get("tenant", "sim")),
            klass=str(doc.get("klass", "batch")),
            replicas=int(doc.get("replicas", 1)),
            elastic=bool(doc.get("elastic", False)),
        )
        self.executor.set_work(req.job, float(doc.get("duration", 60.0)))
        self._requests[req.job] = req
        reply = self.fleet.submit(req)
        self._stats["submitted"] += 1
        if reply["status"] == "infeasible":
            self._stats["infeasible"] += 1
        self._emit(
            "submit",
            job=req.job,
            klass=req.klass,
            replicas=req.replicas,
            status=reply["status"],
        )

    def _finish_one(self) -> None:
        handle = self.executor.pop_finished()
        app_id = self.executor.finish(handle)
        job = self.executor.job_of(handle)
        if self.clock() < self._flap_until:
            # the gang is physically done but the control plane can't see
            # it — the terminal event lands when the flap clears
            self._emit("finish_deferred", job=job)
            self._timer(self._flap_until, "late_finish", (job, app_id))
            return
        self._ingest_terminal(job, app_id, AppState.SUCCEEDED)

    def _ingest_terminal(self, job: str, app_id: str, state: AppState) -> None:
        if self._drains:
            # maintenance drain: slices this gang frees in a drained pool
            # cordon instead of returning to the allocator
            for uid in [
                u.uid
                for u in self.model.units_of(job)
                if u.pool in self._drains
            ]:
                self.model.release([uid])
                rec = self._drains[self.model.unit(uid).pool]
                self.model.assign([uid], rec["sentinel"])
                rec["uids"].add(uid)
        self.reconciler.ingest(
            StateEvent(
                scheduler="local",
                app_id=app_id,
                state=state,
                source="sim",
                time_usec=int(self.clock() * 1e6),
            )
        )
        if state == AppState.SUCCEEDED:
            self._stats["completed"] += 1
        self._emit("gang_done", job=job, state=state.name)
        self._settle_threads()

    def _settle_threads(self) -> None:
        """Park-or-die barrier over promotion threads: an ingest may have
        spawned one; its first virtual sleep must register before the
        driver advances again."""
        if self.engine is None:
            return
        for th in self.engine.active_threads():
            if th.is_alive():
                self.clock.wait_parked(th)

    def _dispatch_timer(self, kind: str, payload: Any) -> None:
        if kind == "fault":
            self._apply_fault(payload)
        elif kind == "late_arrival":
            self._submit(payload)
        elif kind == "late_finish":
            job, app_id = payload
            self._ingest_terminal(job, app_id, AppState.SUCCEEDED)
        elif kind == "resubmit":
            for job in payload:
                self._resubmit(job)
        elif kind == "uncordon":
            uids, seq = payload
            self.model.release(uids)
            self._emit("uncordon", slices=len(uids), fault_seq=seq)
            self._kick()
        elif kind == "drain_end":
            rec = payload
            self.model.release(sorted(rec["uids"]))
            self._drains.pop(rec["pool"], None)
            self._emit(
                "drain_end", pool=rec["pool"], slices=len(rec["uids"])
            )
            self._kick()
        elif kind == "flap_end":
            self._emit("flap_end")

    def _kick(self) -> None:
        """Re-run the placement loop after capacity returns (terminal
        events normally drive it; cordon release has no event)."""
        with self.fleet._lock:
            self.fleet._schedule_loop()

    def _resubmit(self, job: str) -> None:
        req = self._requests.get(job)
        if req is None or job in self._pipeline_jobs:
            return  # pipeline stages fail their run; no blind restart
        cur = self.fleet.job(job)
        if cur is not None and cur.state in ("queued", "running"):
            return  # already back (double fault on the same gang)
        # remaining work stays banked in the executor from the cancel
        reply = self.fleet.submit(req)
        self._stats["resubmitted"] += 1
        self._emit(
            "resubmit", job=job, klass=req.klass, status=reply["status"]
        )

    # -- faults --------------------------------------------------------------

    def _apply_fault(self, ev: FaultEvent) -> None:
        self._stats["faults"] += 1
        obs_metrics.SIM_FAULTS.inc(kind=ev.kind)
        self._emit(
            "fault",
            fault=ev.kind,
            count=ev.count,
            pool=ev.pool,
            duration_s=ev.duration_s,
            klass=ev.klass,
            seq=ev.seq,
        )
        if ev.kind == "slice_loss":
            self._fault_slice_loss(ev)
        elif ev.kind == "pool_drain":
            self._fault_pool_drain(ev)
        elif ev.kind == "preemption_wave":
            self._fault_preemption(ev)
        elif ev.kind == "control_flap":
            now = self.clock()
            self._flap_until = max(self._flap_until, now + ev.duration_s)
            self._timer(self._flap_until, "flap_end", None)

    def _fault_slice_loss(self, ev: FaultEvent) -> None:
        pool = ev.pool or self.model.pools[0].name
        units = [u for u in self.model.units() if u.pool == pool]
        if not units:
            return
        n = min(ev.count, len(units))
        start = self._rng.randrange(len(units) - n + 1)
        lost = units[start : start + n]
        victims = sorted(
            {
                owner
                for u in lost
                if (owner := self.model.owner_of(u.uid)) is not None
                and not owner.startswith("__")
            }
        )
        now = self.clock()
        if ev.klass == "serve":
            # the lost slices hosted serve capacity: degrade the synthetic
            # TTFT feed for the outage window
            self._degraded.append((now, now + ev.duration_s))
        terminals = []
        for job in victims:
            fj = self.fleet.job(job)
            if fj is None or fj.state != "running":
                continue
            att = self.executor.attempts.get(fj.handle)
            self.executor.cancel(fj.handle)
            self.model.release_job(job)
            if att is not None:
                terminals.append((job, fj.handle.rsplit("/", 1)[1]))
        lost_uids = [u.uid for u in lost]
        self.model.release(lost_uids)
        self.model.assign(lost_uids, f"__down__:{ev.seq}")
        self._emit(
            "slices_down", pool=pool, slices=lost_uids, victims=victims
        )
        for job, app_id in terminals:
            self._ingest_terminal(job, app_id, AppState.FAILED)
        self._timer(
            self.clock() + ev.duration_s, "uncordon", (lost_uids, ev.seq)
        )
        if terminals:
            self._timer(
                self.clock() + SLICE_LOSS_RESTART_S,
                "resubmit",
                [j for j, _ in terminals],
            )

    def _fault_pool_drain(self, ev: FaultEvent) -> None:
        pool = ev.pool or self.model.pools[0].name
        if pool in self._drains:
            return
        rec = {"pool": pool, "sentinel": f"__drain__:{ev.seq}", "uids": set()}
        free = [
            u.uid
            for u in self.model.free_units()
            if u.pool == pool
        ]
        self.model.assign(free, rec["sentinel"])
        rec["uids"].update(free)
        self._drains[pool] = rec
        self._emit("drain_start", pool=pool, slices=len(free))
        self._timer(self.clock() + ev.duration_s, "drain_end", rec)

    def _fault_preemption(self, ev: FaultEvent) -> None:
        running = sorted(
            job
            for job in self._requests
            if job not in self._pipeline_jobs
            and (fj := self.fleet.job(job)) is not None
            and fj.state == "running"
            and (not ev.klass or fj.req.klass == ev.klass)
        )
        if not running:
            return
        picked = sorted(self._rng.sample(running, min(ev.count, len(running))))
        self._emit("preempted", jobs=picked, klass=ev.klass)
        for job in picked:
            fj = self.fleet.job(job)
            self.executor.cancel(fj.handle)
            self.model.release_job(job)
            self._ingest_terminal(
                job, fj.handle.rsplit("/", 1)[1], AppState.FAILED
            )
        self._timer(self.clock() + PREEMPT_RESTART_S, "resubmit", picked)

    # -- pipelines -----------------------------------------------------------

    def _submit_pipeline(self, entry: dict) -> None:
        import copy

        if self.engine is None:
            return
        spec_doc = copy.deepcopy(entry.get("spec") or {})
        name = str(spec_doc.get("name", "pipeline"))
        art_dir = os.path.join(self.state_dir, "artifacts", name)
        os.makedirs(art_dir, exist_ok=True)
        score = float(entry.get("score", 1.0))
        digest = hashlib.sha256(f"{name}:{self.seed}".encode()).hexdigest()
        for stage in spec_doc.get("stages", []):
            if stage.get("kind") == "train" and stage.get("ckpt_dir"):
                ckpt_dir = os.path.join(art_dir, stage["ckpt_dir"])
                os.makedirs(ckpt_dir, exist_ok=True)
                from torchx_tpu import settings

                with open(
                    os.path.join(ckpt_dir, settings.CHECKPOINT_MANIFEST), "w"
                ) as f:
                    json.dump(
                        {
                            "latest_step": 1000,
                            "steps": {"1000": {"digest": digest}},
                        },
                        f,
                    )
                stage["ckpt_dir"] = ckpt_dir
            if stage.get("kind") == "eval" and stage.get("score_file"):
                score_file = os.path.join(art_dir, stage["score_file"])
                with open(score_file, "w") as f:
                    json.dump({"score": score, "digest": digest}, f)
                stage["score_file"] = score_file
        spec = PipelineSpec.from_dict(spec_doc)
        pid = self.engine.submit(spec, tenant="sim")
        self._emit("pipeline_submit", pipeline=pid, spec=name, score=score)
        self._settle_threads()

    # -- telemetry -----------------------------------------------------------

    def _metrics_tick(self) -> None:
        cfg = self._serve_cfg
        now = self.clock()
        n = int(cfg.get("requests_per_tick", 20))
        degraded = any(a <= now < b for a, b in self._degraded)
        val = float(
            cfg.get("ttft_degraded_s", 1.2)
            if degraded
            else cfg.get("ttft_base_s", 0.08)
        )
        for le in TTFT_BUCKETS:
            if val <= le:
                self._buckets[le] += n
        self._ttft_count += n
        self._ttft_sum += n * val
        samples = [
            PromSample(
                name="tpx_sim_serve_ttft_seconds_bucket",
                labels=(("le", _fmt_le(le)),),
                value=float(self._buckets[le]),
                kind="histogram",
            )
            for le in TTFT_BUCKETS
        ]
        samples.append(
            PromSample(
                name="tpx_sim_serve_ttft_seconds_count",
                labels=(),
                value=float(self._ttft_count),
                kind="histogram",
            )
        )
        samples.append(
            PromSample(
                name="tpx_sim_serve_ttft_seconds_sum",
                labels=(),
                value=self._ttft_sum,
                kind="histogram",
            )
        )
        self.store.ingest("sim", samples, ts=now)
        for alert in self.slo.evaluate(now=now):
            self._stats["slo_alerts"] += 1
            self._emit(
                "slo_alert",
                slo=alert.slo,
                severity=alert.severity,
                state=alert.state,
                burn_short=round(alert.burn_short, 3),
                burn_long=round(alert.burn_long, 3),
            )
        if self.autoscaler is not None:
            self._autoscale_tick()

    def _autoscale_tick(self) -> None:
        pool = self._serve_pool
        queued = len(
            [
                j
                for j in (self.fleet.job(k) for k in sorted(self._requests))
                if j is not None
                and j.state == "queued"
                and j.req.klass == "serve"
            ]
        )
        desired = self.autoscaler.observe(
            replicas=pool.replicas,
            queue_depth=queued / max(1, pool.replicas),
            burn_rate=self.slo.max_burn() if self.slo.specs else None,
        )
        if desired != pool.replicas:
            self._emit(
                "autoscale", replicas=pool.replicas, desired=desired
            )
            pool.replicas = desired
            self.autoscaler.notify_scaled()
            self._stats["autoscales"] += 1
