"""Virtual-time simulation of the fleet control plane.

The sim subsystem runs the PRODUCTION control plane — the real
:class:`~torchx_tpu.fleet.FleetScheduler`, :class:`~torchx_tpu.control
.reconciler.Reconciler`, :class:`~torchx_tpu.obs.slo.SloEngine`,
:class:`~torchx_tpu.serve.pool.Autoscaler` and :class:`~torchx_tpu
.pipelines.engine.PipelineEngine` — unmodified, on a deterministic
discrete-event :class:`~torchx_tpu.sim.clock.VirtualClock` instead of
wall time. Hours of fleet behavior (diurnal arrivals, correlated slice
loss, canary promotions under SLO burn) replay in seconds of wall
clock, and the same seed produces a byte-identical run journal.

Everything here is jax-free (enforced by ``scripts/lint_internal.py``):
the simulator must import on the daemon's fast path and inside the CLI
without dragging in an accelerator runtime.

Layout:

* :mod:`~torchx_tpu.sim.clock` — the virtual clock and its seams;
* :mod:`~torchx_tpu.sim.executor` — the modeled-fleet
  :class:`~torchx_tpu.fleet.FleetExecutor`;
* :mod:`~torchx_tpu.sim.traffic` — seeded synthetic traces + journal
  replay;
* :mod:`~torchx_tpu.sim.faults` — seeded, replayable fault storms;
* :mod:`~torchx_tpu.sim.scenarios` — bundled scenario files;
* :mod:`~torchx_tpu.sim.harness` — the wiring + event loop.
"""

from torchx_tpu.sim.clock import ClockProto, SystemClock, VirtualClock
from torchx_tpu.sim.executor import SimExecutor
from torchx_tpu.sim.faults import FaultEvent, FaultStorm
from torchx_tpu.sim.harness import SimHarness, SimReport
from torchx_tpu.sim.scenarios import BUNDLED_SCENARIOS, get_scenario
from torchx_tpu.sim.traffic import CLASS_MIX, diurnal_trace, replay_trace

__all__ = [
    "ClockProto",
    "SystemClock",
    "VirtualClock",
    "SimExecutor",
    "FaultEvent",
    "FaultStorm",
    "SimHarness",
    "SimReport",
    "BUNDLED_SCENARIOS",
    "get_scenario",
    "CLASS_MIX",
    "diurnal_trace",
    "replay_trace",
]
