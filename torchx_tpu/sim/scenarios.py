"""Bundled simulation scenarios + scenario loading.

A scenario is one JSON-shaped dict describing a full run:

``fleet``
    :meth:`~torchx_tpu.fleet.model.FleetModel.from_spec` string.
``hours`` / ``rate_scale`` / ``seed``
    :func:`~torchx_tpu.sim.traffic.diurnal_trace` arguments (``seed`` is
    the default; ``tpx sim run --seed`` overrides it).
``replay_journal``
    optional recorded fleet journal path — replaces the synthetic trace
    with :func:`~torchx_tpu.sim.traffic.replay_trace`.
``backend``
    executor the scenario is modeled against (must be ``"sim"`` — the
    analyzer's TPX604 rule warns when a scenario names a real backend,
    because the virtual-time executor is the only thing that runs).
``faults``
    :meth:`~torchx_tpu.sim.faults.FaultStorm.from_spec` entries.
``serve``
    synthetic serve-plane telemetry: ``ttft_base_s``,
    ``ttft_degraded_s`` (TTFT while a serve-degrading fault is active),
    ``requests_per_tick``, ``slos`` (SLO spec strings over the
    ``tpx_sim_*`` metrics), ``autoscale`` (AutoscalePolicy fields +
    ``replicas``/``load``).
``pipelines``
    ``[{"at": <virtual s>, "score": <eval score>, "spec": <PipelineSpec
    dict>}]`` — submitted to the real PipelineEngine at ``at``.
``launch_latency_s`` / ``complete_latency_s`` / ``metrics_interval_s``
    executor latencies and the telemetry tick.

:func:`get_scenario` resolves a bundled name or a JSON file path.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any

#: the default SLO set every scenario gets unless it declares its own.
DEFAULT_SIM_SLOS = ["ttft:tpx_sim_serve_ttft_seconds<0.5@0.99"]

BUNDLED_SCENARIOS: dict[str, dict[str, Any]] = {
    # the SIM_SMOKE / unit-test scenario: small enough to run in well
    # under a second, busy enough to exercise every journal row kind.
    "smoke-tiny": {
        "name": "smoke-tiny",
        "backend": "sim",
        "fleet": "sim:v5e-4x8",
        "seed": 11,
        "hours": 0.5,
        "rate_scale": 1.0,
        "metrics_interval_s": 30.0,
        "faults": [
            {"t": 420.0, "kind": "slice_loss", "count": 2, "duration_s": 300.0},
            {"t": 900.0, "kind": "preemption_wave", "count": 1, "klass": "batch"},
        ],
    },
    # the bench companion: 10x the original 16-slice bench fleet under
    # the same diurnal curve, no faults — pure scheduling behavior.
    "fleet-diurnal": {
        "name": "fleet-diurnal",
        "backend": "sim",
        "fleet": "sim:v5e-4x160",
        "seed": 11,
        "hours": 2.0,
        "rate_scale": 10.0,
        "metrics_interval_s": 60.0,
        "faults": [],
    },
    # the acceptance scenario: 1000 slices, ~2700 gangs over 3 virtual
    # hours, correlated slice loss + a preemption wave + a maintenance
    # drain + a control-plane flap.
    "failure-storm": {
        "name": "failure-storm",
        "backend": "sim",
        "fleet": "sim:v5e-4x1000",
        "seed": 11,
        "hours": 3.0,
        "rate_scale": 6.7,
        "metrics_interval_s": 120.0,
        "faults": [
            {"t": 2400.0, "kind": "slice_loss", "count": 50, "duration_s": 1800.0},
            {
                "kind": "preemption_wave",
                "start": 3600.0,
                "end": 7200.0,
                "events": 8,
                "count": 3,
                "klass": "preemptible",
            },
            {"t": 5400.0, "kind": "pool_drain", "pool": "sim", "duration_s": 600.0},
            {"t": 8100.0, "kind": "control_flap", "duration_s": 120.0},
        ],
    },
    # the full-stack scenario: a train -> eval -> promote pipeline whose
    # canary window collides with a serve-degrading slice loss; the SLO
    # burn gate must roll the promotion back in virtual time.
    "pipeline-canary-under-storm": {
        "name": "pipeline-canary-under-storm",
        "backend": "sim",
        "fleet": "sim:v5e-4x16",
        "seed": 11,
        "hours": 1.0,
        "rate_scale": 0.5,
        "metrics_interval_s": 15.0,
        "serve": {
            "ttft_base_s": 0.08,
            "ttft_degraded_s": 1.2,
            "requests_per_tick": 50,
            "slos": DEFAULT_SIM_SLOS,
        },
        "faults": [
            {
                "t": 1000.0,
                "kind": "slice_loss",
                "count": 4,
                "duration_s": 900.0,
                "klass": "serve",
            },
        ],
        "pipelines": [
            {
                "at": 60.0,
                "score": 0.93,
                "spec": {
                    "name": "canary-under-storm",
                    "stages": [
                        {
                            "name": "train",
                            "kind": "train",
                            "replicas": 2,
                            "ckpt_dir": "ckpt",
                            "cfg": {"sim_duration_s": 600.0},
                        },
                        {
                            "name": "eval",
                            "kind": "eval",
                            "depends_on": ["train"],
                            "score_file": "score.json",
                            "threshold": 0.9,
                            "cfg": {"sim_duration_s": 120.0},
                        },
                        {
                            "name": "promote",
                            "kind": "promote",
                            "depends_on": ["eval"],
                            "observe_s": 600.0,
                            "burn_threshold": 1.0,
                        },
                    ],
                },
            },
        ],
    },
    # the federation acceptance scenario: two regional cells under
    # phase-shifted diurnal traffic; one cell drains mid-trace and
    # uncordons later. Run by FederationSimHarness (the "cells" key is
    # the routing signal): asserts zero dropped requests and a bounded
    # failover p99 — the deterministic twin of scripts/bench_federation.
    "federation-two-cell": {
        "name": "federation-two-cell",
        "backend": "sim",
        "fleet": "sim:v5e-4x8",
        "seed": 11,
        "hours": 1.5,
        "metrics_interval_s": 60.0,
        "burn_budget": 2.0,
        "cells": [
            {"name": "us-east1", "capacity_rps": 0.05, "phase_h": 0.0},
            {"name": "eu-west4", "capacity_rps": 0.05, "phase_h": 8.0},
        ],
        "serve": {
            "ttft_base_s": 0.08,
            "ttft_degraded_s": 0.4,
            "requests_per_tick": 4,
            "dial_timeout_s": 0.1,
            "slo_target_s": 0.5,
            "slos": DEFAULT_SIM_SLOS,
        },
        "faults": [
            {"t": 1800.0, "kind": "cell_drain", "cell": "us-east1"},
            {"t": 3600.0, "kind": "cell_uncordon", "cell": "us-east1"},
        ],
    },
}


def get_scenario(name_or_path: str) -> dict[str, Any]:
    """Resolve a scenario by bundled name or JSON file path.

    Returns a deep copy (callers mutate freely). Raises ``ValueError``
    for an unknown name / unreadable file / non-object JSON."""
    if name_or_path in BUNDLED_SCENARIOS:
        return copy.deepcopy(BUNDLED_SCENARIOS[name_or_path])
    if os.path.exists(name_or_path):
        try:
            with open(name_or_path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(f"cannot load scenario {name_or_path!r}: {e}") from e
        if not isinstance(doc, dict):
            raise ValueError(
                f"scenario {name_or_path!r} must be a JSON object"
            )
        doc.setdefault("name", os.path.splitext(os.path.basename(name_or_path))[0])
        return doc
    raise ValueError(
        f"unknown scenario {name_or_path!r}; bundled:"
        f" {', '.join(sorted(BUNDLED_SCENARIOS))}"
    )
