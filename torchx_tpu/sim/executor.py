"""The modeled-fleet :class:`~torchx_tpu.fleet.api.FleetExecutor`.

Promoted from the original ``scripts/bench_fleet.py`` inline simulator
(the bench now imports it from here). Each :meth:`schedule` call becomes
one timed *attempt*: a gang runs at ``cur_replicas / launch_replicas``
speed (the market's shrink cost is modeled, not assumed away), finishing
after its remaining full-speed work divided by that speed plus the
configured gang-launch latency. :meth:`cancel` banks the remaining work,
so the mesh-reshape resubmit — or a fault-storm restart — picks the job
up where it left off instead of restarting it.

Per-generation chip and HBM facts come from the
:class:`~torchx_tpu.fleet.model.FleetModel` the scheduler places onto
(its :class:`~torchx_tpu.specs.api.TpuSlice` shapes feed the placement
oracle); the executor only models *when* an attempt finishes.
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional


class SimExecutor:
    """FleetExecutor over virtual time.

    Args:
        clock: the virtual clock (any ``() -> float`` callable).
        work: fleet job id -> remaining full-speed seconds; jobs are
            added with :meth:`set_work` (or pre-seeded by the caller).
        launch_latency_s: virtual seconds from ``schedule()`` to the gang
            actually computing (image pull + TPU init in the model).
        complete_latency_s: virtual seconds between the last step and the
            terminal event becoming observable (teardown + watch lag).
    """

    def __init__(
        self,
        clock: Callable[[], float],
        work: Optional[dict] = None,
        launch_latency_s: float = 0.0,
        complete_latency_s: float = 0.0,
    ) -> None:
        self.clock = clock
        self.work: dict[str, float] = dict(work or {})
        self.launch_latency_s = max(0.0, float(launch_latency_s))
        self.complete_latency_s = max(0.0, float(complete_latency_s))
        self.attempts: dict[str, dict] = {}  # handle -> attempt record
        self.events: list[tuple[float, int, str]] = []  # (finish, tie, handle)
        self.busy_integral = 0.0  # slice-seconds actually computed
        self.placed_at: dict[str, float] = {}  # job -> first placement time
        self._n = 0

    def set_work(self, job: str, seconds: float) -> None:
        """Declare (or reset) a job's remaining full-speed work."""
        self.work[job] = max(0.0, float(seconds))

    # -- FleetExecutor -----------------------------------------------------

    def schedule(self, job, mesh_spec):  # noqa: ANN001 - FleetExecutor seam
        """FleetExecutor seam: start one gang attempt and model its
        finish time (remaining work scaled by the placed replica
        fraction, plus launch/complete latency). Returns a
        ``local://sim/app-N`` handle."""
        self._n += 1
        handle = f"local://sim/app-{self._n}"
        now = self.clock()
        self.placed_at.setdefault(job.req.job, now)
        speed = job.cur_replicas / job.req.replicas
        finish = (
            now
            + self.launch_latency_s
            + self.work.get(job.req.job, 0.0) / speed
            + self.complete_latency_s
        )
        self.attempts[handle] = {
            "job": job.req.job,
            "start": now + self.launch_latency_s,
            "speed": speed,
            "slices": job.cur_replicas,
            "live": True,
        }
        heapq.heappush(self.events, (finish, self._n, handle))
        return handle

    def cancel(self, handle):  # noqa: ANN001 - FleetExecutor seam
        """FleetExecutor seam: stop an attempt, banking the work it
        completed so a later resubmit resumes from the checkpoint."""
        att = self.attempts.get(handle)
        if att is None or not att["live"]:
            return
        att["live"] = False
        elapsed = max(0.0, self.clock() - att["start"])
        job = att["job"]
        self.work[job] = max(0.0, self.work.get(job, 0.0) - elapsed * att["speed"])
        self.busy_integral += att["slices"] * elapsed

    # -- the harness's side ------------------------------------------------

    def next_finish(self) -> Optional[float]:
        """Earliest live attempt's finish time (dead heap entries from
        cancelled attempts are dropped on the way); None when idle."""
        while self.events and not self.attempts[self.events[0][2]]["live"]:
            heapq.heappop(self.events)
        return self.events[0][0] if self.events else None

    def pop_finished(self) -> str:
        """Pop the earliest live attempt's heap entry (the caller has
        already advanced the clock to its finish time); returns the
        handle. Raises ``IndexError`` when nothing is due."""
        while self.events and not self.attempts[self.events[0][2]]["live"]:
            heapq.heappop(self.events)
        _t, _tie, handle = heapq.heappop(self.events)
        return handle

    def finish(self, handle) -> str:  # noqa: ANN001
        """Retire a live attempt at its finish time; returns its app id
        (the ``local`` scheduler app id inside the handle)."""
        att = self.attempts[handle]
        att["live"] = False
        self.work[att["job"]] = 0.0
        self.busy_integral += att["slices"] * max(
            0.0, self.clock() - self.complete_latency_s - att["start"]
        )
        return handle.rsplit("/", 1)[1]

    def job_of(self, handle: str) -> str:
        """Fleet job id behind an attempt handle."""
        return self.attempts[handle]["job"]
