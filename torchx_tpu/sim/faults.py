"""Seeded, replayable fault storms for the simulator.

A fault spec is plain JSON inside a scenario file. Each entry is either
one concrete :class:`FaultEvent`::

    {"t": 1800, "kind": "slice_loss", "count": 4, "duration_s": 900}

or a *storm* — a window that expands into many events at seeded-uniform
times::

    {"kind": "preemption_wave", "start": 600, "end": 4200,
     "events": 12, "count": 2, "klass": "preemptible"}

:meth:`FaultStorm.from_spec` does the expansion with its own
``random.Random(seed)``, so the same spec + seed always yields the same
event list (replayable storms are what make same-seed journals
byte-identical). The *application* of each event — which slices die,
which gangs restart — lives in :class:`~torchx_tpu.sim.harness
.SimHarness`; this module only decides *when* and *how big*.

Kinds:

* ``slice_loss`` — ``count`` topologically-adjacent slices of one pool
  go dark for ``duration_s``; every gang touching them dies and is
  resubmitted with its banked remaining work.
* ``pool_drain`` — a pool stops accepting placements for ``duration_s``
  (maintenance drain); running gangs finish, freed slices cordon.
* ``preemption_wave`` — ``count`` running gangs of ``klass`` are
  externally preempted (defender-capacity reclaim) and resubmitted.
* ``control_flap`` — the control plane is unreachable for
  ``duration_s``: submits and terminal events buffer and land late.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

FAULT_KINDS = ("slice_loss", "pool_drain", "preemption_wave", "control_flap")


@dataclass(frozen=True)
class FaultEvent:
    """One concrete injected fault at one virtual instant."""

    t: float
    kind: str
    count: int = 1
    pool: str = ""
    duration_s: float = 900.0
    klass: str = ""
    seq: int = 0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}"
            )


@dataclass
class FaultStorm:
    """The expanded, time-ordered fault schedule of one run."""

    events: list[FaultEvent] = field(default_factory=list)

    @classmethod
    def from_spec(cls, spec: list, seed: int) -> "FaultStorm":
        """Expand a scenario's ``faults`` list deterministically.

        Entries with ``t`` are taken as-is; entries with
        ``start``/``end``/``events`` expand into that many events at
        seeded-uniform times inside the window. Event order is
        ``(t, seq)`` with ``seq`` assigned in expansion order, so ties
        resolve identically run to run."""
        rng = random.Random(seed)
        out: list[FaultEvent] = []
        seq = 0
        for entry in spec or []:
            kind = str(entry.get("kind", ""))
            common = {
                "kind": kind,
                "count": int(entry.get("count", 1)),
                "pool": str(entry.get("pool", "")),
                "duration_s": float(entry.get("duration_s", 900.0)),
                "klass": str(entry.get("klass", "")),
            }
            if "t" in entry:
                out.append(FaultEvent(t=float(entry["t"]), seq=seq, **common))
                seq += 1
                continue
            start = float(entry.get("start", 0.0))
            end = float(entry.get("end", start))
            n = int(entry.get("events", 1))
            times = sorted(rng.uniform(start, end) for _ in range(n))
            for t in times:
                out.append(FaultEvent(t=t, seq=seq, **common))
                seq += 1
        out.sort(key=lambda e: (e.t, e.seq))
        return cls(events=out)

    def __iter__(self):
        return iter(self.events)

    def __len__(self) -> int:
        return len(self.events)
