"""Causal multi-head attention with GQA, TPU-first.

Kernel selection (``impl``):

* ``"pallas"`` — the Pallas TPU flash-attention kernel
  (jax.experimental.pallas.ops.tpu.flash_attention): O(seq) memory, tiled
  for the MXU. Used automatically on TPU for long sequences.
* ``"splash"`` — the Pallas TPU splash-attention kernel
  (jax.experimental.pallas.ops.tpu.splash_attention): sparse-aware flash
  with *native GQA* — KV heads are shared across query-head groups inside
  the kernel, so the 4x ``_repeat_kv`` HBM blow-up the flash path pays at
  Llama-3 shapes (32 q-heads over 8 kv-heads) disappears. This is the
  production MaxText kernel.
* ``"xla"`` — plain einsum softmax attention. XLA fuses this well for short
  sequences and it runs everywhere (CPU tests); also the numerical
  reference the pallas path is tested against.
* ``"auto"`` — splash on TPU when shapes allow (head_dim in {64, 128, 256},
  seq a multiple of 128 and >= 512, no packed segment_ids — the v5e sweep
  measured splash fastest at GQA shapes, docs/performance.md), else xla.
  The flash kernel is explicit-opt-in via ``"pallas"``.

All paths compute softmax in float32 and accept grouped KV heads
(n_kv_heads <= n_heads, Llama-3 GQA).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, d] -> [b, s, kv_heads*n_rep, d]"""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def xla_attention(
    q: jnp.ndarray,  # [b, s, h, d]
    k: jnp.ndarray,  # [b, s, kv_h, d]
    v: jnp.ndarray,
    causal: bool = True,
    segment_ids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Plain einsum softmax attention (f32 softmax, GQA via KV repeat);
    runs everywhere and is the numerical reference for the kernels."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    s_q, s_k = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _fit_block(requested: int, seq: int) -> int:
    """Largest multiple-of-128 divisor of ``seq`` that is <= ``requested``
    (clamped up to the 128-lane minimum) — both TPU kernels require blocks
    that divide the sequence and are lane multiples. 0 = no valid block
    (seq is not a multiple of 128)."""
    blk = (min(max(requested, 128), seq) // 128) * 128
    while blk >= 128 and seq % blk:
        blk -= 128
    return blk if blk >= 128 else 0


def _pallas_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    return d in (64, 128, 256) and s_q % 128 == 0 and s_k % 128 == 0 and s_q >= 512


def pallas_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
) -> jnp.ndarray:
    """block_q/block_kv (0 = kernel defaults) tune the flash tiling.
    Profiling showed the default 128-blocks run the MXU half-empty at
    head_dim 64 (docs/performance.md) — larger blocks amortize that."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)

    kwargs = {}
    bq = bk = 0
    if block_q or block_kv:
        # 0 from _fit_block means 'no valid custom block, use defaults'
        bq = _fit_block(block_q or 128, q.shape[1])
        bk = _fit_block(block_kv or 128, k.shape[1])
    if bq and bk:  # only pass tiling the kernel will accept
        kwargs["block_sizes"] = BlockSizes(
            block_q=bq,
            block_k_major=bk,
            block_k=bk,
            block_b=1,
            block_q_major_dkv=bq,
            block_k_major_dkv=bk,
            block_k_dkv=bk,
            block_q_dkv=bq,
            block_k_major_dq=bk,
            block_k_dq=bk,
            block_q_dq=bq,
        )
    # pallas kernel takes [b, h, s, d]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=q.shape[-1] ** -0.5,
        **kwargs,
    )
    return out.transpose(0, 2, 1, 3)


def splash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
    segment_ids: Optional[jnp.ndarray] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Splash attention: GQA-native flash (no KV head repeat).

    KV stays at ``n_kv_heads`` all the way into the kernel — at Llama-3
    GQA ratios that is 4x less KV HBM traffic than ``pallas_attention``'s
    ``_repeat_kv``. ``interpret=True`` runs the kernel in the Pallas
    interpreter so CPU tests can cover this path.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        BlockSizes,
        CausalMask,
        FullMask,
        MultiHeadMask,
        SegmentIds,
        make_splash_mha,
    )

    b, s_q, h, d = q.shape
    s_k = k.shape[1]

    bq = _fit_block(block_q or 512, s_q)
    bkv = _fit_block(block_kv or 1024, s_k)
    if not (bq and bkv):
        # _fit_block only fails when the sequence has no multiple-of-128
        # divisor, i.e. seq itself is not a multiple of 128
        raise ValueError(
            "splash attention needs sequence lengths that are multiples"
            f" of 128; got q_seq={s_q}, kv_seq={s_k}"
            " (use impl='xla' for ragged shapes)"
        )
    one_head = CausalMask((s_q, s_k)) if causal else FullMask((s_q, s_k))
    mask = MultiHeadMask([one_head] * h)
    kernel = make_splash_mha(
        mask,
        head_shards=1,
        q_seq_shards=1,
        block_sizes=BlockSizes(
            block_q=bq,
            block_kv=bkv,
            block_kv_compute=bkv,
            block_q_dkv=bq,
            block_kv_dkv=bkv,
            block_kv_dkv_compute=bkv,
            block_q_dq=bq,
            block_kv_dq=bkv,
        ),
        interpret=interpret,
    )
    seg = None
    if segment_ids is not None:
        seg = SegmentIds(q=segment_ids, kv=segment_ids)
    # kernel shapes: q [h, s, d], k/v [kv_h, s, d]; sm scale is the
    # caller's job (fold into q — cheaper than scaling the logits)
    out = jax.vmap(kernel, in_axes=(0, 0, 0, 0 if seg is not None else None))(
        q.transpose(0, 2, 1, 3) * (d**-0.5),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        seg,
    )
    return out.transpose(0, 2, 1, 3)


def _shard_wrap(kernel, q, k, v, segment_ids, mesh, batch_axes, head_axis):
    """Run a Pallas kernel under shard_map when the mesh shards its inputs.

    Mosaic lowering demands a FULLY-manual axis context (partial-manual is
    rejected with "Mosaic kernels cannot be automatically partitioned", see
    jax/_src/tpu_custom_call.py), so the wrap manualizes every mesh axis
    not already bound by a parent shard_map. Attention is embarrassingly
    parallel over batch and heads: batch shards over (dp, fsdp), heads over
    tp, the sequence axis stays whole (resharded at entry if the residual
    stream was sp-sharded), and nothing else moves — no collectives inside;
    fsdp/tp weight collectives stay outside, handled by the partitioner.

    Returns None when the shapes don't divide the mesh (caller falls back
    to xla attention, which partitions automatically).
    """
    sizes = dict(mesh.shape)
    if all(s == 1 for s in sizes.values()):
        # single-device mesh (the single-chip bench): nothing to partition
        return kernel(q, k, v, segment_ids)
    from torchx_tpu.parallel.mesh import manual_axes

    parent_manual = set(manual_axes())
    batch_axes = tuple(
        a for a in batch_axes if sizes.get(a, 1) > 1 and a not in parent_manual
    )
    if head_axis in parent_manual or sizes.get(head_axis, 1) <= 1:
        head_axis = None

    batch_div = 1
    for a in batch_axes:
        batch_div *= sizes[a]
    head_div = sizes.get(head_axis, 1) if head_axis else 1
    if (
        q.shape[0] % batch_div
        or q.shape[2] % head_div
        or k.shape[2] % head_div
    ):
        return None  # shapes don't divide the mesh: xla fallback

    qkv_spec = P(batch_axes or None, None, head_axis, None)
    seg_spec = P(batch_axes or None, None)
    # Mosaic requires every mesh axis manual: bind all axes a parent
    # shard_map hasn't (size-1 and unused axes just replicate)
    manual = frozenset(sizes) - frozenset(parent_manual)
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        kernel,
        in_specs=(
            qkv_spec,
            qkv_spec,
            qkv_spec,
            seg_spec if segment_ids is not None else None,
        ),
        out_specs=qkv_spec,
        axis_names=manual,
        check_vma=False,
        **(dict(mesh=None) if parent_manual else dict(mesh=mesh)),
    )
    return fn(q, k, v, segment_ids)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    segment_ids: Optional[jnp.ndarray] = None,
    impl: str = "auto",
    block_q: int = 0,
    block_kv: int = 0,
    mesh=None,
) -> jnp.ndarray:
    """[b, s, heads, head_dim] x3 -> [b, s, heads, head_dim].

    ``mesh`` (a jax.sharding.Mesh) must be passed when batch or heads are
    sharded and a Pallas kernel may be selected: Mosaic kernels cannot be
    automatically partitioned, so the kernel runs under a shard_map over
    the (dp, fsdp) batch axes and the tp head axis.
    """
    if impl == "pallas" and segment_ids is not None:
        raise ValueError(
            "the pallas flash-attention path does not support segment_ids;"
            " use impl='xla' (or 'auto', which falls back) for packed"
            " cross-document masking"
        )
    use_splash = impl == "splash" or (
        # measured fastest on TPU (v5e sweep, docs/performance.md): splash
        # beats the flash kernel at GQA shapes (no KV repeat) — 46.9% vs
        # 39.6% MFU at llama3_1b — so "auto" prefers it when shapes allow
        impl == "auto"
        and segment_ids is None
        and _on_tpu()
        and _pallas_ok(q, k)
    )
    if use_splash or impl == "pallas":
        if use_splash:

            def kernel(q, k, v, seg):  # noqa: ANN001
                return splash_attention(
                    q, k, v, causal=causal, block_q=block_q,
                    block_kv=block_kv, segment_ids=seg,
                )
        else:

            def kernel(q, k, v, seg):  # noqa: ANN001
                return pallas_attention(
                    q, k, v, causal=causal, block_q=block_q, block_kv=block_kv
                )

        if mesh is None:
            return kernel(q, k, v, segment_ids)
        out = _shard_wrap(
            kernel, q, k, v, segment_ids, mesh, ("dp", "fsdp"), "tp"
        )
        if out is not None:
            return out
        if impl != "auto":
            raise ValueError(
                f"impl={impl!r}: batch {q.shape[0]} / heads "
                f"{q.shape[2]} do not divide the mesh's dp*fsdp / tp axes; "
                "Pallas kernels need divisible shapes (use impl='auto' to "
                "fall back to xla attention)"
            )
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
