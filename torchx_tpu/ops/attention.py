"""Causal multi-head attention with GQA, TPU-first.

Kernel selection (``impl``):

* ``"pallas"`` — the Pallas TPU flash-attention kernel
  (jax.experimental.pallas.ops.tpu.flash_attention): O(seq) memory, tiled
  for the MXU. Used automatically on TPU for long sequences.
* ``"xla"`` — plain einsum softmax attention. XLA fuses this well for short
  sequences and it runs everywhere (CPU tests); also the numerical
  reference the pallas path is tested against.
* ``"auto"`` — pallas on TPU when shapes allow (head_dim multiple of 128,
  seq multiple of the block size), else xla.

All paths compute softmax in float32 and accept grouped KV heads
(n_kv_heads <= n_heads, Llama-3 GQA).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, d] -> [b, s, kv_heads*n_rep, d]"""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def xla_attention(
    q: jnp.ndarray,  # [b, s, h, d]
    k: jnp.ndarray,  # [b, s, kv_h, d]
    v: jnp.ndarray,
    causal: bool = True,
    segment_ids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    s_q, s_k = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _pallas_ok(q: jnp.ndarray, k: jnp.ndarray) -> bool:
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    return d in (64, 128, 256) and s_q % 128 == 0 and s_k % 128 == 0 and s_q >= 512


def pallas_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 0,
    block_kv: int = 0,
) -> jnp.ndarray:
    """block_q/block_kv (0 = kernel defaults) tune the flash tiling.
    Profiling showed the default 128-blocks run the MXU half-empty at
    head_dim 64 (docs/performance.md) — larger blocks amortize that."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    def sanitize(requested: int, seq: int) -> int:
        """Largest multiple-of-128 divisor of seq that is <= requested —
        the kernel requires blocks to divide the sequence and be lane
        multiples; 0 means 'no valid custom block, use defaults'."""
        b = (min(requested, seq) // 128) * 128
        while b >= 128 and seq % b:
            b -= 128
        return b if b >= 128 else 0

    kwargs = {}
    bq = bk = 0
    if block_q or block_kv:
        bq = sanitize(block_q or 128, q.shape[1])
        bk = sanitize(block_kv or 128, k.shape[1])
    if bq and bk:  # only pass tiling the kernel will accept
        kwargs["block_sizes"] = BlockSizes(
            block_q=bq,
            block_k_major=bk,
            block_k=bk,
            block_b=1,
            block_q_major_dkv=bq,
            block_k_major_dkv=bk,
            block_k_dkv=bk,
            block_q_dkv=bq,
            block_k_major_dq=bk,
            block_k_dq=bk,
            block_q_dq=bq,
        )
    # pallas kernel takes [b, h, s, d]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=q.shape[-1] ** -0.5,
        **kwargs,
    )
    return out.transpose(0, 2, 1, 3)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    segment_ids: Optional[jnp.ndarray] = None,
    impl: str = "auto",
    block_q: int = 0,
    block_kv: int = 0,
) -> jnp.ndarray:
    """[b, s, heads, head_dim] x3 -> [b, s, heads, head_dim]."""
    if impl == "pallas" and segment_ids is not None:
        raise ValueError(
            "the pallas flash-attention path does not support segment_ids;"
            " use impl='xla' (or 'auto', which falls back) for packed"
            " cross-document masking"
        )
    if impl == "pallas" or (
        impl == "auto"
        and segment_ids is None
        and _on_tpu()
        and _pallas_ok(q, k)
    ):
        return pallas_attention(
            q, k, v, causal=causal, block_q=block_q, block_kv=block_kv
        )
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
