"""Fused Pallas training kernels: flash attention and RMSNorm(+residual).

This module is the ``--kernels pallas`` hot path (ISSUE 20 / the 60%-MFU
push). It owns two hand-written Mosaic kernels, both testable on CPU via
the Pallas interpreter:

* :func:`flash_attention` — tiled online-softmax attention. The score
  matrix is never materialized: the kv-sequential grid keeps one
  ``[block_q, block_kv]`` tile of logits live in VMEM, carrying the
  running row-max ``m``, denominator ``l`` and f32 accumulator across kv
  blocks (the standard flash recurrence). The backward is the standard
  two-kernel flash backward: ``delta = rowsum(dO * O)`` precomputed, one
  kv-sequential kernel accumulating ``dq``, one q-sequential kernel
  accumulating ``dk``/``dv`` — logits are recomputed from the saved
  logsumexp, so residual memory stays O(seq).
* :func:`rms_norm_residual` — residual add + RMSNorm in one VMEM pass:
  ``s = x + residual`` (input dtype, bitwise-identical to the unfused
  add), ``y = rms_norm(s) * w`` in f32. Returns both ``y`` and ``s`` (the
  stream continues from ``s``). The backward reuses the fused dx+dw
  kernel from :mod:`torchx_tpu.ops.norms` on ``s`` and routes the ``s``
  cotangent through both inputs.

Selection contract (the ``--kernels`` flag, TPX112's runtime twin):
``"pallas"`` compiles Mosaic on TPU and silently resolves to the
reference ops anywhere else; ``"interpret"`` runs the same kernels in the
Pallas interpreter (CPU parity tests); ``"reference"`` never enters this
module. :func:`flash_attention` returns ``None`` whenever gating fails —
untileable head_dim / ragged sequence / mesh that does not divide — and
the caller falls back to :func:`torchx_tpu.ops.attention.attention`;
:func:`rms_norm_residual` degrades internally to the plain-XLA math with
identical semantics.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchx_tpu.ops.attention import _fit_block, _repeat_kv, _shard_wrap
from torchx_tpu.ops.norms import _bwd_pallas, _pick_rows, _rms_norm_fwd_math

#: Same "already softmax-dead" constant the xla reference uses.
NEG_INF = -1e30

#: head dims the flash kernels tile on the MXU (lane-dim friendly).
FLASH_HEAD_DIMS = (64, 128, 256)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def flash_shapes_ok(s_q: int, s_k: int, head_dim: int) -> bool:
    """Static gate for the fused flash kernels: lane-tileable head dim,
    128-multiple self-attention sequences. (TPX112 duplicates this check
    statically — analyze never imports jax.)"""
    return (
        head_dim in FLASH_HEAD_DIMS
        and s_q == s_k
        and s_q % 128 == 0
        and s_q >= 128
    )


def norm_shapes_ok(d: int) -> bool:
    """Static gate for the fused norm kernel: lane-aligned feature dim."""
    return d % 128 == 0


# ---------------------------------------------------------------------------
# flash attention forward
# ---------------------------------------------------------------------------


def _dot(a, b, dims):
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32
    )


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *, scale, causal, bq, bk
):
    """One (batch*head, q-block, kv-block) grid cell. The kv axis is the
    innermost (sequential on TPU) grid dim, so ``m``/``l``/``acc`` output
    blocks are revisited and carry the online-softmax state across kv
    blocks — no S×S score matrix ever exists."""
    import jax.experimental.pallas as pl

    j = pl.program_id(2)
    qf = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
    kf = k_ref[0].astype(jnp.float32)  # [bk, d]
    vf = v_ref[0].astype(jnp.float32)
    s = _dot(qf, kf, ((1,), (1,)))  # [bq, bk]
    if causal:
        i = pl.program_id(1)
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)  # [bq]

    @pl.when(j == 0)
    def _init():
        p = jnp.exp(s - m_cur[:, None])
        m_ref[0] = m_cur
        l_ref[0] = jnp.sum(p, axis=-1)
        acc_ref[0] = _dot(p, vf, ((1,), (0,)))

    @pl.when(j > 0)
    def _update():
        m_prev = m_ref[0]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[0] = l_ref[0] * alpha + jnp.sum(p, axis=-1)
        acc_ref[0] = acc_ref[0] * alpha[:, None] + _dot(p, vf, ((1,), (0,)))
        m_ref[0] = m_new


def _flash_fwd(q3, k3, v3, causal, block_q, block_kv, interpret):
    """[bh, s, d] x3 -> (o_f32 [bh, s, d], lse [bh, s] f32)."""
    import jax.experimental.pallas as pl

    bh, s_q, d = q3.shape
    s_k = k3.shape[1]
    bq = _fit_block(block_q or 512, s_q)
    bk = _fit_block(block_kv or 512, s_k)
    scale = d**-0.5
    acc, m, l = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, s_q // bq, s_k // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, bq), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_q), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_q), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    # Normalization outside the kernel avoids a last-kv-block branch;
    # causal rows always see kv block 0, so l > 0 everywhere.
    return acc / l[:, :, None], m + jnp.log(l)


# ---------------------------------------------------------------------------
# flash attention backward (standard two-kernel flash bwd)
# ---------------------------------------------------------------------------


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
    scale, causal, bq, bk,
):
    import jax.experimental.pallas as pl

    j = pl.program_id(2)
    qf = q_ref[0].astype(jnp.float32)
    kf = k_ref[0].astype(jnp.float32)
    vf = v_ref[0].astype(jnp.float32)
    dof = do_ref[0].astype(jnp.float32)
    s = _dot(qf * scale, kf, ((1,), (1,)))
    if causal:
        i = pl.program_id(1)
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, None])  # exact softmax from saved lse
    dp = _dot(dof, vf, ((1,), (1,)))  # [bq, bk]
    ds = p * (dp - delta_ref[0][:, None])
    dq_tile = _dot(ds, kf, ((1,), (0,))) * scale

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = dq_tile

    @pl.when(j > 0)
    def _acc():
        dq_ref[0] += dq_tile


def _flash_dkv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, dk_ref, dv_ref, *,
    scale, causal, bq, bk,
):
    import jax.experimental.pallas as pl

    i = pl.program_id(2)  # q blocks sequential here
    j = pl.program_id(1)
    qf = q_ref[0].astype(jnp.float32)
    kf = k_ref[0].astype(jnp.float32)
    vf = v_ref[0].astype(jnp.float32)
    dof = do_ref[0].astype(jnp.float32)
    s = _dot(qf * scale, kf, ((1,), (1,)))  # [bq, bk]
    if causal:
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, None])
    dv_tile = _dot(p, dof, ((0,), (0,)))  # [bk, d]
    dp = _dot(dof, vf, ((1,), (1,)))
    ds = p * (dp - delta_ref[0][:, None])
    dk_tile = _dot(ds, qf, ((0,), (0,))) * scale  # [bk, d]

    @pl.when(i == 0)
    def _init():
        dk_ref[0] = dk_tile
        dv_ref[0] = dv_tile

    @pl.when(i > 0)
    def _acc():
        dk_ref[0] += dk_tile
        dv_ref[0] += dv_tile


def _flash_bwd(q3, k3, v3, o_f32, lse, do, causal, block_q, block_kv, interpret):
    import jax.experimental.pallas as pl

    bh, s_q, d = q3.shape
    s_k = k3.shape[1]
    bq = _fit_block(block_q or 512, s_q)
    bk = _fit_block(block_kv or 512, s_k)
    scale = d**-0.5
    delta = jnp.sum(do.astype(jnp.float32) * o_f32, axis=-1)  # [bh, s_q]

    qkv_spec = lambda which: pl.BlockSpec(  # noqa: E731
        (1, bq, d) if which == "q" else (1, bk, d),
        (lambda b, i, j: (b, i, 0)) if which == "q" else (lambda b, i, j: (b, j, 0)),
    )
    row_spec = pl.BlockSpec((1, bq), lambda b, i, j: (b, i))
    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, s_q // bq, s_k // bk),  # kv sequential: dq accumulates
        in_specs=[
            qkv_spec("q"), qkv_spec("k"), qkv_spec("k"),
            qkv_spec("q"), row_spec, row_spec,
        ],
        out_specs=[qkv_spec("q")],
        out_shape=[jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do, lse, delta)[0]

    # dkv grid swaps roles: q blocks are innermost/sequential, the dk/dv
    # output blocks at kv position j are revisited across q blocks.
    q_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    row_spec_t = pl.BlockSpec((1, bq), lambda b, j, i: (b, i))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, s_k // bk, s_q // bq),
        in_specs=[q_spec, q_spec, row_spec_t, row_spec_t, kv_spec, kv_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_k, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, do, lse, delta, k3, v3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, causal, block_q, block_kv, interpret):
    o_f32, _ = _flash_fwd(q3, k3, v3, causal, block_q, block_kv, interpret)
    return o_f32.astype(q3.dtype)


def _flash_vjp_fwd(q3, k3, v3, causal, block_q, block_kv, interpret):
    o_f32, lse = _flash_fwd(q3, k3, v3, causal, block_q, block_kv, interpret)
    return o_f32.astype(q3.dtype), (q3, k3, v3, o_f32, lse)


def _flash_vjp_bwd(causal, block_q, block_kv, interpret, res, do):
    q3, k3, v3, o_f32, lse = res
    dq, dk, dv = _flash_bwd(
        q3, k3, v3, o_f32, lse, do, causal, block_q, block_kv, interpret
    )
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,  # [b, s, h, d]
    k: jnp.ndarray,  # [b, s, kv_h, d]
    v: jnp.ndarray,
    causal: bool = True,
    kernels: str = "pallas",
    block_q: int = 0,
    block_kv: int = 0,
    mesh=None,
) -> Optional[jnp.ndarray]:
    """Fused flash attention, or ``None`` when gating says "fall back".

    ``None`` is returned (never raised) when: ``kernels`` does not select
    this module, ``"pallas"`` was asked for off-TPU (the reference ops are
    faster than the interpreter there — TPX112's warning), the shapes fail
    :func:`flash_shapes_ok`, or the mesh does not divide batch/heads. The
    caller keeps the reference path as the single fallback.
    """
    if kernels not in ("pallas", "interpret"):
        return None
    if kernels == "pallas" and not _on_tpu():
        return None
    if not flash_shapes_ok(q.shape[1], k.shape[1], q.shape[-1]):
        return None
    if q.shape[2] % k.shape[2]:
        return None
    interpret = kernels == "interpret"
    n_rep = q.shape[2] // k.shape[2]

    def kernel(q4, k4, v4, seg):  # noqa: ANN001 (matches _shard_wrap)
        k4 = _repeat_kv(k4, n_rep)
        v4 = _repeat_kv(v4, n_rep)
        b, s, h, d = q4.shape
        to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)  # noqa: E731
        o3 = _flash(
            to3(q4), to3(k4), to3(v4), causal, block_q, block_kv, interpret
        )
        return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    if mesh is None or all(s == 1 for s in dict(mesh.shape).values()):
        return kernel(q, k, v, None)
    # may return None when batch/heads don't divide the mesh: fall back
    return _shard_wrap(kernel, q, k, v, None, mesh, ("dp", "fsdp"), "tp")


# ---------------------------------------------------------------------------
# fused residual-add + RMSNorm
# ---------------------------------------------------------------------------


def _rms_norm_residual_math(x, res, weight, eps):
    """Reference path: exactly the unfused op sequence, so the fused
    kernels can be parity-tested bitwise against it."""
    s = x + res
    return _rms_norm_fwd_math(s, weight, eps), s


def _norm_res_kernel(x_ref, r_ref, w_ref, y_ref, s_ref, *, eps: float):
    s = x_ref[...] + r_ref[...]  # input dtype: bitwise == unfused add
    s_ref[...] = s
    sf = s.astype(jnp.float32)
    # reciprocal(sqrt(...)) rather than rsqrt: bitwise-identical to
    # _rms_norm_fwd_math under the interpreter (the parity tests check it)
    rrms = jnp.reciprocal(
        jnp.sqrt(jnp.mean(sf * sf, axis=-1, keepdims=True) + eps)
    )
    y_ref[...] = ((sf * rrms) * w_ref[...].astype(jnp.float32)).astype(
        y_ref.dtype
    )


def _norm_res_pallas(x2d, r2d, weight, eps, interpret):
    """-> (y [n, d], s [n, d]) or None when the shard doesn't tile."""
    import jax.experimental.pallas as pl

    n, d = x2d.shape
    rows = _pick_rows(n, d)
    if rows == 0 or d % 128:
        return None
    return pl.pallas_call(
        functools.partial(_norm_res_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2d.dtype),
            jax.ShapeDtypeStruct((n, d), x2d.dtype),
        ],
        interpret=interpret,
    )(x2d, r2d, weight.reshape(1, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rms_norm_residual_fused(x, res, weight, eps, interpret):
    return _rms_norm_residual_math(x, res, weight, eps)


def _nr_fwd(x, res, weight, eps, interpret):
    d = x.shape[-1]
    out = _norm_res_pallas(
        x.reshape(-1, d), res.reshape(-1, d), weight, eps, interpret
    )
    if out is None:  # untileable shard: plain math, same values
        y, s = _rms_norm_residual_math(x, res, weight, eps)
    else:
        y, s = (a.reshape(x.shape) for a in out)
    return (y, s), (s, weight)


def _nr_bwd(eps, interpret, resids, cot):
    s, weight = resids
    dy, ds_out = cot
    d = s.shape[-1]
    # the dx+dw kernel from ops/norms runs on the summed stream s; the
    # extra ds_out cotangent (s is also an output) adds straight through
    dx2d, dw = _bwd_pallas(
        s.reshape(-1, d), dy.reshape(-1, d), weight, eps, interpret=interpret
    )
    ds = dx2d.reshape(s.shape).astype(s.dtype) + ds_out
    return ds, ds, dw.astype(weight.dtype)


_rms_norm_residual_fused.defvjp(_nr_fwd, _nr_bwd)


def rms_norm_residual(
    x: jnp.ndarray,
    residual: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    kernels: str = "reference",
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``s = x + residual; y = rms_norm(s) * weight`` -> ``(y, s)``.

    Unlike :func:`flash_attention` this never returns ``None``: every
    gating failure degrades internally to the reference op sequence with
    identical values, so call sites need no fallback branch. ``mesh``
    plays the same role as in :func:`torchx_tpu.ops.norms.rms_norm` —
    Mosaic kernels cannot be auto-partitioned, so a sharded stream runs
    the kernel under a full-manual shard_map (weight replicated, its grad
    summed by the shard_map transpose).
    """
    if kernels not in ("pallas", "interpret"):
        return _rms_norm_residual_math(x, residual, weight, eps)
    if kernels == "pallas" and not _on_tpu():
        return _rms_norm_residual_math(x, residual, weight, eps)
    from torchx_tpu.parallel.mesh import manual_axes

    if manual_axes():
        # inside a parent manual region (pipeline stage): a nested
        # shard_map would rebind axes — reference path, every mode
        return _rms_norm_residual_math(x, residual, weight, eps)
    if not norm_shapes_ok(x.shape[-1]):
        return _rms_norm_residual_math(x, residual, weight, eps)
    interpret = kernels == "interpret"
    if mesh is None or all(s == 1 for s in dict(mesh.shape).values()):
        return _rms_norm_residual_fused(x, residual, weight, eps, interpret)

    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh.shape)
    batch_axes = tuple(a for a in ("dp", "fsdp") if sizes.get(a, 1) > 1)
    batch_div = 1
    for a in batch_axes:
        batch_div *= sizes[a]
    seq_axis = (
        "sp"
        if x.ndim == 3
        and sizes.get("sp", 1) > 1
        and x.shape[1] % sizes["sp"] == 0
        else None
    )
    if x.ndim != 3 or (batch_div > 1 and x.shape[0] % batch_div):
        return _rms_norm_residual_math(x, residual, weight, eps)
    x_spec = P(batch_axes or None, seq_axis, None)
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        lambda xs, rs, ws: _rms_norm_residual_fused(xs, rs, ws, eps, interpret),
        mesh=mesh,
        in_specs=(x_spec, x_spec, P(None)),
        out_specs=(x_spec, x_spec),
        axis_names=frozenset(sizes),  # Mosaic needs a fully-manual context
        check_vma=False,
    )
    return fn(x, residual, weight)


def resolve_kernels(requested: str) -> str:
    """Resolve a ``--kernels`` request against the runtime platform:
    ``"pallas"`` off-TPU becomes ``"reference"`` (what TPX112 warns
    about at launch time); everything else passes through."""
    if requested == "pallas" and not _on_tpu():
        return "reference"
    return requested
