"""Normalization ops.

RMSNorm computes in float32 regardless of input dtype (bf16 squares
underflow badly) and casts back — the standard TPU-stable recipe. The
*forward* needs no custom kernel: XLA fuses the whole thing into the
surrounding matmul's epilogue.

The *backward* is a different story (round-4 xprof, docs/performance.md):
autodiff of ``x_hat * w`` emits the weight-grad ``sum_{b,s}(dy * x_hat)``
as a separate ``[d]``-output reduction dot per layer. XLA schedules those
on the MXU as skinny matmuls — ~6% of the training step re-reading
activations the dx pass already read. :func:`rms_norm` therefore carries a
custom VJP whose backward is one fused Pallas kernel producing ``dx`` and
``dw`` in a single read of ``x``/``dy`` (grid-sequential f32 accumulation
of ``dw``), used on TPU when shapes allow; elsewhere the plain-XLA
backward applies (identical math, f32 accumulation, reduction order aside).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_norm_fwd_math(
    x: jnp.ndarray, weight: jnp.ndarray, eps: float
) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    rrms = jnp.reciprocal(
        jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    )
    return ((xf * rrms) * weight.astype(jnp.float32)).astype(dtype)


def _bwd_math(x, weight, dy, eps):
    """Reference backward (pure XLA): returns (dx, dw[f32])."""
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    rrms = jnp.reciprocal(
        jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    )
    xhat = xf * rrms
    dw = jnp.sum(dyf * xhat, axis=tuple(range(x.ndim - 1)))
    dxhat = dyf * wf
    c = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = (rrms * (dxhat - xhat * c)).astype(x.dtype)
    return dx, dw


def _bwd_kernel(x_ref, dy_ref, w_ref, dx_ref, dw_ref, *, eps: float):
    """Fused dx + dw for one [rows, d] tile; dw accumulates across the
    sequential TPU grid."""
    import jax.experimental.pallas as pl

    xf = x_ref[...].astype(jnp.float32)
    dyf = dy_ref[...].astype(jnp.float32)
    wf = w_ref[...].astype(jnp.float32)  # [1, d]
    rrms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xhat = xf * rrms
    dxhat = dyf * wf
    c = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rrms * (dxhat - xhat * c)).astype(dx_ref.dtype)
    dw_tile = jnp.sum(dyf * xhat, axis=0, keepdims=True)  # [1, d] f32

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[...] = dw_tile

    @pl.when(pl.program_id(0) > 0)
    def _acc():
        dw_ref[...] += dw_tile


def _pick_rows(n: int, d: int = 2048) -> int:
    """Largest row-tile that divides ``n`` and fits scoped VMEM (~16M):
    budget ~32 bytes/element — 3 bf16 io blocks double-buffered plus ~5 f32
    temporaries (xf/dyf/xhat/dxhat/products) the compiler keeps live."""
    for r in (1024, 512, 256, 128, 64, 32, 16, 8):
        if n % r == 0 and r * d * 22 <= 12 * 1024 * 1024:
            return r
    return 0


def _bwd_pallas(x2d, dy2d, weight, eps: float, interpret: bool = False):
    """-> (dx [n, d], dw [d] f32) via the fused kernel."""
    import jax.experimental.pallas as pl

    n, d = x2d.shape
    rows = _pick_rows(n, d)
    if rows == 0 or d % 128:
        # untileable shard (interpret mode bypasses _fused_ok, and the
        # sharded path re-tiles on PER-SHARD rows): plain math, same grads
        return _bwd_math(x2d, weight, dy2d, eps)
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2d.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, dy2d, weight.reshape(1, d))
    return dx, dw[0]


def _fused_ok(x: jnp.ndarray) -> bool:
    """TPU only, lane-aligned feature dim, tileable row count, and not
    inside a shard_map manual region (there the plain backward keeps the
    well-tested semantics — the partitioner handles the skinny dots)."""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False
    from torchx_tpu.parallel.mesh import manual_axes

    in_manual = bool(manual_axes())
    n = 1
    for s in x.shape[:-1]:
        n *= s
    return (
        on_tpu
        and not in_manual
        and x.ndim >= 2
        and x.shape[-1] % 128 == 0
        and _pick_rows(n, x.shape[-1]) > 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_fused(x, weight, eps, interpret):
    return _rms_norm_fwd_math(x, weight, eps)


def _fused_fwd(x, weight, eps, interpret):
    return _rms_norm_fwd_math(x, weight, eps), (x, weight)


def _fused_bwd(eps, interpret, res, dy):
    x, weight = res
    d = x.shape[-1]
    dx2d, dw = _bwd_pallas(
        x.reshape(-1, d), dy.reshape(-1, d), weight, eps, interpret=interpret
    )
    # Under shard_map the weight enters replicated (P(None)) and the
    # shard_map transpose psums its cotangent over the axes the region's
    # specs shard rows over — measured: a mesh sharding rows over
    # (dp, fsdp, sp) sums those shards exactly once, and axes that merely
    # replicate the rows (tp/ep) are treated as carrying replicated
    # cotangents (which these are). The local row-shard dw is therefore
    # exactly right as-is.
    return dx2d.reshape(x.shape), dw.astype(weight.dtype)


_rms_norm_fused.defvjp(_fused_fwd, _fused_bwd)


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    fused: str = "auto",
    mesh=None,
) -> jnp.ndarray:
    """RMS-normalize ``x`` over its last axis and scale by ``weight``.

    ``fused`` selects the backward:

    * "auto" (default) — the plain XLA backward. Measured on v5e-1
      (round 5, docs/performance.md): the weight-grad reductions already
      lower as multiply-reduce fusions at ~1.3% of the step, and the
      Pallas kernel — while itself nearly free (0.01% of step) — costs
      ~0.3pp MFU in fusion opportunities at the custom_vjp boundary, so
      plain is the measured-fastest default. Overridable per-process with
      ``TPX_FUSED_NORM``.
    * "pallas" — force the fused dx+dw kernel (re-evaluate at batch >= 8
      or on hardware where the reductions lower as skinny MXU dots).
    * "interpret" — the kernel in the Pallas interpreter (CPU tests).
    * "never" — plain XLA backward, no env override.

    ``mesh`` must be passed when batch/seq may be sharded and the fused
    kernel is wanted: like every Mosaic kernel it cannot be automatically
    partitioned, so on a multi-device mesh it runs under a full-manual
    shard_map — [b, s, d] x over (dp, fsdp) x sp, weight replicated, the
    weight grad summed over the row shards by the shard_map transpose.
    """
    if fused == "auto":
        import os

        from torchx_tpu.settings import ENV_TPX_FUSED_NORM

        fused = os.environ.get(ENV_TPX_FUSED_NORM, "never")
    interpret = fused == "interpret"
    from torchx_tpu.parallel.mesh import manual_axes

    if manual_axes():
        # inside a shard_map manual region (a pipeline stage): opening a
        # nested shard_map over the concrete mesh would rebind the
        # parent's axes (rejected by Shardy) — plain backward, every mode
        return _rms_norm_fwd_math(x, weight, eps)
    if not (interpret or (fused == "pallas" and _fused_ok(x))):
        return _rms_norm_fwd_math(x, weight, eps)
    if mesh is None or all(s == 1 for s in mesh.shape.values()):
        return _rms_norm_fused(x, weight, eps, interpret)

    from jax.sharding import PartitionSpec as P

    sizes = dict(mesh.shape)
    batch_axes = tuple(a for a in ("dp", "fsdp") if sizes.get(a, 1) > 1)
    batch_div = 1
    for a in batch_axes:
        batch_div *= sizes[a]
    seq_axis = (
        "sp"
        if x.ndim == 3 and sizes.get("sp", 1) > 1 and x.shape[1] % sizes["sp"] == 0
        else None
    )
    if x.ndim != 3 or (batch_div > 1 and x.shape[0] % batch_div):
        return _rms_norm_fwd_math(x, weight, eps)  # unshardable: plain path
    x_spec = P(batch_axes or None, seq_axis, None)
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        lambda xs, ws: _rms_norm_fused(xs, ws, eps, interpret),
        mesh=mesh,
        in_specs=(x_spec, P(None)),
        out_specs=x_spec,
        axis_names=frozenset(sizes),  # Mosaic needs a fully-manual context
        check_vma=False,
    )
    return fn(x, weight)
