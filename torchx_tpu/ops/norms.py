"""Normalization ops.

RMSNorm computes in float32 regardless of input dtype (bf16 squares
underflow badly) and casts back — the standard TPU-stable recipe. XLA fuses
the whole thing into the surrounding matmul's epilogue; no custom kernel is
warranted for a bandwidth-bound elementwise op.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    rrms = jnp.reciprocal(jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))
    return ((xf * rrms) * weight.astype(jnp.float32)).astype(dtype)
