from torchx_tpu.ops.norms import rms_norm  # noqa: F401
from torchx_tpu.ops.rope import apply_rope, rope_frequencies  # noqa: F401
from torchx_tpu.ops.attention import attention  # noqa: F401
