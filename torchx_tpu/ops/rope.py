"""Rotary position embeddings (RoPE), Llama-3 style.

Frequencies are precomputed once in float32 and closed over by the jitted
step (static across steps — no recompute in the hot loop); the rotation is
a pair of fused multiplies XLA folds into the attention projections.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, max_seq: int, theta: float = 500000.0, start=0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (cos, sin), each [max_seq, head_dim//2], float32.

    ``start`` offsets the position index (static int or traced scalar):
    sequence-sharded layouts (ring attention under a manualized ``sp``
    axis) compute the frequencies for their own shard of positions with
    ``start = axis_index("sp") * local_seq``.
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq, dtype=jnp.float32) + jnp.asarray(
        start, dtype=jnp.float32
    )
    freqs = jnp.outer(t, inv_freq)  # [seq, head_dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray,  # [batch, seq, heads, head_dim]
    cos: jnp.ndarray,  # [seq, head_dim/2] (already sliced to positions)
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate [batch, seq, heads, head_dim] by the given frequencies."""
    dtype = x.dtype
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate((x1 * c - x2 * s, x2 * c + x1 * s), axis=-1).astype(dtype)
