"""Paged (block-table) KV-cache attention for continuous-batching decode.

The dense decode cache (:mod:`torchx_tpu.models.generate`) reserves
``[L, batch, max_seq, kvh, hd]`` per sequence — worst-case ``max_seq``
whether or not the request ever decodes that far. Serving at high
concurrency wastes most of that HBM: the vLLM observation is that KV
memory should be allocated in fixed-size *blocks* as tokens actually
arrive, with a per-sequence *block table* mapping logical positions to
physical blocks in one shared pool.

This module is the device-side half: pure, jittable functions over a
fixed ``[num_blocks, block_size, kvh, hd]`` pool per layer —

* :func:`gather_kv` — block-table gather back to a contiguous
  ``[slots, S, kvh, hd]`` view (S = blocks_per_slot * block_size);
* :func:`paged_attention` — single-query-token GQA attention against the
  gathered view, masked by per-slot valid lengths;
* :func:`append_kv` — scatter one new K/V token per slot into the pool at
  its block-table position;
* :func:`write_prefill` — bulk-write a prefilled prompt's K/V into the
  blocks a slot was assigned.

Everything is static-shape (XLA compiles once per pool geometry); the
host-side allocator that assigns blocks lives in
:mod:`torchx_tpu.serve.kv_pool`. Block 0 is reserved as the trash block:
unassigned table entries point at it, writes from inactive slots land in
it, and the length mask keeps its contents out of every softmax.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Physical block index every unassigned block-table entry points at.
#: Writes from inactive/padded slots land here; masked attention never
#: reads it as valid context.
TRASH_BLOCK = 0


def gather_kv(pool: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """Gather one layer's pooled K (or V) into per-slot contiguous views.

    ``pool``: ``[num_blocks, block_size, kvh, hd]``; ``tables``:
    ``[slots, blocks_per_slot]`` int32 physical block ids. Returns
    ``[slots, blocks_per_slot * block_size, kvh, hd]`` — position ``p`` of
    slot ``i`` is ``pool[tables[i, p // bs], p % bs]``.
    """
    slots, bpr = tables.shape
    _, bs, kvh, hd = pool.shape
    g = pool[tables]  # [slots, bpr, bs, kvh, hd]
    return g.reshape(slots, bpr * bs, kvh, hd)


def paged_attention(
    q: jnp.ndarray,  # [slots, h, hd] — ONE query token per slot
    k_pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd]
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,  # [slots, blocks_per_slot] int32
    lengths: jnp.ndarray,  # [slots] int32 — valid tokens (incl. current)
) -> jnp.ndarray:
    """Single-token decode attention against the paged cache.

    GQA: query heads ``h`` fold onto ``kvh`` cache heads by repetition
    (same as the dense path's ``_cached_attention``). Positions at or
    beyond ``lengths[i]`` — unwritten block tails and every unassigned
    (trash) block — are masked out of slot ``i``'s softmax. Returns
    ``[slots, h, hd]``.
    """
    slots, h, d = q.shape
    k = gather_kv(k_pool, tables)  # [slots, S, kvh, hd]
    v = gather_kv(v_pool, tables)
    n_rep = h // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    logits = (
        jnp.einsum("shd,sthd->sht", q, k, preferred_element_type=jnp.float32)
        * d**-0.5
    )
    S = k.shape[1]
    mask = jnp.arange(S)[None, :] < lengths[:, None]  # [slots, S]
    logits = jnp.where(mask[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("sht,sthd->shd", probs, v)


def paged_attention_chunk(
    q: jnp.ndarray,  # [slots, t, h, hd] — a chunk of query tokens per slot
    k_pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd]
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,  # [slots, blocks_per_slot] int32
    positions: jnp.ndarray,  # [slots, t] int32 — absolute position of each query
) -> jnp.ndarray:
    """Multi-query-token attention against the paged cache.

    The chunked-prefill generalisation of :func:`paged_attention`: query
    ``j`` of slot ``i`` sits at absolute position ``positions[i, j]`` and
    attends causally to every cached position ``s <= positions[i, j]`` —
    which covers both a previously-cached shared prefix *and* the chunk's
    own K/V, provided the caller scattered the chunk into the pool first.
    Padded query rows produce garbage that the caller never samples.
    Returns ``[slots, t, h, hd]``.
    """
    slots, t, h, d = q.shape
    k = gather_kv(k_pool, tables)  # [slots, S, kvh, hd]
    v = gather_kv(v_pool, tables)
    n_rep = h // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    logits = (
        jnp.einsum("sqhd,skhd->shqk", q, k, preferred_element_type=jnp.float32)
        * d**-0.5
    )
    S = k.shape[1]
    mask = jnp.arange(S)[None, None, :] <= positions[:, :, None]  # [slots, t, S]
    logits = jnp.where(mask[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("shqk,skhd->sqhd", probs, v)


def scatter_kv_chunk(
    pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd]
    tables: jnp.ndarray,  # [slots, blocks_per_slot]
    positions: jnp.ndarray,  # [slots, t] — logical position of each new token
    new: jnp.ndarray,  # [slots, t, kvh, hd]
    valid: jnp.ndarray | None = None,  # [slots, t] bool — False: write trash
) -> jnp.ndarray:
    """Scatter a chunk of new K (or V) tokens per slot into table positions.

    The multi-token form of :func:`append_kv`, used by suffix prefill:
    token ``j`` of slot ``i`` lands at ``tables[i, positions[i,j] // bs]``
    offset ``positions[i,j] % bs``. ``valid`` marks real (non-padding)
    tokens; invalid ones are redirected to the trash block — their
    positions can lie past the table (bucket padding), where a clamped
    gather would otherwise alias a live block.
    """
    slots, t = positions.shape
    bs = pool.shape[1]
    block_idx = jnp.clip(positions // bs, 0, tables.shape[1] - 1)
    block_ids = jnp.take_along_axis(tables, block_idx, axis=1)  # [slots, t]
    if valid is not None:
        block_ids = jnp.where(valid, block_ids, TRASH_BLOCK)
    offsets = positions % bs
    flat_new = new.reshape(slots * t, *new.shape[2:])
    return pool.at[block_ids.reshape(-1), offsets.reshape(-1)].set(
        flat_new, mode="drop"
    )


def append_kv(
    pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd]
    tables: jnp.ndarray,  # [slots, blocks_per_slot]
    positions: jnp.ndarray,  # [slots] — logical position being written
    new: jnp.ndarray,  # [slots, kvh, hd]
) -> jnp.ndarray:
    """Scatter one new K (or V) token per slot into its table position.

    Slots whose table entry for ``positions[i] // block_size`` is the
    trash block (inactive slots) harmlessly overwrite trash; collisions
    there don't matter because nothing masked-in ever reads it.
    """
    slots = tables.shape[0]
    bs = pool.shape[1]
    block_ids = tables[jnp.arange(slots), positions // bs]  # [slots]
    offsets = positions % bs
    return pool.at[block_ids, offsets].set(new, mode="drop")


def write_prefill(
    pool: jnp.ndarray,  # [num_blocks, bs, kvh, hd]
    block_ids: jnp.ndarray,  # [n_bucket_blocks] physical ids (trash-padded)
    kv: jnp.ndarray,  # [t_bucket, kvh, hd] — t_bucket = n_bucket_blocks * bs
) -> jnp.ndarray:
    """Bulk-write a prefilled prompt's K (or V) rows into assigned blocks.

    ``kv`` covers the whole prefill bucket; rows past the true prompt
    length are garbage from padding and land either in the slot's own
    final block past its valid length (masked) or — for fully-unused
    bucket blocks — in the trash block.
    """
    nb = block_ids.shape[0]
    bs = pool.shape[1]
    chunks = kv.reshape(nb, bs, *kv.shape[1:])
    return pool.at[block_ids].set(chunks, mode="drop")
