"""Int8 quantization for TPU inference (weight-only, AQT-style).

TPU MXUs execute int8 matmuls at 2x the bf16 rate and HBM traffic halves,
so weight-only int8 is the standard first rung of the quantization ladder
(the approach AQT and JetStream take; reference torchx has no quantization
story — this is beyond-parity). Symmetric per-output-channel scales keep
the matmul a pure ``int8 x bf16`` contraction followed by one rescale:

    y = (x @ w_int8) * scale          # scale: [out] f32

Accuracy: per-channel symmetric int8 on transformer weights costs well
under 0.1 nats of perplexity at 1-8B scale; activations stay bf16 (the
risky part of full int8 is activation outliers, deferred).

Everything here is shape-polymorphic and jit-safe; tests validate
numerics on CPU, the dtype plumbing is what the TPU path needs.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# weights quantized by quantize_params: every 2D+ float leaf whose name is
# a projection matrix (the FFN/attention/head matmuls carry ~all weight
# bytes; norms/embeddings stay exact)
_QUANT_KEYS = {
    "wq", "wk", "wv", "wo",
    "w_gate", "w_up", "w_down",
    "lm_head",
}


def quantize(w: jnp.ndarray, axis: int = -1) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (int8 weights, f32 scale) with symmetric per-channel scales.

    ``axis`` is the OUTPUT-channel axis (kept exact). For stacked weights
    (ndim >= 3, e.g. scan-over-layers ``[L, in, out]``) the leading axis is
    preserved too, so every layer gets its own scales; the remaining axes
    form the quantization group.
    """
    keep = {axis % w.ndim}
    if w.ndim >= 3:
        keep.add(0)  # leading layer-stack axis: per-layer scales
    reduce_axes = tuple(i for i in range(w.ndim) if i not in keep)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return q, scale.astype(jnp.float32)


def dequantize(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):  # noqa: ANN001
    """int8 x per-channel scale -> float weights."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(
    x: jnp.ndarray,  # [..., in] bf16/f32
    q: jnp.ndarray,  # [in, out] int8
    scale: jnp.ndarray,  # [1, out] f32
    out_dtype: Any = None,  # default: x.dtype
) -> jnp.ndarray:
    """x @ dequant(q) with the rescale folded AFTER the contraction, so XLA
    lowers the inner product onto the int8 MXU path where available.
    Pass ``out_dtype=jnp.float32`` to keep the f32 accumulation (e.g. the
    lm_head, where logits must not round-trip through bf16)."""
    y = jax.lax.dot_general(
        x,
        q,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (y * scale.reshape(-1)).astype(out_dtype or x.dtype)


def quantize_params(params: Params) -> Params:
    """Quantize every projection matrix in a Llama/MoE param tree.

    Returns a tree of the same structure where each quantized leaf ``k``
    becomes a dict ``{"q": int8, "scale": f32}``; everything else is
    untouched. ~2x smaller checkpoints/HBM for the weight-dominated parts.
    """

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if (
                    k in _QUANT_KEYS
                    and isinstance(v, jnp.ndarray)
                    and jnp.issubdtype(v.dtype, jnp.floating)
                    # 2D proj or [L, in, out] layer stack; expert-stacked
                    # MoE weights (ndim >= 4) keep their einsum path exact
                    and v.ndim in (2, 3)
                ):
                    q, scale = quantize(v)
                    out[k] = {"q": q, "scale": scale}
                else:
                    out[k] = walk(v)
            return out
        return node

    return walk(params)


_AQT_DG = None


def aqt_dot_general():  # noqa: ANN201 - aqt types are an optional dep
    """Drop-in int8 TRAINING dot_general (AQT v2 ``config_v4``): forward
    and both backward dots run int8xint8->int32 on the MXU with dynamic
    symmetric per-tensor scales and a straight-through estimator.

    Measured on v5e-1 (slope-timed 4096^3 matmul): bf16 190 TFLOP/s vs
    int8 370 TOP/s — a 1.94x kernel speedup; see docs/performance.md for
    what survives at the full-model level. Serving-side weight-only int8
    (``quantize_params``) is unrelated — this path quantizes dynamically
    inside the training step and keeps master weights in bf16/f32."""
    global _AQT_DG
    if _AQT_DG is None:
        from aqt.jax.v2 import config as aqt_config

        cfg = aqt_config.config_v4(fwd_bits=8, dlhs_bits=8, drhs_bits=8)
        # deterministic rounding in the backward: config_v4 defaults the
        # gradient-side quantizers to stochastic rounding, which demands an
        # RNG key threaded through every dot (Context.key) — a plumbing
        # cost the model body shouldn't pay; the quality delta at 8 bits
        # is second-order next to per-tensor dynamic scaling
        aqt_config.set_stochastic_rounding(
            cfg,
            vjp_lhs_stochastic_rounding=False,
            vjp_rhs_stochastic_rounding=False,
            implementation="jax.uniform",
        )
        _AQT_DG = cfg
    return _AQT_DG


def maybe_matmul(
    x: jnp.ndarray,
    w: Any,
    out_dtype: Any = None,
    int8_training: bool = False,
) -> jnp.ndarray:
    """``x @ w`` that accepts either a plain matrix or a quantized
    ``{"q", "scale"}`` record — lets one model body serve both.
    ``int8_training=True`` routes plain-matrix matmuls through the AQT
    int8 training dot (quantized fwd + bwd)."""
    if isinstance(w, dict) and "q" in w:
        return int8_matmul(x, w["q"], w["scale"], out_dtype=out_dtype)
    if int8_training:
        dg = aqt_dot_general()
        y = dg(x, w, (((x.ndim - 1,), (0,)), ((), ())))
        return y.astype(out_dtype or x.dtype)
    y = x @ w
    return y.astype(out_dtype) if out_dtype is not None else y


def size_bytes(params: Params) -> int:
    """Total bytes of every leaf (quantization-savings accounting)."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
