"""Ring attention: causal attention over sequence shards (context parallel).

Long-context training shards the sequence axis across devices (`sp` mesh
axis). Attention then needs every query to see all earlier keys, which live
on other devices. Ring attention rotates KV blocks around the `sp` axis
with ``lax.ppermute`` while accumulating the softmax online (flash-style
running max / normalizer merge), so each device only ever holds one extra
KV block: O(seq/n) memory, and the permute overlaps with the block matmuls
on TPU (ICI is bidirectional; XLA pipelines the ring).

Causality note: with sequence blocks laid out contiguously (block i holds
positions [i*B, (i+1)*B)), block j contributes to queries in block i iff
j < i (fully visible) or j == i (triangular). Blocks j > i are skipped —
but in a ring every device must keep permuting to feed its neighbors, so
skipped blocks still travel; their contribution is masked out.

The public entry :func:`ring_attention` wraps the per-shard kernel in
``jax.shard_map`` over the given mesh and is differentiable end-to-end
(ppermute's transpose is the reverse permute).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(
    q: jnp.ndarray,  # [b, sq, h, d]
    k: jnp.ndarray,  # [b, sk, h, d] (kv heads already repeated)
    v: jnp.ndarray,
    mode: jnp.ndarray,  # scalar int: 0 = skip, 1 = causal (diagonal), 2 = full
    scale: float,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits-masked [b,h,sq,sk] f32, none); computes masked logits."""
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    sq, sk = q.shape[1], k.shape[1]
    tril = jnp.tril(jnp.ones((sq, sk), dtype=bool))
    mask = jnp.where(
        mode == 2,
        jnp.ones((sq, sk), dtype=bool),
        jnp.where(mode == 1, tril, jnp.zeros((sq, sk), dtype=bool)),
    )
    return jnp.where(mask[None, None], logits, _NEG_INF)


def _ring_attention_shard(
    q: jnp.ndarray,  # [b, s_local, h, d] — this device's query block
    k: jnp.ndarray,  # [b, s_local, kv_h, d]
    v: jnp.ndarray,
    axis_name: str,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard causal ring attention (runs inside shard_map)."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        b, s, h, d = k.shape
        k = jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
            b, s, h * n_rep, d
        )
        v = jnp.broadcast_to(v[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
            b, s, h * n_rep, d
        )

    b, sq, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        o, m, l, kv = carry
        k_blk, v_blk = kv
        src = (my_idx - i) % n  # global block index this kv came from
        mode = jnp.where(src == my_idx, 1, jnp.where(src < my_idx, 2, 0))
        logits = _block_attn(q, k_blk, v_blk, mode, scale)  # [b,h,sq,sk] f32
        m_blk = jnp.max(logits, axis=-1)  # [b,h,sq]
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)  # rescale old accumulator
        p = jnp.exp(logits - m_new[..., None])  # [b,h,sq,sk]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        kv_next = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (k_blk, v_blk)
        )
        return (o_new, m_new, l_new, kv_next), None

    o0 = jnp.zeros((b, h, sq, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, sq), dtype=jnp.float32)
    # checkpoint each ring step: without it, scan AD saves every block's
    # [b, h, sq, sk] f32 logits — an [n, b, h, sq, sk] stack that at 8B
    # long-context scale is tens of GB per device (measured via the AOT
    # fit: 68 GB of a 78 GB temp footprint at seq 32k, sp=8). Recomputing
    # the block logits in backward costs one extra qk matmul per block —
    # the standard blockwise-attention trade. prevent_cse=False: scan's
    # loop structure already prevents the pathological CSE, so the
    # default optimization barriers would only block fusion.
    (o, m, l, _), _ = jax.lax.scan(
        jax.checkpoint(step, prevent_cse=False), (o0, m0, l0, (k, v)), jnp.arange(n)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [b, sq, h, d]


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
) -> jnp.ndarray:
    """Causal attention over a sequence-sharded [b, s, h, d] layout.

    q/k/v are global arrays whose ``s`` axis is sharded over ``seq_axis``;
    returns output in the same layout. Works inside jit, including inside
    another shard_map's manual region (e.g. a pp pipeline stage) — but only
    when that region already manualizes ``seq_axis`` itself: the per-shard
    kernel then runs directly. Nesting a second shard_map that rebinds an
    axis the parent bound is rejected by Shardy's verifier, so the parent
    (``pipeline_apply(manual_axes={"sp"})``) must take the sequence axis
    manual alongside its own.
    """
    from torchx_tpu.parallel.mesh import manual_axes

    parent_manual = manual_axes()
    if parent_manual:
        if seq_axis in parent_manual:
            # the ambient manual region already owns the sequence axis:
            # q/k/v are per-shard views here, use the collective kernel
            # directly (no inner shard_map)
            return _ring_attention_shard(q, k, v, axis_name=seq_axis)
        raise RuntimeError(
            "ring_attention called inside a manual region "
            f"(manual axes {set(parent_manual)}) that does not include "
            f"the sequence axis {seq_axis!r}. Nesting a shard_map that "
            "rebinds parent axes is rejected by the Shardy partitioner — "
            "manualize the sequence axis in the outer shard_map instead "
            '(pipeline_apply(..., manual_axes=frozenset({"sp"}), '
            "x_spec=P(None, 'sp', None)))."
        )
    # shapes are static at trace time: drop the batch sharding when the
    # (micro)batch is too small to split over dp/fsdp — e.g. inside a
    # pipeline stage where microbatching shrank the batch axis
    batch_div = 1
    for a in batch_axes:
        batch_div *= mesh.shape.get(a, 1)
    eff_batch_axes = batch_axes if q.shape[0] % max(batch_div, 1) == 0 else ()
    spec = P(eff_batch_axes, seq_axis, head_axis, None)
    # standalone: full-manual over the concrete mesh (also keeps eager
    # calls working — partial-auto shard_map requires jit)
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        functools.partial(_ring_attention_shard, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
