"""Ulysses attention: all-to-all sequence parallelism.

The second context-parallel strategy from the checklist (alongside
:mod:`torchx_tpu.ops.ring_attention`): instead of rotating KV blocks,
Ulysses **re-shards** — an all-to-all turns the sequence-sharded layout
[b, s/P, h, d] into a head-sharded layout [b, s, h/P, d], each device runs
ordinary full attention over its head group (any kernel: here the fused
XLA path), and a second all-to-all transposes back.

Trade-offs vs ring attention: two all-to-alls instead of P ppermute hops
(cheaper on small meshes, and the inner attention is a single dense
kernel), but the head count must be divisible by the mesh axis and peak
memory holds the full sequence per device for its head group. Use ring
for very long sequences, Ulysses when heads >> mesh axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchx_tpu.ops.attention import xla_attention


def _ulysses_shard(
    q: jnp.ndarray,  # [b, s/P, h, d] local sequence shard
    k: jnp.ndarray,  # [b, s/P, kv_h, d]
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    def seq_to_heads(x: jnp.ndarray) -> jnp.ndarray:
        # [b, s/P, h, d] -> [b, s, h/P, d]: tiled all-to-all splits the head
        # axis into P groups and gathers the full sequence
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x: jnp.ndarray) -> jnp.ndarray:
        # inverse: [b, s, h/P, d] -> [b, s/P, h, d]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    q_g = seq_to_heads(q)
    k_g = seq_to_heads(k)
    v_g = seq_to_heads(v)
    out = xla_attention(q_g, k_g, v_g, causal=True)  # full seq, local heads
    return heads_to_seq(out)


def ulysses_attention(
    q: jnp.ndarray,  # [b, s, h, d] globally, s sharded over seq_axis
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
) -> jnp.ndarray:
    """Causal attention over a sequence-sharded layout via all-to-all.

    Requires n_heads and kv_heads divisible by seq_axis * head_axis sizes
    (heads stay sharded over ``head_axis`` like ring_attention; the
    all-to-all only exchanges within the seq axis).
    """
    n = mesh.shape[seq_axis]
    h_shard = mesh.shape.get(head_axis, 1) if head_axis else 1
    if q.shape[2] % (n * h_shard) or k.shape[2] % (n * h_shard):
        raise ValueError(
            f"ulysses needs heads divisible by mesh axes {seq_axis}={n}"
            f" x {head_axis}={h_shard};"
            f" got q heads {q.shape[2]}, kv heads {k.shape[2]}"
        )
    spec = P(batch_axes, seq_axis, head_axis, None)
    from torchx_tpu.parallel.mesh import shard_map as tpx_shard_map

    fn = tpx_shard_map(
        functools.partial(_ulysses_shard, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
