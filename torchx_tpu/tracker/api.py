"""Experiment tracking / lineage API.

Reference analog: torchx/tracker/api.py (275 LoC):

* :class:`TrackerBase` — backend ABC (artifacts, metadata, lineage, run ids).
* :class:`AppRun` — the in-job API; ``AppRun.run_from_env()`` reads the env
  vars the Runner injected at dryrun (TPX_JOB_ID / TPX_TRACKERS /
  TPX_TRACKER_<NAME>_CONFIG) and fans writes out to every configured backend.

Client side, :func:`tracker_config_env_vars` turns the entries configured in
``.tpxconfig`` ``[tracker:<name>]`` sections (or the ``tpx_trackers``
entrypoint group) into those env vars.
"""

from __future__ import annotations

import importlib
import logging
import os
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu import settings

logger = logging.getLogger(__name__)


@dataclass
class TrackerArtifact:
    name: str
    path: str
    metadata: Optional[Mapping[str, Any]] = None


@dataclass
class TrackerSource:
    source_run_id: str
    artifact_name: Optional[str] = None


@dataclass
class Lineage:
    run_id: str
    sources: list[TrackerSource]
    # downstream runs that declared run_id as a source (backends that can
    # answer the reverse query populate it; others leave it empty)
    descendants: list[str] = field(default_factory=list)


class TrackerBase(ABC):
    """Backend contract (reference tracker/api.py:61-122)."""

    @abstractmethod
    def add_artifact(
        self, run_id: str, name: str, path: str, metadata: Optional[Mapping[str, Any]] = None
    ) -> None:
        """Record a named artifact (path/URL + optional metadata) on a run."""
        ...

    @abstractmethod
    def artifacts(self, run_id: str) -> Mapping[str, TrackerArtifact]:
        """All artifacts of a run, keyed by name."""
        ...

    @abstractmethod
    def add_metadata(self, run_id: str, **kwargs: Any) -> None:
        """Merge key=value metadata onto a run."""
        ...

    @abstractmethod
    def metadata(self, run_id: str) -> Mapping[str, Any]:
        """A run's accumulated metadata."""
        ...

    @abstractmethod
    def add_source(
        self, run_id: str, source_id: str, artifact_name: Optional[str] = None
    ) -> None:
        """Link ``source_id`` (a parent run, optionally one artifact of
        it) as an input of this run — the lineage edge."""
        ...

    @abstractmethod
    def sources(
        self, run_id: str, artifact_name: Optional[str] = None
    ) -> Iterable[TrackerSource]:
        """The runs (optionally filtered to one artifact) this run
        consumed."""
        ...

    @abstractmethod
    def run_ids(self, **kwargs: str) -> Iterable[str]:
        """Known run ids, newest last; backends may accept filter
        kwargs (e.g. ``parent_run_id``)."""
        ...

    def lineage(self, run_id: str) -> Lineage:
        """The run's source edges as a :class:`Lineage` record."""
        return Lineage(run_id=run_id, sources=list(self.sources(run_id)))


# =========================================================================
# Factory / env-var plumbing
# =========================================================================

# entry-point group name for tracker backend factories
TRACKER_ENTRYPOINT_GROUP = "tpx_trackers"


def _load_tracker(name: str, config: Optional[str]) -> Optional[TrackerBase]:
    """name is either an entry-point name or a ``module:fn`` factory spec;
    the factory takes (config: str | None) and returns a TrackerBase."""
    factory = None
    try:
        from importlib.metadata import entry_points

        for ep in entry_points(group=TRACKER_ENTRYPOINT_GROUP):
            if ep.name == name:
                factory = ep.load()
                break
    except Exception:  # noqa: BLE001
        pass
    if factory is None:
        # plugin registry wins over module:fn interpretation (a plugin may
        # legitimately register a colon-containing name)
        try:
            from torchx_tpu.plugins import get_plugin_trackers

            factory = get_plugin_trackers().get(name)
        except ImportError:
            pass
    if factory is None and ":" in name:
        mod_name, _, fn_name = name.partition(":")
        try:
            factory = getattr(importlib.import_module(mod_name), fn_name)
        except (ImportError, AttributeError) as e:
            logger.warning("cannot load tracker %r: %s", name, e)
            return None
    if factory is None:
        # builtin shorthand
        if name == "fsspec":
            from torchx_tpu.tracker.backend.fsspec import create as factory
        elif name == "mlflow":
            from torchx_tpu.tracker.mlflow import create as factory
        else:
            logger.warning("unknown tracker backend %r", name)
            return None
    try:
        return factory(config)
    except Exception as e:  # noqa: BLE001
        logger.warning("tracker %r factory failed: %s", name, e)
        return None


def trackers_from_environ() -> dict[str, TrackerBase]:
    """In-job: instantiate every tracker named in $TPX_TRACKERS."""
    names = [
        n.strip()
        for n in os.environ.get(settings.ENV_TPX_TRACKERS, "").split(",")
        if n.strip()
    ]
    out: dict[str, TrackerBase] = {}
    for name in names:
        key = name.replace(":", "_").replace(".", "_").upper()
        config = os.environ.get(f"{settings.ENV_TPX_TRACKER_PREFIX}{key}_CONFIG")
        tracker = _load_tracker(name, config)
        if tracker is not None:
            out[name] = tracker
    return out


def tracker_config_env_vars(
    parent_run_id: Optional[str] = None,
    trackers: Optional[Mapping[str, Optional[str]]] = None,
) -> dict[str, str]:
    """Client side: env vars the Runner injects into every role at dryrun
    (reference runner/api.py:68-87,358-391). ``trackers`` maps backend name
    -> optional config string; default comes from .tpxconfig [tracker:*]."""
    if trackers is None:
        from torchx_tpu.runner.config import load_tracker_sections

        trackers = load_tracker_sections()
    if not trackers:
        return {}
    env = {settings.ENV_TPX_TRACKERS: ",".join(trackers)}
    for name, config in trackers.items():
        if config:
            key = name.replace(":", "_").replace(".", "_").upper()
            env[f"{settings.ENV_TPX_TRACKER_PREFIX}{key}_CONFIG"] = config
    if parent_run_id:
        env[settings.ENV_TPX_PARENT_RUN_ID] = parent_run_id
    return env


# =========================================================================
# In-job AppRun facade
# =========================================================================


class AppRun:
    """Job-side tracking handle fanning out to all configured backends."""

    _instance: Optional["AppRun"] = None

    def __init__(self, id: str, backends: Mapping[str, TrackerBase]) -> None:
        self.id = id
        self.backends = dict(backends)

    @classmethod
    def run_from_env(cls) -> "AppRun":
        """Singleton built from scheduler-injected env (TPX_JOB_ID et al.).

        Outside a tpx-launched job, returns an id of "<unknown_run_id>" with
        zero backends: all calls become no-ops so user code runs unchanged.
        """
        if cls._instance is None:
            run_id = os.environ.get(settings.ENV_TPX_JOB_ID, "<unknown_run_id>")
            backends = trackers_from_environ()
            run = cls(run_id, backends)
            parent = os.environ.get(settings.ENV_TPX_PARENT_RUN_ID)
            if parent:
                run.add_source(parent)
            cls._instance = run
        return cls._instance

    def add_metadata(self, **kwargs: Any) -> None:
        """Fan ``key=value`` metadata out to every configured backend."""
        for b in self.backends.values():
            b.add_metadata(self.id, **kwargs)

    def add_artifact(
        self, name: str, path: str, metadata: Optional[Mapping[str, Any]] = None
    ) -> None:
        """Record an artifact on this job's run in every backend."""
        for b in self.backends.values():
            b.add_artifact(self.id, name, path, metadata)

    def add_source(self, source_id: str, artifact_name: Optional[str] = None) -> None:
        """Link a parent run as an input of this job's run."""
        for b in self.backends.values():
            b.add_source(self.id, source_id, artifact_name)
