"""MLflow tracker backend.

Reference analog: torchx/tracker/mlflow.py:33-376. Maps tpx runs onto
MLflow runs — run_id -> an MLflow run tagged ``tpx.run_id`` — with the
reference's full artifact and lineage semantics:

* **Artifacts are really logged.** ``add_artifact`` with a local file/dir
  uploads it into the MLflow artifact store (``log_artifact(s)``), so the
  MLflow UI serves the bytes; remote or absent paths are recorded as URI
  pointer tags instead (the reference's remote-artifact behavior). Artifact
  metadata rides a JSON tag. ``artifacts()`` merges the store listing
  (recursive, reference ``get_artifacts``) with pointer tags.
* **Lineage links both ways.** ``add_source`` tags the run with its
  upstream; :meth:`lineage` returns upstream sources AND downstream
  descendants (runs whose source tags reference this run), which is what
  ``tpx tracker lineage`` renders.
* **Structured config logging.** :meth:`log_params_flat` flattens nested
  dataclasses / mappings into dotted MLflow params (reference
  ``log_params_flat``).

The mlflow import is deferred: this module imports cleanly without mlflow
installed and only fails when actually constructed (the environment gates
optional deps; see create()).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu.tracker.api import (
    Lineage,
    TrackerArtifact,
    TrackerBase,
    TrackerSource,
)

RUN_ID_TAG = "tpx.run_id"
ARTIFACT_TAG_PREFIX = "tpx.artifact."
ARTIFACT_META_TAG_PREFIX = "tpx.artifact_meta."
SOURCE_TAG_PREFIX = "tpx.source."


class MLflowTracker(TrackerBase):
    def __init__(
        self,
        tracking_uri: Optional[str] = None,
        experiment_name: str = "tpx",
    ) -> None:
        import mlflow

        self._mlflow = mlflow
        self._client = mlflow.tracking.MlflowClient(tracking_uri=tracking_uri)
        exp = self._client.get_experiment_by_name(experiment_name)
        self._experiment_id = (
            exp.experiment_id
            if exp
            else self._client.create_experiment(experiment_name)
        )
        self._run_cache: dict[str, str] = {}  # tpx run id -> mlflow run id

    def _mlflow_run(self, run_id: str) -> str:
        if run_id in self._run_cache:
            return self._run_cache[run_id]
        hits = self._client.search_runs(
            [self._experiment_id], filter_string=f"tags.`{RUN_ID_TAG}` = '{run_id}'"
        )
        if hits:
            mlrun_id = hits[0].info.run_id
        else:
            run = self._client.create_run(
                self._experiment_id, tags={RUN_ID_TAG: run_id}, run_name=run_id
            )
            mlrun_id = run.info.run_id
        self._run_cache[run_id] = mlrun_id
        return mlrun_id

    # -- artifacts ---------------------------------------------------------

    def add_artifact(
        self,
        run_id: str,
        name: str,
        path: str,
        metadata: Optional[Mapping[str, Any]] = None,
    ) -> None:
        mlrun = self._mlflow_run(run_id)
        if os.path.isdir(path):
            self._client.log_artifacts(mlrun, path, artifact_path=name)
            self._client.set_tag(mlrun, f"{ARTIFACT_TAG_PREFIX}{name}", name)
        elif os.path.isfile(path):
            self._client.log_artifact(mlrun, path, artifact_path=name)
            self._client.set_tag(mlrun, f"{ARTIFACT_TAG_PREFIX}{name}", name)
        else:
            # remote / not-locally-materialized artifact: record the URI
            self._client.set_tag(mlrun, f"{ARTIFACT_TAG_PREFIX}{name}", path)
        if metadata:
            self._client.set_tag(
                mlrun,
                f"{ARTIFACT_META_TAG_PREFIX}{name}",
                json.dumps(dict(metadata), default=str),
            )

    def artifacts(self, run_id: str) -> Mapping[str, TrackerArtifact]:
        mlrun = self._mlflow_run(run_id)
        run = self._client.get_run(mlrun)
        metas: dict[str, Mapping[str, Any]] = {}
        pointers: dict[str, str] = {}
        for tag, value in run.data.tags.items():
            if tag.startswith(ARTIFACT_META_TAG_PREFIX):
                try:
                    metas[tag[len(ARTIFACT_META_TAG_PREFIX) :]] = json.loads(value)
                except ValueError:
                    pass
            elif tag.startswith(ARTIFACT_TAG_PREFIX):
                pointers[tag[len(ARTIFACT_TAG_PREFIX) :]] = value
        out: dict[str, TrackerArtifact] = {}
        base = run.info.artifact_uri
        for name, value in pointers.items():
            if value == name:
                # logged into the store: resolve to the artifact URI
                value = f"{base}/{name}"
            out[name] = TrackerArtifact(
                name=name, path=value, metadata=metas.get(name)
            )
        # store entries logged outside add_artifact still surface
        for item in self._list_artifacts_recursive(mlrun):
            root = item.split("/", 1)[0]
            if root not in out:
                out[root] = TrackerArtifact(
                    name=root, path=f"{base}/{root}", metadata=metas.get(root)
                )
        return out

    def _list_artifacts_recursive(self, mlrun: str) -> Iterable[str]:
        stack = [""]
        while stack:
            prefix = stack.pop()
            for info in self._client.list_artifacts(mlrun, prefix or None):
                if info.is_dir:
                    stack.append(info.path)
                else:
                    yield info.path

    # -- metadata ----------------------------------------------------------

    def add_metadata(self, run_id: str, **kwargs: Any) -> None:
        mlrun = self._mlflow_run(run_id)
        for key, value in kwargs.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                self._client.log_param(mlrun, key, value)
            else:
                self._client.log_metric(mlrun, key, float(value))

    def metadata(self, run_id: str) -> Mapping[str, Any]:
        run = self._client.get_run(self._mlflow_run(run_id))
        out: dict[str, Any] = dict(run.data.params)
        out.update(run.data.metrics)
        return out

    def log_params_flat(self, run_id: str, config: Any, prefix: str = "") -> None:
        """Flatten a nested config (dataclass / mapping / primitives) into
        dotted MLflow params: ``{"opt": {"lr": 3e-4}}`` -> ``opt.lr=0.0003``
        (reference mlflow.py log_params_flat)."""
        flat: dict[str, Any] = {}

        def walk(obj: Any, path: str) -> None:
            if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
                obj = dataclasses.asdict(obj)
            if isinstance(obj, Mapping):
                for k, v in obj.items():
                    walk(v, f"{path}.{k}" if path else str(k))
            elif isinstance(obj, (list, tuple)):
                flat[path] = json.dumps(list(obj), default=str)
            else:
                flat[path] = obj

        walk(config, prefix)
        self.add_metadata(run_id, **{k: v for k, v in flat.items() if k})

    # -- lineage -----------------------------------------------------------

    def add_source(
        self, run_id: str, source_id: str, artifact_name: Optional[str] = None
    ) -> None:
        existing = list(self.sources(run_id))
        self._client.set_tag(
            self._mlflow_run(run_id),
            f"{SOURCE_TAG_PREFIX}{len(existing)}",
            f"{source_id}|{artifact_name or ''}",
        )

    def sources(
        self, run_id: str, artifact_name: Optional[str] = None
    ) -> Iterable[TrackerSource]:
        run = self._client.get_run(self._mlflow_run(run_id))
        # numeric sort on the tag index: "…source.10" must come after
        # "…source.2", which lexicographic sorting would scramble
        source_tags = [
            (tag, value)
            for tag, value in run.data.tags.items()
            if tag.startswith(SOURCE_TAG_PREFIX)
        ]

        def _idx(kv: tuple[str, str]) -> int:
            suffix = kv[0][len(SOURCE_TAG_PREFIX):]
            return int(suffix) if suffix.isdigit() else 0

        for tag, value in sorted(source_tags, key=_idx):
            src, _, art = value.partition("|")
            source = TrackerSource(source_run_id=src, artifact_name=art or None)
            if artifact_name is None or source.artifact_name == artifact_name:
                yield source

    def _all_runs(self) -> Iterable[Any]:
        """Every run in the experiment, following page tokens —
        ``search_runs`` returns a single page (default ``max_results``),
        so reverse lineage would silently miss runs in large experiments."""
        token: Optional[str] = None
        while True:
            page = self._client.search_runs(
                [self._experiment_id], page_token=token
            )
            yield from page
            token = getattr(page, "token", None)
            if not token:
                return

    def descendants(self, run_id: str) -> Iterable[str]:
        """Runs that declared ``run_id`` as a source (downstream links)."""
        for run in self._all_runs():
            rid = run.data.tags.get(RUN_ID_TAG)
            if not rid or rid == run_id:
                continue
            for tag, value in run.data.tags.items():
                if tag.startswith(SOURCE_TAG_PREFIX) and (
                    value.partition("|")[0] == run_id
                ):
                    yield rid
                    break

    def lineage(self, run_id: str) -> Lineage:
        return Lineage(
            run_id=run_id,
            sources=list(self.sources(run_id)),
            descendants=list(self.descendants(run_id)),
        )

    def run_ids(self, **kwargs: str) -> Iterable[str]:
        """All tracked run ids; ``source_run_id=<id>`` filters to runs
        downstream of that id (reference run_ids parent filtering)."""
        source = kwargs.get("source_run_id") or kwargs.get("parent_run_id")
        if source:
            yield from self.descendants(source)
            return
        for run in self._all_runs():
            rid = run.data.tags.get(RUN_ID_TAG)
            if rid:
                yield rid


def create(config: Optional[str]) -> MLflowTracker:
    """Factory. config: ``[tracking_uri][;experiment=<name>]``."""
    tracking_uri: Optional[str] = None
    experiment = "tpx"
    if config:
        for part in config.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("experiment="):
                experiment = part.split("=", 1)[1]
            else:
                tracking_uri = part
    return MLflowTracker(tracking_uri=tracking_uri, experiment_name=experiment)
