"""MLflow tracker backend.

Reference analog: torchx/tracker/mlflow.py (376 LoC). Maps tpx runs onto
MLflow runs: run_id -> an MLflow run tagged ``tpx.run_id``; metadata ->
params/metrics (numeric values become metrics, the rest params); artifacts
-> artifact URI tags; lineage sources -> ``tpx.source.<n>`` tags.

The mlflow import is deferred: this module imports cleanly without mlflow
installed and only fails when actually constructed (the environment gates
optional deps; see create()).
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Optional

from torchx_tpu.tracker.api import TrackerArtifact, TrackerBase, TrackerSource

RUN_ID_TAG = "tpx.run_id"
ARTIFACT_TAG_PREFIX = "tpx.artifact."
SOURCE_TAG_PREFIX = "tpx.source."


class MLflowTracker(TrackerBase):
    def __init__(
        self,
        tracking_uri: Optional[str] = None,
        experiment_name: str = "tpx",
    ) -> None:
        import mlflow

        self._mlflow = mlflow
        self._client = mlflow.tracking.MlflowClient(tracking_uri=tracking_uri)
        exp = self._client.get_experiment_by_name(experiment_name)
        self._experiment_id = (
            exp.experiment_id
            if exp
            else self._client.create_experiment(experiment_name)
        )
        self._run_cache: dict[str, str] = {}  # tpx run id -> mlflow run id

    def _mlflow_run(self, run_id: str) -> str:
        if run_id in self._run_cache:
            return self._run_cache[run_id]
        hits = self._client.search_runs(
            [self._experiment_id], filter_string=f"tags.`{RUN_ID_TAG}` = '{run_id}'"
        )
        if hits:
            mlrun_id = hits[0].info.run_id
        else:
            run = self._client.create_run(
                self._experiment_id, tags={RUN_ID_TAG: run_id}, run_name=run_id
            )
            mlrun_id = run.info.run_id
        self._run_cache[run_id] = mlrun_id
        return mlrun_id

    def add_artifact(
        self,
        run_id: str,
        name: str,
        path: str,
        metadata: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self._client.set_tag(
            self._mlflow_run(run_id), f"{ARTIFACT_TAG_PREFIX}{name}", path
        )

    def artifacts(self, run_id: str) -> Mapping[str, TrackerArtifact]:
        run = self._client.get_run(self._mlflow_run(run_id))
        out = {}
        for tag, value in run.data.tags.items():
            if tag.startswith(ARTIFACT_TAG_PREFIX):
                name = tag[len(ARTIFACT_TAG_PREFIX) :]
                out[name] = TrackerArtifact(name=name, path=value)
        return out

    def add_metadata(self, run_id: str, **kwargs: Any) -> None:
        mlrun = self._mlflow_run(run_id)
        for key, value in kwargs.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                self._client.log_param(mlrun, key, value)
            else:
                self._client.log_metric(mlrun, key, float(value))

    def metadata(self, run_id: str) -> Mapping[str, Any]:
        run = self._client.get_run(self._mlflow_run(run_id))
        out: dict[str, Any] = dict(run.data.params)
        out.update(run.data.metrics)
        return out

    def add_source(
        self, run_id: str, source_id: str, artifact_name: Optional[str] = None
    ) -> None:
        existing = list(self.sources(run_id))
        self._client.set_tag(
            self._mlflow_run(run_id),
            f"{SOURCE_TAG_PREFIX}{len(existing)}",
            f"{source_id}|{artifact_name or ''}",
        )

    def sources(
        self, run_id: str, artifact_name: Optional[str] = None
    ) -> Iterable[TrackerSource]:
        run = self._client.get_run(self._mlflow_run(run_id))
        for tag, value in sorted(run.data.tags.items()):
            if tag.startswith(SOURCE_TAG_PREFIX):
                src, _, art = value.partition("|")
                source = TrackerSource(source_run_id=src, artifact_name=art or None)
                if artifact_name is None or source.artifact_name == artifact_name:
                    yield source

    def run_ids(self, **kwargs: str) -> Iterable[str]:
        for run in self._client.search_runs([self._experiment_id]):
            rid = run.data.tags.get(RUN_ID_TAG)
            if rid:
                yield rid


def create(config: Optional[str]) -> MLflowTracker:
    """Factory. config: ``[tracking_uri][;experiment=<name>]``."""
    tracking_uri: Optional[str] = None
    experiment = "tpx"
    if config:
        for part in config.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("experiment="):
                experiment = part.split("=", 1)[1]
            else:
                tracking_uri = part
    return MLflowTracker(tracking_uri=tracking_uri, experiment_name=experiment)
