"""Filesystem-tree tracker backend.

Reference analog: torchx/tracker/backend/fsspec.py (291 LoC). Encodes runs,
artifacts, metadata and lineage as a directory tree on any fsspec-mountable
filesystem (local, gs://, s3://):

    <root>/<quoted_run_id>/
        artifacts/<name>.json      {"name","path","metadata"}
        metadata.json              merged key-value metadata
        sources/<quoted_source>.json

Works without the fsspec package for plain local paths (a GCS/S3 root then
requires fsspec to be importable).
"""

from __future__ import annotations

import json
import os
import urllib.parse
from typing import Any, Iterable, Mapping, Optional

from torchx_tpu.tracker.api import TrackerArtifact, TrackerBase, TrackerSource


def _quote(run_id: str) -> str:
    return urllib.parse.quote(run_id, safe="")


def _unquote(name: str) -> str:
    return urllib.parse.unquote(name)


class _LocalFS:
    """Minimal fs shim so local roots need no fsspec install."""

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def open(self, path: str, mode: str):  # noqa: ANN202
        if "w" in mode:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def ls(self, path: str) -> list[str]:
        try:
            return [os.path.join(path, p) for p in os.listdir(path)]
        except FileNotFoundError:
            return []


class _RemoteFS:
    """Adapts an fsspec filesystem to the _LocalFS contract (missing
    directories list as empty instead of raising)."""

    def __init__(self, fs) -> None:  # noqa: ANN001
        self._fs = fs

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        self._fs.makedirs(path, exist_ok=exist_ok)

    def open(self, path: str, mode: str):  # noqa: ANN202
        return self._fs.open(path, mode)

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def ls(self, path: str) -> list[str]:
        try:
            return list(self._fs.ls(path, detail=False))
        except FileNotFoundError:
            return []


def _fs_for(root: str):  # noqa: ANN202
    if "://" in root:
        import fsspec

        fs, _, _ = fsspec.get_fs_token_paths(root)
        return _RemoteFS(fs)
    return _LocalFS()


class FsspecTracker(TrackerBase):
    def __init__(self, root: str) -> None:
        self._root = root.rstrip("/")
        self._fs = _fs_for(root)

    # -- paths --------------------------------------------------------------

    def _run_dir(self, run_id: str) -> str:
        return f"{self._root}/{_quote(run_id)}"

    # -- artifacts ----------------------------------------------------------

    def add_artifact(
        self,
        run_id: str,
        name: str,
        path: str,
        metadata: Optional[Mapping[str, Any]] = None,
    ) -> None:
        p = f"{self._run_dir(run_id)}/artifacts/{_quote(name)}.json"
        with self._fs.open(p, "w") as f:
            json.dump({"name": name, "path": path, "metadata": dict(metadata or {})}, f)

    def artifacts(self, run_id: str) -> Mapping[str, TrackerArtifact]:
        out = {}
        for p in self._fs.ls(f"{self._run_dir(run_id)}/artifacts"):
            with self._fs.open(p, "r") as f:
                data = json.load(f)
            out[data["name"]] = TrackerArtifact(
                name=data["name"], path=data["path"], metadata=data.get("metadata")
            )
        return out

    # -- metadata -----------------------------------------------------------

    def add_metadata(self, run_id: str, **kwargs: Any) -> None:
        p = f"{self._run_dir(run_id)}/metadata.json"
        existing: dict[str, Any] = {}
        if self._fs.exists(p):
            with self._fs.open(p, "r") as f:
                existing = json.load(f)
        existing.update(kwargs)
        with self._fs.open(p, "w") as f:
            json.dump(existing, f, default=str)

    def metadata(self, run_id: str) -> Mapping[str, Any]:
        p = f"{self._run_dir(run_id)}/metadata.json"
        if not self._fs.exists(p):
            return {}
        with self._fs.open(p, "r") as f:
            return json.load(f)

    # -- lineage ------------------------------------------------------------

    def add_source(
        self, run_id: str, source_id: str, artifact_name: Optional[str] = None
    ) -> None:
        p = f"{self._run_dir(run_id)}/sources/{_quote(source_id)}.json"
        with self._fs.open(p, "w") as f:
            json.dump({"source_run_id": source_id, "artifact_name": artifact_name}, f)

    def sources(
        self, run_id: str, artifact_name: Optional[str] = None
    ) -> Iterable[TrackerSource]:
        for p in self._fs.ls(f"{self._run_dir(run_id)}/sources"):
            with self._fs.open(p, "r") as f:
                data = json.load(f)
            src = TrackerSource(
                source_run_id=data["source_run_id"],
                artifact_name=data.get("artifact_name"),
            )
            if artifact_name is None or src.artifact_name == artifact_name:
                yield src

    # -- run listing ----------------------------------------------------------

    def run_ids(self, **kwargs: str) -> Iterable[str]:
        for p in self._fs.ls(self._root):
            yield _unquote(os.path.basename(p.rstrip("/")))


def create(config: Optional[str]) -> FsspecTracker:
    """Factory (entry-point / $TPX_TRACKER_<N>_CONFIG target). ``config`` is
    the root path/URL."""
    if not config:
        raise ValueError(
            "fsspec tracker requires a root path config, e.g."
            " [tracker:fsspec] config = /mnt/experiments"
        )
    return FsspecTracker(config)
