from torchx_tpu.tracker.api import AppRun, TrackerBase, trackers_from_environ  # noqa: F401


def app_run_from_env() -> AppRun:
    """Convenience alias (reference: torchx.tracker.app_run_from_env)."""
    return AppRun.run_from_env()
