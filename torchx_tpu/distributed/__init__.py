"""In-job distributed helpers (the analog of torchx.distributed).

Reference analog: torchx/distributed/__init__.py (303 LoC) — rank/world-size
helpers, ``init_pg``, rank0-first barriers over torch.distributed. Here the
substrate is ``jax.distributed`` + the launcher-injected gang env
(TPX_REPLICA_ID / TPX_NUM_REPLICAS / TPX_COORDINATOR_HOST): user code calls
:func:`init_from_env` once (or relies on ``dist.spmd``'s bootstrap which
does it automatically) and then uses plain jax collectives.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

from torchx_tpu import settings

_initialized = False


def is_tpx_job() -> bool:
    """True when running inside a tpx-launched replica."""
    return settings.ENV_TPX_APP_ID in os.environ


def gang_info() -> tuple[int, int, str]:
    """(process_id, num_processes, coordinator_host) from the injected env,
    falling back to GKE's TPU_WORKER_* variables when the launcher vars are
    absent (e.g. hand-rolled podslice jobs). The single source of truth —
    the spmd bootstrap uses this same parser."""
    raw = os.environ.get(settings.ENV_TPX_REPLICA_ID)
    if raw is None:
        # multi-slice backends that can't do arithmetic at pod start inject
        # the (slice_id, host_id, hosts_per_slice) decomposition instead
        slice_id = os.environ.get(settings.ENV_TPX_SLICE_ID)
        host_id = os.environ.get(settings.ENV_TPX_HOST_ID)
        per_slice = os.environ.get(settings.ENV_TPX_HOSTS_PER_SLICE)
        if slice_id is not None and host_id is not None and per_slice is not None:
            raw = str(int(slice_id) * int(per_slice) + int(host_id))
    process_id = int(raw or os.environ.get(settings.ENV_TPU_WORKER_ID) or 0)
    num = int(os.environ.get(settings.ENV_TPX_NUM_REPLICAS) or 0)
    coordinator = os.environ.get(settings.ENV_TPX_COORDINATOR_HOST, "")
    if not coordinator:
        hostnames = os.environ.get(settings.ENV_TPU_WORKER_HOSTNAMES, "")
        hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
        if hosts:
            coordinator = hosts[0]
            num = num or len(hosts)
    return process_id, num or 1, coordinator or "localhost"


def replica_id() -> int:
    """This process's global id within the role's gang (0-based)."""
    return gang_info()[0]


def num_replicas() -> int:
    """Total processes in the role's gang."""
    return gang_info()[1]


def coordinator_address(port: Optional[int] = None) -> str:
    """``host:port`` of replica 0 — the jax.distributed coordinator."""
    host = gang_info()[2]
    return f"{host}:{port or settings.TPX_COORDINATOR_PORT}"


def _jax_distributed_initialized() -> bool:
    import jax

    try:
        return jax.distributed.is_initialized()
    except AttributeError:  # older jax
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None


def init_from_env(port: Optional[int] = None) -> None:
    """Initialize jax.distributed from the launcher-injected env. Safe to
    call multiple times, outside a tpx job (no-op for single process), and
    after the ``dist.spmd`` bootstrap already initialized the world.

    The analog of ``torchx.distributed.init_pg(backend="auto")``
    (reference distributed/__init__.py:164-227).
    """
    global _initialized
    if _initialized:
        return
    process_id, n, host = gang_info()
    if n > 1:
        import jax

        if not _jax_distributed_initialized():
            jax.distributed.initialize(
                coordinator_address=f"{host}:{port or settings.TPX_COORDINATOR_PORT}",
                num_processes=n,
                process_id=process_id,
            )
    _initialized = True


def local_device_count() -> int:
    """Accelerator devices attached to THIS process."""
    import jax

    return jax.local_device_count()


def world_device_count() -> int:
    """Accelerator devices across the whole gang."""
    import jax

    return jax.device_count()


def is_process_zero() -> bool:
    """True on the gang's coordinator process (logging/checkpoint guard)."""
    import jax

    return jax.process_index() == 0


@contextlib.contextmanager
def on_process_zero_first() -> Iterator[None]:
    """Process 0 runs the body before everyone else (download-once pattern;
    analog of ``on_rank0_first``, reference distributed/__init__.py:230-303).

    Uses a jax collective as the barrier, so call only after device init.
    """
    import jax
    import jax.numpy as jnp

    def barrier() -> None:
        if jax.process_count() > 1:
            # tiny global psum = cross-process barrier
            jax.block_until_ready(
                jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
                    jnp.ones((jax.local_device_count(),))
                )
            )

    if is_process_zero():
        yield
        barrier()
    else:
        barrier()
        yield
