"""Cross-session trace stitching: one timeline from many writers.

A single serve request now leaves spans in up to four places — the
router's process, the prefill replica, the KV transfer, and the decode
replica — and a fleet job's lifecycle spans come from the control
daemon's process while its in-job heartbeats come from the gang's. Each
writer has its own obs session dir; :mod:`torchx_tpu.obs.timeline` reads
one dir at a time, so the picture stays sharded.

This module is the merge layer ``tpx trace --stitch`` uses: gather every
session's records, resolve an operator-friendly identifier (app id,
serve ``request_id``, fleet job name, or raw 32-hex trace id) to a trace
id, and rebuild one tree across all of them. Orphan spans — parents
recorded by a writer whose file we can't see — surface as extra roots
rather than vanishing, same holdback discipline as the journals.

stdlib-only and jax-free.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from torchx_tpu.obs import timeline

__all__ = [
    "collect_records",
    "resolve_trace_ids",
    "StitchedTrace",
    "stitch",
    "render_stitched",
]

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")

#: span attrs an identifier is matched against (beyond app_id): the
#: serve request id stamped by the router/replicas and the fleet job
#: name stamped by the scheduler's lifecycle spans.
_IDENT_ATTRS = ("app_id", "request_id", "fleet_job")


def collect_records(
    obs_dir: Optional[str] = None,
) -> tuple[list[dict[str, Any]], list[str]]:
    """Load every session's trace records under the obs root.

    Returns ``(records, source_files)`` with one source path per record
    (parallel lists), newest session first — the raw material for
    resolution and stitching."""
    records: list[dict[str, Any]] = []
    sources: list[str] = []
    for path in timeline.iter_trace_files(obs_dir):
        recs = timeline.load_records(path)
        records.extend(recs)
        sources.extend([path] * len(recs))
    return records, sources


def resolve_trace_ids(records: list[dict[str, Any]], ident: str) -> list[str]:
    """Trace ids matching an operator identifier, in order of first
    appearance. A 32-hex string is taken as a literal trace id; anything
    else matches span attrs ``app_id``/``request_id``/``fleet_job`` and
    event ``app_id`` fields."""
    if _TRACE_ID_RE.match(ident):
        return [ident]
    out: list[str] = []
    for r in records:
        tid = r.get("trace_id")
        if not tid or tid in out:
            continue
        if timeline.is_span(r):
            attrs = r.get("attrs") or {}
            if any(attrs.get(k) == ident for k in _IDENT_ATTRS):
                out.append(tid)
        elif r.get("app_id") == ident:
            out.append(tid)
    return out


@dataclass
class StitchedTrace:
    """One reconstructed cross-session trace."""

    trace_id: str
    roots: list[timeline.TimelineNode]
    #: session dirs that contributed at least one record.
    sessions: list[str] = field(default_factory=list)
    span_count: int = 0


def stitch(
    ident: str, obs_dir: Optional[str] = None
) -> Optional[StitchedTrace]:
    """Resolve ``ident`` and rebuild its trace across every session dir.

    Returns None when nothing matches. With multiple matching traces the
    newest (first found — files iterate newest-first) wins, matching
    ``tpx trace``'s behavior."""
    records, sources = collect_records(obs_dir)
    ids = resolve_trace_ids(records, ident)
    if not ids:
        return None
    trace_id = ids[0]
    sessions = sorted(
        {
            os.path.dirname(src)
            for r, src in zip(records, sources)
            if r.get("trace_id") == trace_id
        }
    )
    roots = timeline.build_timeline(records, trace_id)
    count = sum(
        1
        for r in records
        if r.get("trace_id") == trace_id and timeline.is_span(r)
    )
    return StitchedTrace(
        trace_id=trace_id, roots=roots, sessions=sessions, span_count=count
    )


def render_stitched(st: StitchedTrace, include_events: bool = False) -> str:
    """Render a stitched trace: a provenance header (which session dirs
    fed it) above the merged indented timeline."""
    lines = [
        f"trace {st.trace_id}  "
        f"({st.span_count} spans from {len(st.sessions)} sessions)"
    ]
    lines += [f"  session {s}" for s in st.sessions]
    lines.append(timeline.render_timeline(st.roots, include_events=include_events))
    return "\n".join(lines)
