"""Span/trace model for the launch path.

A :class:`Span` is one timed operation (``runner.schedule``, a supervisor
attempt, a workspace build, the in-job first step); spans carrying the same
``trace_id`` form one trace, and ``parent_span_id`` links them into the
tree ``tpx trace`` renders. Propagation is two-level:

* **in-process** — a ``contextvars.ContextVar`` holds the active span, so
  nested instrumented calls parent automatically (and correctly across
  threads/async);
* **cross-process** — the client injects ``$TPX_TRACE_ID`` /
  ``$TPX_PARENT_SPAN`` into the job's env at submit
  (:func:`inject_env`); a process that opens a root span with those set
  joins the client's trace instead of starting its own.

Completed spans are serialized onto the same non-propagating events logger
that carries :class:`~torchx_tpu.runner.events.api.TpxEvent` records, so
one pipeline (and one JSONL sink — see :mod:`torchx_tpu.obs.sinks`) holds
the full story of a launch.
"""

from __future__ import annotations

import contextvars
import json
import os
import uuid
from contextlib import contextmanager
from dataclasses import asdict, dataclass, field, fields
from typing import Any, Iterator, Optional

from torchx_tpu import settings
from torchx_tpu.util.times import epoch_usec

#: record-discriminator value in the shared JSONL stream ("kind" key).
SPAN_KIND = "span"

#: HTTP headers carrying trace context across service hops (router →
#: replica, client → daemon) — the header-shaped twin of ``$TPX_TRACE_ID``
#: / ``$TPX_PARENT_SPAN``.
HDR_TRACE_ID = "X-Tpx-Trace-Id"
HDR_PARENT_SPAN = "X-Tpx-Parent-Span"

_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "tpx_current_span", default=None
)


def tracing_enabled() -> bool:
    """True unless ``$TPX_TRACE`` is set to 0/false/off. Checked at every
    emit (not cached) so tests and operators can flip it at runtime."""
    return os.environ.get(settings.ENV_TPX_TRACE, "1").lower() not in (
        "0",
        "false",
        "off",
    )


def new_trace_id() -> str:
    """A fresh 32-hex-char trace id."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A fresh 16-hex-char span id."""
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    """One timed, attributed operation within a trace.

    ``start_epoch_usec``/``end_epoch_usec`` are wall-clock epoch
    microseconds (the unit shared with ``TpxEvent`` stamps); ``status`` is
    ``"OK"`` or ``"ERROR"``. ``attrs`` carries small JSON-safe details
    (app_id, attempt number, poll count, ...) — never payloads.
    """

    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    start_epoch_usec: int = 0
    end_epoch_usec: Optional[int] = None
    attrs: dict[str, Any] = field(default_factory=dict)
    status: str = "OK"
    session: str = ""

    def duration_usec(self) -> Optional[int]:
        """Span duration in microseconds, or None while still open."""
        if self.end_epoch_usec is None:
            return None
        return self.end_epoch_usec - self.start_epoch_usec

    def serialize(self) -> str:
        """One JSON line, discriminated by ``"kind": "span"`` so readers
        can tell spans from TpxEvent records in the shared JSONL stream."""
        return json.dumps({"kind": SPAN_KIND, **asdict(self)}, default=str)

    @staticmethod
    def deserialize(data: str) -> "Span":
        """Inverse of :meth:`serialize`; unknown fields are dropped so old
        readers survive new writers (same forward-compatibility contract
        as ``TpxEvent.deserialize``)."""
        obj = json.loads(data)
        known = {f.name for f in fields(Span)}
        return Span(**{k: v for k, v in obj.items() if k in known})


def current_span() -> Optional[Span]:
    """The active span in this context, or None."""
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    """Trace id of the active span, falling back to the inherited
    ``$TPX_TRACE_ID`` (an in-job process with no local span yet is still
    part of the client's trace)."""
    span = _CURRENT.get()
    if span is not None:
        return span.trace_id
    return os.environ.get(settings.ENV_TPX_TRACE_ID) or None


def current_span_id() -> Optional[str]:
    """Span id of the active span, falling back to ``$TPX_PARENT_SPAN``."""
    span = _CURRENT.get()
    if span is not None:
        return span.span_id
    return os.environ.get(settings.ENV_TPX_PARENT_SPAN) or None


def inject_env(env: dict[str, str], force: bool = False) -> None:
    """Write the current trace context into a job env dict (the submit-time
    hook: ``Runner.dryrun`` and the supervisor's resubmit both call this on
    every role). By default the trace id is inherited if already present
    (a pre-traced AppDef stays in its trace); the parent span is always
    refreshed so each attempt's in-job spans hang off that attempt.
    ``force=True`` overwrites both — the supervisor uses it so resubmitted
    attempts join the *supervise* trace even when the dryrun was produced
    under an earlier one."""
    if not tracing_enabled():
        return
    trace_id = current_trace_id()
    span_id = current_span_id()
    if trace_id:
        if force:
            env[settings.ENV_TPX_TRACE_ID] = trace_id
        else:
            env.setdefault(settings.ENV_TPX_TRACE_ID, trace_id)
    if span_id:
        env[settings.ENV_TPX_PARENT_SPAN] = span_id


def start_span(name: str, session: str = "", **attrs: Any) -> tuple[Optional[Span], Any]:
    """Open a span and make it current; returns ``(span, token)`` for
    :func:`end_span`. Returns ``(None, None)`` when tracing is disabled.
    Prefer the :func:`span` context manager; this split exists for
    instrumentation that cannot nest a ``with`` block (``log_event``)."""
    if not tracing_enabled():
        return None, None
    parent = _CURRENT.get()
    if parent is not None:
        trace_id: str = parent.trace_id
        # an anchor from trace_context() may carry an empty span id
        # (remote trace known, remote span not): parent on nothing then
        parent_id: Optional[str] = parent.span_id or None
    else:
        trace_id = os.environ.get(settings.ENV_TPX_TRACE_ID) or new_trace_id()
        parent_id = os.environ.get(settings.ENV_TPX_PARENT_SPAN) or None
    sp = Span(
        name=name,
        trace_id=trace_id,
        span_id=new_span_id(),
        parent_span_id=parent_id,
        start_epoch_usec=epoch_usec(),
        attrs={k: v for k, v in attrs.items() if v is not None},
        session=session,
    )
    token = _CURRENT.set(sp)
    return sp, token


def end_span(
    span_: Optional[Span], token: Any, exc: Optional[BaseException] = None
) -> None:
    """Close a span from :func:`start_span`: restore the previous context,
    stamp the end time, mark ERROR on exception, and emit it."""
    if span_ is None:
        return
    _CURRENT.reset(token)
    span_.end_epoch_usec = epoch_usec()
    if exc is not None:
        span_.status = "ERROR"
        span_.attrs.setdefault("exception", f"{type(exc).__name__}: {exc}")
    record_span(span_)


@contextmanager
def span(name: str, session: str = "", **attrs: Any) -> Iterator[Optional[Span]]:
    """Context manager: time a block as one span, parented on the current
    context (or the inherited env context at the root). Yields the open
    :class:`Span` so callers can add attrs mid-flight, or None when
    tracing is disabled::

        with trace.span("supervisor.attempt", attempt=2) as sp:
            ...
            if sp is not None:
                sp.attrs["state"] = str(status.state)
    """
    sp, token = start_span(name, session=session, **attrs)
    try:
        yield sp
    except BaseException as e:
        end_span(sp, token, exc=e)
        raise
    else:
        end_span(sp, token)


@contextmanager
def trace_context(
    trace_id: Optional[str], parent_span_id: Optional[str] = None
) -> Iterator[None]:
    """Adopt a remote trace context for the duration of a block.

    Installs a synthetic (never-emitted) anchor span carrying
    ``trace_id``/``parent_span_id``, so every span opened inside the block
    joins the remote trace — the receive-side hook for contexts arriving
    via HTTP headers (:func:`extract_headers`), a ``KvPayload``, or a
    journaled fleet recipe. No-op when ``trace_id`` is falsy or tracing is
    disabled."""
    if not trace_id or not tracing_enabled():
        yield
        return
    anchor = Span(
        name="",  # marker: anchors are context carriers, never recorded
        trace_id=trace_id,
        span_id=parent_span_id or "",
    )
    token = _CURRENT.set(anchor)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def inject_headers(headers: dict[str, str]) -> dict[str, str]:
    """Stamp the current trace context into an HTTP header dict (the
    send-side twin of :func:`inject_env` for service hops). Returns the
    dict for chaining; untouched when there is no context or tracing is
    disabled."""
    if not tracing_enabled():
        return headers
    trace_id = current_trace_id()
    span_id = current_span_id()
    if trace_id:
        headers[HDR_TRACE_ID] = trace_id
    if span_id:
        headers[HDR_PARENT_SPAN] = span_id
    return headers


def extract_headers(headers: Any) -> tuple[Optional[str], Optional[str]]:
    """Read ``(trace_id, parent_span_id)`` out of request headers (any
    mapping with ``.get``, e.g. ``http.server`` message objects — their
    lookups are case-insensitive already). Returns ``(None, None)`` when
    absent; feed the result to :func:`trace_context`."""
    tid = headers.get(HDR_TRACE_ID) or None
    sid = headers.get(HDR_PARENT_SPAN) or None
    return tid, sid


def heartbeat(name: str, session: str = "", **attrs: Any) -> Optional[Span]:
    """Emit an instantaneous (zero-duration) span — the in-job progress
    marker (`job.first_step`, throughput snapshots) that joins the
    client trace via the injected env context. Also flushes the metrics
    textfile so the marker and its metrics land together."""
    if not tracing_enabled():
        return None
    with span(name, session=session, **attrs) as sp:
        pass
    from torchx_tpu.obs import sinks

    sinks.flush_metrics()
    return sp


def record_span(span_: Span) -> None:
    """Ship one completed span down the shared events pipeline. Root-span
    completion additionally flushes the session's metrics textfile, so a
    finished top-level operation always leaves current metrics behind."""
    if not tracing_enabled():
        return
    from torchx_tpu.runner.events import get_events_logger

    get_events_logger().info(span_.serialize())
    if span_.parent_span_id is None:
        from torchx_tpu.obs import sinks

        sinks.flush_metrics()
