"""Per-step phase attribution: where a training step's time actually goes.

End-to-end step time is the number every bench reports and the one number
nobody can act on: a 54% MFU plateau looks identical whether the missing
time is input stalls, an unoverlapped grad all-reduce, or plain kernel
inefficiency. This module splits each step into named phases so the MFU
push optimizes against attributed time instead of guesses:

* ``data_wait`` — host blocked on input (measured by the prefetcher's
  wait hook, :meth:`~torchx_tpu.parallel.prefetch.Prefetcher.set_wait_observer`);
* ``forward_backward`` — the fenced device step minus the attributed
  optimizer and exposed-collective slices;
* ``grad_sync`` per mesh axis — the EXPOSED (unoverlapped) collective
  time, attributed from the measured device residual (below);
* ``optimizer`` — the modeled elementwise AdamW update slice;
* ``checkpoint`` / ``host`` — measured save and log/emit time.

The trainer measures what a host can measure (wall step, the fenced
device call, input waits, checkpoint saves, log emission); the fused
jitted step hides the compute/collective boundary from host timers, so
the device slice is split by arithmetic the repo already trusts: the
roofline compute floor from :meth:`~torchx_tpu.analyze.plan.ModelShape.flops_per_token`
(the jax-free mirror with an exactness contract against the real model
configs) and the calibrated per-axis collective model from
:func:`~torchx_tpu.analyze.costmodel.collective_traffic`. The device
residual above the compute floor is attributed between "compute slack"
and "exposed collectives" in proportion to their modeled shares — an
attribution model, not a hardware counter, and the docstrings say so.

Two numbers close loops elsewhere:

* overlap fraction ``1 - exposed_comm / modeled_comm`` — how much of the
  modeled serialized collective time the schedule actually hid;
* :func:`feed_calibration` folds measured-vs-predicted collective
  seconds into :meth:`~torchx_tpu.tune.calibrate.CalibrationTable.observe_collectives`,
  so ``collective_scale`` finally carries measured residuals (until this
  profiler existed nothing could split comm from compute, and the scale
  only moved via the shared step residual).

Records append to ``profile.jsonl`` in the obs session dir (fsync'd,
single-line ``O_APPEND`` writes) next to ``trace.jsonl``; readers use
the same torn-line holdback as every journal in the repo. ``tpx profile``
renders the timeline/roofline summary; summaries also export as
``tpx_profile_*`` gauges for the telemetry plane and ``tpx top``.

Jax-free by construction (lint-enforced): the trainer hands in plain
numbers, so the CLI and the analyzers can import this module anywhere.
Sim-hosted clock rules apply: durations come from ``time.perf_counter``
(wall-cost measurement), record timestamps from the injected ``clock``
seam.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Iterator, Optional

logger = logging.getLogger(__name__)

#: the profile journal's filename inside an obs session dir.
PROFILE_FILE = "profile.jsonl"

#: stable schema version for records, ``--json`` summaries, and diffs.
SCHEMA_VERSION = 1

#: phases every profiled trainer run reports with nonzero totals.
CORE_PHASES = ("data_wait", "forward_backward", "optimizer", "host")

#: render/summary order of all scalar phases (``grad_sync`` is per-axis
#: and rides its own record key).
PHASES = ("data_wait", "forward_backward", "optimizer", "checkpoint", "host")

#: modeled AdamW update arithmetic per parameter (grad + two moments +
#: weight-decayed apply, a dozen elementwise ops) — the optimizer slice
#: is memory-bound in practice, but a FLOP-floor model keeps the slice
#: honest-order-of-magnitude without a second bandwidth table.
OPTIMIZER_FLOPS_PER_PARAM = 12.0


@dataclasses.dataclass(frozen=True)
class AttributionModel:
    """The static arithmetic a :class:`StepProfiler` splits device time with.

    Everything here is jax-free launcher-side fact: the FLOP contract
    from :class:`~torchx_tpu.analyze.plan.ModelShape`, the roofline peak
    the trainer already uses for MFU, and the CALIBRATED per-axis
    serialized-collective seconds from the cost model (calibrated so
    :func:`feed_calibration`'s EMA folds converge on the residual).
    """

    flops_per_token: float
    tokens_per_step: int
    peak_flops: float  # aggregate over all devices
    param_count: int
    comm_axis_s: dict[str, float] = dataclasses.field(default_factory=dict)
    generation: str = ""

    @property
    def ideal_compute_s(self) -> float:
        """Roofline floor: step seconds at 100% MFU."""
        if self.peak_flops <= 0:
            return 0.0
        return self.tokens_per_step * self.flops_per_token / self.peak_flops

    @property
    def optimizer_s(self) -> float:
        """Modeled elementwise optimizer-update seconds per step."""
        if self.peak_flops <= 0:
            return 0.0
        return OPTIMIZER_FLOPS_PER_PARAM * self.param_count / self.peak_flops

    @property
    def total_comm_s(self) -> float:
        """Modeled serialized collective seconds per step (all axes)."""
        return sum(self.comm_axis_s.values())

    @property
    def compute_slack_s(self) -> float:
        """Modeled compute time beyond the 100%-MFU floor at the rank
        model's assumed MFU — the non-collective share of any residual."""
        from torchx_tpu.tune.rank import ASSUMED_MFU

        return self.ideal_compute_s * (1.0 / ASSUMED_MFU - 1.0)

    def to_dict(self) -> dict:
        """Stable JSON form (the journal's ``meta`` record body)."""
        return {
            "flops_per_token": self.flops_per_token,
            "tokens_per_step": self.tokens_per_step,
            "peak_flops": self.peak_flops,
            "param_count": self.param_count,
            "comm_modeled_axis_s": dict(sorted(self.comm_axis_s.items())),
            "generation": self.generation,
        }


def modeled_collective_seconds(
    plan: Any,
    *,
    generation: str = "",
    calibration: Optional[Any] = None,
) -> dict[str, float]:
    """Per-axis modeled serialized collective seconds for one step.

    ``collective_traffic`` bytes (rescaled by the generation's learned
    ``collective_scale`` — pass ``calibration=None`` to load the default
    table) over the generation's ICI/DCN link bandwidth from
    :data:`~torchx_tpu.tune.rank.GENERATION_PERF`.
    """
    from torchx_tpu.analyze import costmodel
    from torchx_tpu.tune import rank
    from torchx_tpu.tune.calibrate import CalibrationTable

    gen = generation or getattr(plan, "accelerator", "")
    if calibration is None:
        calibration = CalibrationTable.load_default().scales_for(gen)
    perf = rank.perf_for(gen)
    out: dict[str, float] = {}
    for t in costmodel.collective_traffic(plan, calibration):
        bw = (
            perf.dcn_bytes_per_s
            if t.network in ("dcn", "mixed")
            else perf.ici_bytes_per_s
        )
        out[t.axis] = out.get(t.axis, 0.0) + t.bytes_per_step / bw
    return out


def attribution_model(
    *,
    flops_per_token: float,
    tokens_per_step: int,
    peak_flops: float,
    param_count: int,
    plan: Any = None,
    generation: str = "",
) -> AttributionModel:
    """Build the :class:`AttributionModel` for one training run.

    ``plan`` (a :class:`~torchx_tpu.analyze.plan.ParallelPlan`) supplies
    the per-axis collective model; without one the comm terms are zero
    (single-device runs have nothing to overlap).
    """
    comm: dict[str, float] = {}
    if plan is not None:
        comm = modeled_collective_seconds(plan, generation=generation)
    return AttributionModel(
        flops_per_token=float(flops_per_token),
        tokens_per_step=int(tokens_per_step),
        peak_flops=float(peak_flops),
        param_count=int(param_count),
        comm_axis_s=comm,
        generation=generation,
    )


def profile_path(session: Optional[str] = None) -> str:
    """The session's profile journal path (``<session dir>/profile.jsonl``)."""
    from torchx_tpu.obs import sinks

    return os.path.join(sinks.session_dir(session), PROFILE_FILE)


class StepProfiler:
    """Records per-step phase segments and appends attributed records.

    The trainer drives it with :meth:`begin_step` / :meth:`phase`
    context-manager hooks / :meth:`end_step`; externally measured slices
    (the prefetcher's wait hook) arrive via :meth:`observe_wait`. Each
    finished step is attributed (see the module docstring), kept
    in-memory for the end-of-run summary, and appended to the journal
    with an fsync so a kill leaves at most one torn final line.

    ``clock`` stamps records with wall time and is an injected seam
    (default-arg reference, never called at import) per the sim-hosted
    clock rules; durations always come from ``time.perf_counter``.
    """

    def __init__(
        self,
        model: AttributionModel,
        *,
        path: Optional[str] = None,
        session: Optional[str] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.model = model
        self.path = path or profile_path(session)
        self._clock = clock
        self._pending: dict[str, float] = {}
        self._step_t0: Optional[float] = None
        self._records: list[dict] = []
        self._wrote_meta = False
        self._journal_ok = True

    # -- recording hooks ---------------------------------------------------

    def begin_step(self) -> None:
        """Open a step window; pending segments from outside a window
        (warmup waits) are discarded."""
        self._pending = {}
        self._step_t0 = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Accrue the block's ``perf_counter`` duration to phase ``name``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._pending[name] = (
                self._pending.get(name, 0.0) + time.perf_counter() - t0
            )

    def observe_wait(self, seconds: float) -> None:
        """Credit externally measured input-wait seconds to ``data_wait``
        (the :meth:`Prefetcher.set_wait_observer` callback target)."""
        self._pending["data_wait"] = self._pending.get("data_wait", 0.0) + float(
            seconds
        )

    def end_step(self, step: int) -> Optional[dict]:
        """Close the step window: attribute, record, append. Returns the
        record, or None without a matching :meth:`begin_step`."""
        if self._step_t0 is None:
            return None
        wall = time.perf_counter() - self._step_t0
        self._step_t0 = None
        measured, self._pending = self._pending, {}
        return self.record_step(step, wall_s=wall, measured=measured)

    def record_step(
        self, step: int, *, wall_s: float, measured: dict[str, float]
    ) -> dict:
        """Attribute one step from externally measured phase seconds
        (what the context-manager hooks collect; exposed directly for
        replayed or simulated steps and tests) and append its record."""
        rec = self._attribute(step, wall_s, measured)
        self._records.append(rec)
        self._append(rec)
        return rec

    # -- attribution -------------------------------------------------------

    def _attribute(
        self, step: int, wall_s: float, measured: dict[str, float]
    ) -> dict:
        """Split the measured slices into the full phase record.

        The fenced device call (``forward_backward`` as measured) fuses
        compute, grad collectives, and the optimizer; the split assigns
        it the modeled optimizer slice, then attributes the residual
        above the roofline compute floor between compute slack and
        exposed collectives in proportion to their modeled shares.
        Phase seconds sum back to the measured slices by construction.
        """
        m = self.model
        device_s = max(0.0, float(measured.get("forward_backward", 0.0)))
        opt_s = min(m.optimizer_s, 0.5 * device_s)
        residual = max(0.0, device_s - m.ideal_compute_s - opt_s)
        total_comm = m.total_comm_s
        exposed = 0.0
        if total_comm > 0.0 and residual > 0.0:
            share = total_comm / (total_comm + m.compute_slack_s)
            exposed = residual * share
        grad_sync = {
            axis: exposed * (s / total_comm)
            for axis, s in sorted(m.comm_axis_s.items())
        } if total_comm > 0.0 else {}
        phases = {
            "data_wait": float(measured.get("data_wait", 0.0)),
            "forward_backward": max(0.0, device_s - opt_s - exposed),
            "optimizer": opt_s,
            "checkpoint": float(measured.get("checkpoint", 0.0)),
            "host": float(measured.get("host", 0.0)),
        }
        mfu = 0.0
        if wall_s > 0 and m.peak_flops > 0:
            mfu = m.tokens_per_step * m.flops_per_token / (wall_s * m.peak_flops)
        overlap = None
        if total_comm > 0.0:
            overlap = 1.0 - min(exposed, total_comm) / total_comm
        return {
            "v": SCHEMA_VERSION,
            "kind": "step",
            "step": int(step),
            "ts": self._clock(),
            "wall_s": float(wall_s),
            "phases": phases,
            "grad_sync": grad_sync,
            "tokens": m.tokens_per_step,
            "mfu": mfu,
            "comm_exposed_s": exposed,
            "comm_modeled_s": total_comm,
            "overlap_frac": overlap,
        }

    # -- journal -----------------------------------------------------------

    def _append(self, rec: dict) -> None:
        """Fsync'd single-line ``O_APPEND`` write (meta record first).
        Best-effort after the first failure: profiling must never take
        down the training job over a full disk."""
        if not self._journal_ok:
            return
        try:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            payload = b""
            if not self._wrote_meta:
                meta = {
                    "v": SCHEMA_VERSION,
                    "kind": "meta",
                    "ts": self._clock(),
                    "pid": os.getpid(),
                    "model": self.model.to_dict(),
                }
                payload += json.dumps(meta, sort_keys=True).encode() + b"\n"
            payload += json.dumps(rec, sort_keys=True).encode() + b"\n"
            fd = os.open(self.path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
            try:
                os.write(fd, payload)
                os.fsync(fd)
            finally:
                os.close(fd)
            self._wrote_meta = True
        except OSError as e:
            self._journal_ok = False
            logger.warning("profile journal unavailable (%s): %s", self.path, e)

    # -- end of run --------------------------------------------------------

    def close(self, *, calibrate: bool = True) -> dict:
        """Summarize the run, export ``tpx_profile_*`` gauges, and (by
        default) feed measured collective seconds into the calibration
        table. Returns the summary (stable ``--json`` schema)."""
        summary = summarize(
            self._records,
            meta={"v": SCHEMA_VERSION, "kind": "meta", "model": self.model.to_dict()},
        )
        export_metrics(summary)
        if calibrate:
            try:
                fold = feed_calibration(summary, generation=self.model.generation)
            except Exception as e:  # noqa: BLE001 - calibration is best-effort
                logger.warning("collective calibration feed failed: %s", e)
            else:
                if fold is not None:
                    summary["calibration"] = fold
        return summary


# -- reading / summarizing ---------------------------------------------------


def load_profile(target: str) -> list[dict]:
    """Records of one profile journal, torn-line holdback included.

    ``target`` is the journal file itself or a session directory
    containing ``profile.jsonl`` (the same reader contract as every
    journal in the repo: a crashed writer leaves at most one unparseable
    final line, which is silently skipped).
    """
    from torchx_tpu.obs.timeline import load_records

    path = target
    if os.path.isdir(target):
        path = os.path.join(target, PROFILE_FILE)
    return load_records(path)


def summarize(records: list[dict], meta: Optional[dict] = None) -> dict:
    """Aggregate step records into the stable ``tpx profile --json`` schema.

    Per-phase totals and fractions (of summed wall time), per-axis
    ``grad_sync`` seconds, mean MFU, data-wait fraction, and the
    aggregate overlap fraction ``1 - Σexposed / Σmodeled``.
    """
    steps = [r for r in records if r.get("kind") == "step"]
    if meta is None:
        meta = next((r for r in records if r.get("kind") == "meta"), None)
    phase_seconds: dict[str, float] = {}
    grad_sync: dict[str, float] = {}
    wall = exposed = modeled = 0.0
    tokens = 0
    mfus: list[float] = []
    for r in steps:
        wall += float(r.get("wall_s", 0.0))
        exposed += float(r.get("comm_exposed_s", 0.0))
        modeled += float(r.get("comm_modeled_s", 0.0))
        tokens += int(r.get("tokens", 0))
        mfus.append(float(r.get("mfu", 0.0)))
        for ph, s in (r.get("phases") or {}).items():
            phase_seconds[ph] = phase_seconds.get(ph, 0.0) + float(s)
        for axis, s in (r.get("grad_sync") or {}).items():
            grad_sync[axis] = grad_sync.get(axis, 0.0) + float(s)
    n = len(steps)
    data_wait = phase_seconds.get("data_wait", 0.0)
    return {
        "v": SCHEMA_VERSION,
        "steps": n,
        "wall_s": wall,
        "step_s": wall / n if n else 0.0,
        "tokens": tokens,
        "phase_seconds": {k: phase_seconds[k] for k in sorted(phase_seconds)},
        "phase_fracs": {
            k: (phase_seconds[k] / wall if wall > 0 else 0.0)
            for k in sorted(phase_seconds)
        },
        "grad_sync_seconds": {k: grad_sync[k] for k in sorted(grad_sync)},
        "mfu": sum(mfus) / n if n else 0.0,
        "data_wait_frac": data_wait / wall if wall > 0 else 0.0,
        "comm_exposed_s": exposed,
        "comm_modeled_s": modeled,
        "overlap_frac": (
            1.0 - min(exposed, modeled) / modeled if modeled > 0 else None
        ),
        "meta": (meta or {}).get("model", {}) if meta else {},
    }


def diff_summaries(a: dict, b: dict) -> dict:
    """Before/after comparison of two summaries (``tpx profile --diff``).

    Tolerates disjoint phase sets (a phase absent on one side reads as
    0.0): the union of phases is compared, so e.g. a checkpointing run
    diffs cleanly against a non-checkpointing one.
    """

    def _scalar(key: str) -> dict:
        va, vb = a.get(key), b.get(key)
        out = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            out["delta"] = vb - va
        return out

    phases: dict[str, dict] = {}
    pa = dict(a.get("phase_seconds") or {})
    pb = dict(b.get("phase_seconds") or {})
    na, nb = max(1, int(a.get("steps") or 0)), max(1, int(b.get("steps") or 0))
    for ph in sorted(set(pa) | set(pb)):
        sa, sb = pa.get(ph, 0.0) / na, pb.get(ph, 0.0) / nb
        phases[ph] = {"a": sa, "b": sb, "delta": sb - sa}
    return {
        "v": SCHEMA_VERSION,
        "steps": {"a": a.get("steps"), "b": b.get("steps")},
        "step_s": _scalar("step_s"),
        "mfu": _scalar("mfu"),
        "data_wait_frac": _scalar("data_wait_frac"),
        "overlap_frac": _scalar("overlap_frac"),
        "phase_step_s": phases,
    }


# -- rendering ---------------------------------------------------------------


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    return f"{v * 1e3:.2f}ms"


def render_summary(summary: dict) -> str:
    """The ``tpx profile`` text view: per-phase timeline bars + the
    roofline/MFU and overlap lines. Pure string building — testable and
    jax-free like ``render_top``."""
    n = summary.get("steps") or 0
    wall = float(summary.get("wall_s") or 0.0)
    lines = [
        f"profile: {n} step(s), {_fmt_s(wall)} wall,"
        f" {_fmt_s(summary.get('step_s') or 0.0)}/step"
    ]
    fracs = summary.get("phase_fracs") or {}
    seconds = summary.get("phase_seconds") or {}
    rows: list[tuple[str, float, float]] = []
    for ph in PHASES:
        if ph in seconds:
            rows.append((ph, seconds[ph], fracs.get(ph, 0.0)))
    for ph in sorted(set(seconds) - set(PHASES)):
        rows.append((ph, seconds[ph], fracs.get(ph, 0.0)))
    for axis, s in sorted((summary.get("grad_sync_seconds") or {}).items()):
        rows.append((f"grad_sync[{axis}]", s, s / wall if wall > 0 else 0.0))
    if rows:
        lines.append(f"  {'phase':<18} {'total':>9} {'frac':>7}")
        peak_frac = max((f for _, _, f in rows), default=0.0)
        for name, sec, frac in rows:
            bar = "#" * int(round(24 * frac / peak_frac)) if peak_frac > 0 else ""
            lines.append(f"  {name:<18} {_fmt_s(sec):>9} {frac:>6.1%}  {bar}")
    model = summary.get("meta") or {}
    mfu = summary.get("mfu") or 0.0
    peak = float(model.get("peak_flops") or 0.0)
    ideal = ""
    if peak > 0 and n:
        ideal_s = (
            float(model.get("tokens_per_step") or 0)
            * float(model.get("flops_per_token") or 0)
            / peak
        )
        ideal = f"  ideal {_fmt_s(ideal_s)}/step at 100% MFU"
    lines.append(f"roofline: MFU {mfu:.2%}{ideal}")
    modeled = float(summary.get("comm_modeled_s") or 0.0)
    if modeled > 0 and n:
        exposed = float(summary.get("comm_exposed_s") or 0.0)
        overlap = summary.get("overlap_frac")
        lines.append(
            f"overlap: modeled comm {_fmt_s(modeled / n)}/step,"
            f" exposed {_fmt_s(exposed / n)}/step"
            f" -> {overlap:.1%} overlapped"
        )
    else:
        lines.append("overlap: no modeled collective traffic (single axis?)")
    cal = summary.get("calibration")
    if cal:
        c = cal.get("collectives", {})
        lines.append(
            f"calibration: collective_scale ->"
            f" {cal.get('scales', {}).get('collective_scale', 1.0):.3g}"
            f" (err {c.get('err_before', 0.0):.2f} -> {c.get('err_after', 0.0):.2f})"
        )
    return "\n".join(lines)


def render_diff(diff: dict) -> str:
    """The ``tpx profile --diff`` text view over :func:`diff_summaries`."""

    def _num(v: Any, pct: bool = False) -> str:
        if not isinstance(v, (int, float)):
            return "-"
        return f"{v:.1%}" if pct else _fmt_s(float(v))

    steps = diff.get("steps") or {}
    lines = [
        f"profile diff: a={steps.get('a')} step(s), b={steps.get('b')} step(s)",
        f"  {'phase':<18} {'a/step':>9} {'b/step':>9} {'delta':>9}",
    ]
    for ph, row in (diff.get("phase_step_s") or {}).items():
        delta = row.get("delta", 0.0)
        sign = "+" if delta >= 0 else "-"
        lines.append(
            f"  {ph:<18} {_num(row.get('a')):>9} {_num(row.get('b')):>9}"
            f" {sign}{_fmt_s(abs(delta)):>8}"
        )
    for key, pct in (("step_s", False), ("mfu", True), ("data_wait_frac", True), ("overlap_frac", True)):
        row = diff.get(key) or {}
        lines.append(
            f"  {key:<18} {_num(row.get('a'), pct):>9} {_num(row.get('b'), pct):>9}"
        )
    return "\n".join(lines)


# -- exports / calibration feedback ------------------------------------------


def export_metrics(summary: dict) -> None:
    """Publish a summary as the process's ``tpx_profile_*`` gauges and
    flush the obs textfile so the telemetry collector (and ``tpx top``)
    can ingest it. Best-effort: metrics must never fail the run."""
    try:
        from torchx_tpu.obs import metrics as obs_metrics

        n = max(1, int(summary.get("steps") or 0))
        for ph, sec in (summary.get("phase_seconds") or {}).items():
            obs_metrics.PROFILE_PHASE_SECONDS.set(sec / n, phase=ph)
        for axis, sec in (summary.get("grad_sync_seconds") or {}).items():
            obs_metrics.PROFILE_PHASE_SECONDS.set(
                sec / n, phase=f"grad_sync[{axis}]"
            )
        obs_metrics.PROFILE_MFU.set(float(summary.get("mfu") or 0.0))
        obs_metrics.PROFILE_DATA_WAIT_FRAC.set(
            float(summary.get("data_wait_frac") or 0.0)
        )
        overlap = summary.get("overlap_frac")
        if overlap is not None:
            obs_metrics.PROFILE_OVERLAP_FRAC.set(float(overlap))
        from torchx_tpu.obs import sinks

        sinks.flush_metrics()
    except Exception as e:  # noqa: BLE001 - metrics export is best-effort
        logger.debug("profile metrics export failed: %s", e)


def feed_calibration(
    summary: dict, *, generation: str, alpha: Optional[float] = None
) -> Optional[dict]:
    """Fold a summary's measured collective seconds into the calibration
    table (``CalibrationTable.observe_collectives``) and save it.

    Returns the fold report, or None when there is nothing to fold (no
    steps, or no modeled/exposed collective time — single-device runs).
    """
    from torchx_tpu.tune.calibrate import DEFAULT_ALPHA, CalibrationTable

    n = int(summary.get("steps") or 0)
    modeled = float(summary.get("comm_modeled_s") or 0.0)
    exposed = float(summary.get("comm_exposed_s") or 0.0)
    if n <= 0 or modeled <= 0.0 or exposed <= 0.0:
        return None
    table = CalibrationTable.load_default()
    out = table.observe_collectives(
        generation,
        predicted_collective_s=modeled / n,
        measured_collective_s=exposed / n,
        alpha=DEFAULT_ALPHA if alpha is None else alpha,
    )
    overlap = summary.get("overlap_frac")
    if overlap is not None:
        # the same run also measured how much collective time the
        # schedule hid; the ranking's overlap discount learns from it
        out["overlap"] = table.observe_overlap(
            generation,
            measured_overlap_frac=float(overlap),
            alpha=DEFAULT_ALPHA if alpha is None else alpha,
        )["overlap"]
    table.save()
    return out
