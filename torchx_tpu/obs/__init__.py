"""Observability for the launch path: traces, metrics, durable sinks.

The subsystem answers "where did my launch time go" end to end:

* :mod:`torchx_tpu.obs.trace` — the :class:`Span` model with contextvar
  propagation; every Runner API call, scheduler materialize/schedule,
  workspace build, and supervisor attempt nests under one trace, and the
  trace context rides into the job via ``$TPX_TRACE_ID`` /
  ``$TPX_PARENT_SPAN``;
* :mod:`torchx_tpu.obs.metrics` — a dependency-free metrics registry
  (counters / gauges / fixed-bucket histograms) with the launcher's
  standard instruments (API latency, wait polls, retries per failure
  class, backoff time, launch latency);
* :mod:`torchx_tpu.obs.sinks` — durable output under
  ``~/.torchx_tpu/obs/<session>/``: a JSONL trace/event sink and a
  Prometheus-textfile metrics exporter, shared with ``TpxEvent`` through
  the events-logger pipeline;
* :mod:`torchx_tpu.obs.timeline` — reads it all back for
  ``tpx trace <app-handle>``;
* :mod:`torchx_tpu.obs.telemetry` — the fleet telemetry plane: the
  control daemon's collector scrapes replica ``/metricz`` endpoints and
  tails textfile sessions into bounded ring-buffer series, served back
  as an aggregated fleet ``/metricz`` and a ``/v1/metrics/query`` JSON
  API (``tpx top`` renders it);
* :mod:`torchx_tpu.obs.slo` — declarative SLO specs evaluated as
  multi-window burn rates with journaled alert transitions; the serve
  autoscaler and the fleet market consume the burn signal;
* :mod:`torchx_tpu.obs.stitch` — cross-process trace stitching: the
  trace context crosses HTTP hops (``X-Tpx-Trace-Id``), KV-transfer
  payloads, and fleet gang env, and ``tpx trace --stitch`` reassembles
  the one timeline per request or fleet-job lifecycle;
* :mod:`torchx_tpu.obs.profile` — per-step phase attribution for the
  trainer (``data_wait`` / ``forward_backward`` / ``grad_sync`` per mesh
  axis / ``optimizer`` / ``checkpoint`` / ``host``): MFU/roofline
  accounting, measured collective overlap, fsync'd ``profile.jsonl``
  journals rendered by ``tpx profile``, and the measured-residual feed
  into the tune calibration table.
"""

from torchx_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from torchx_tpu.obs.sinks import (
    JsonlTraceHandler,
    flush_metrics,
    obs_root,
    session_dir,
    trace_path,
)
from torchx_tpu.obs.trace import (
    Span,
    current_span,
    current_trace_id,
    heartbeat,
    inject_env,
    span,
    tracing_enabled,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlTraceHandler",
    "MetricsRegistry",
    "REGISTRY",
    "Span",
    "current_span",
    "current_trace_id",
    "flush_metrics",
    "heartbeat",
    "inject_env",
    "obs_root",
    "session_dir",
    "span",
    "trace_path",
    "tracing_enabled",
]
