"""Fleet telemetry: bounded time-series store + the scrape collector.

PR 2's obs layer writes *per-process* Prometheus textfiles and the serving
stack exposes *per-replica* ``/metricz`` endpoints — nothing aggregates
them. This module is the central metric plane the control daemon mounts:

* :func:`parse_exposition` — a forgiving Prometheus text-format parser
  (``# TYPE``-aware, full label unescaping, torn lines skipped — the same
  holdback discipline as the JSONL journals);
* :class:`MetricStore` — bounded per-series ring buffers keyed by
  ``(source, name, labels)`` with counter/gauge/histogram-aware merge
  across sources at read time: counters, histogram buckets and sums ADD
  across replicas/processes, so the aggregated fleet view stays
  semantically correct;
* query reducers (:meth:`MetricStore.query`) — ``last``/``sum``/``avg``/
  ``max``/``min``, counter ``rate``, and histogram percentiles
  (``p50``/``p90``/``p95``/``p99``) computed from windowed bucket deltas —
  the JSON API behind the daemon's ``/v1/metrics/query`` and ``tpx top``;
* :class:`Collector` — the periodic ingest loop: registered replica
  ``/metricz`` targets (HTTP scrape) plus every obs session's
  ``metrics-*.prom`` textfiles, each cycle followed by registered hooks
  (the daemon hangs the SLO engine there).

stdlib-only and jax-free: the collector runs inside the control daemon
and ``tpx top`` must render without pulling in the run path.
"""

from __future__ import annotations

import glob
import logging
import math
import os
import re
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from torchx_tpu import settings
from torchx_tpu.obs import sinks

logger = logging.getLogger(__name__)

__all__ = [
    "PromSample",
    "parse_exposition",
    "Series",
    "MetricStore",
    "scrape_metricz",
    "Collector",
]

#: canonical label encoding inside the store: sorted (key, value) pairs.
LabelSet = tuple[tuple[str, str], ...]

# name{...labels...} value — labels greedy to the LAST brace so quoted
# label values containing "}" survive; the value is never a brace.
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)(?:\s+\d+)?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')
_TYPE_RE = re.compile(r"^#\s*TYPE\s+(\S+)\s+(\S+)\s*$")
_HELP_RE = re.compile(r"^#\s*HELP\s+(\S+)\s+(.*)$")


def _unescape(value: str) -> str:
    """Inverse of the exposition-format label escaping."""
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _parse_value(raw: str) -> Optional[float]:
    low = raw.lower()
    if low in ("+inf", "inf"):
        return math.inf
    if low == "-inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError:
        return None


@dataclass(frozen=True)
class PromSample:
    """One parsed exposition line: a metric name, its canonical label set,
    the sample value, and the ``# TYPE`` kind in force when it was read
    (``counter``/``gauge``/``histogram``/``untyped``)."""

    name: str
    labels: LabelSet
    value: float
    kind: str = "untyped"


def parse_exposition(text: str) -> list[PromSample]:
    """Parse Prometheus text format into :class:`PromSample` rows.

    Tolerant by design — a torn tail line, an unparseable value, or a
    malformed label set skips that LINE, never the whole payload (a
    crashed writer may leave a partially-written textfile; readers must
    survive, exactly like :func:`torchx_tpu.obs.timeline.load_records`).
    ``# TYPE`` lines assign the kind to subsequent samples of that family
    (``name``, ``name_bucket``, ``name_sum``, ``name_count``)."""
    kinds: dict[str, str] = {}
    out: list[PromSample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                kinds[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        value = _parse_value(m.group("value"))
        if value is None:
            continue
        raw_labels = m.group("labels")
        labels: list[tuple[str, str]] = []
        if raw_labels:
            # reject a label blob whose pairs don't reconstruct it — a
            # torn line truncated inside a quoted value must not half-parse
            matched_len = 0
            for lm in _LABEL_RE.finditer(raw_labels):
                labels.append((lm.group(1), _unescape(lm.group(2))))
                matched_len = lm.end()
            tail = raw_labels[matched_len:].strip().rstrip(",").strip()
            if tail:
                continue
        name = m.group("name")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        kind = kinds.get(name) or kinds.get(base, "untyped")
        out.append(
            PromSample(
                name=name, labels=tuple(sorted(labels)), value=value, kind=kind
            )
        )
    return out


@dataclass
class Series:
    """One source's bounded ring buffer for one ``(name, labels)`` series.

    ``samples`` holds ``(epoch_seconds, value)`` pairs, oldest first,
    capped at the store's capacity (appending past it drops the oldest
    sample — bounded memory no matter how long the daemon runs)."""

    name: str
    labels: LabelSet
    kind: str = "untyped"
    samples: deque = field(default_factory=deque)

    def last(self) -> Optional[float]:
        """Most recent value, or None for an empty series."""
        return self.samples[-1][1] if self.samples else None

    def window(self, range_s: Optional[float], now: float) -> list:
        """Samples inside ``[now - range_s, now]`` (all, when range is
        None), oldest first."""
        if range_s is None:
            return list(self.samples)
        lo = now - range_s
        return [(t, v) for t, v in self.samples if t >= lo]

    def delta(self, range_s: Optional[float], now: float) -> float:
        """Cumulative-counter increase over the window. A mid-window
        counter reset (value decreased) contributes the post-reset value,
        the standard Prometheus ``increase()`` approximation."""
        win = self.window(range_s, now)
        if len(win) < 2:
            return 0.0
        total = 0.0
        prev = win[0][1]
        for _, v in win[1:]:
            total += v - prev if v >= prev else v
            prev = v
        return max(0.0, total)


_PERCENTILE_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)$")

#: reducers accepted by :meth:`MetricStore.query` (percentiles besides).
SCALAR_REDUCERS = ("last", "sum", "avg", "max", "min", "rate")


class MetricStore:
    """Bounded multi-source time-series store with merge-aware reads.

    Writes are per ``(source, name, labels)`` ring buffer
    (:meth:`ingest` / :meth:`ingest_text`); reads aggregate across
    sources: counters/histogram components SUM (each replica counts its
    own events), gauges SUM too (fleet totals — the standard
    textfile-collector convention :func:`timeline.load_metrics` already
    follows). Thread-safe: the daemon's collector writes while HTTP
    readers query.
    """

    def __init__(
        self,
        capacity: int = settings.DEFAULT_TELEMETRY_CAPACITY,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.capacity = int(capacity)
        self.clock = clock
        self._lock = threading.Lock()
        self._series: dict[tuple[str, str, LabelSet], Series] = {}
        self._kinds: dict[str, str] = {}

    def __len__(self) -> int:
        """Number of per-source series currently stored."""
        with self._lock:
            return len(self._series)

    def ingest(
        self,
        source: str,
        samples: Iterable[PromSample],
        ts: Optional[float] = None,
    ) -> int:
        """Append one scrape's samples under ``source``; returns the
        number of samples ingested. Each distinct label set gets its own
        ring buffer; kinds upgrade ``untyped`` series when a later scrape
        carries ``# TYPE``."""
        now = self.clock() if ts is None else ts
        n = 0
        with self._lock:
            for s in samples:
                key = (source, s.name, s.labels)
                series = self._series.get(key)
                if series is None:
                    series = Series(
                        name=s.name,
                        labels=s.labels,
                        kind=s.kind,
                        samples=deque(maxlen=self.capacity),
                    )
                    self._series[key] = series
                if s.kind != "untyped":
                    series.kind = s.kind
                    self._kinds[s.name] = s.kind
                elif s.name not in self._kinds:
                    self._kinds.setdefault(s.name, s.kind)
                series.samples.append((now, s.value))
                n += 1
        return n

    def ingest_text(
        self, source: str, text: str, ts: Optional[float] = None
    ) -> int:
        """Parse exposition ``text`` and ingest it under ``source``."""
        return self.ingest(source, parse_exposition(text), ts=ts)

    def names(self) -> list[str]:
        """Sorted distinct metric names across all sources."""
        with self._lock:
            return sorted({name for _, name, _ in self._series})

    def kind_of(self, name: str) -> str:
        """Recorded ``# TYPE`` kind for ``name`` (``untyped`` default)."""
        with self._lock:
            return self._kinds.get(name, "untyped")

    def _matching(
        self, name: str, labels: Optional[dict] = None
    ) -> list[tuple[str, Series]]:
        want = dict(labels or {})
        out = []
        with self._lock:
            for (source, sname, lset), series in self._series.items():
                if sname != name:
                    continue
                have = dict(lset)
                if any(have.get(k) != str(v) for k, v in want.items()):
                    continue
                out.append((source, series))
        return out

    # -- aggregated reads --------------------------------------------------

    def latest(self, name: str, labels: Optional[dict] = None) -> dict:
        """Latest value per label set, summed across sources."""
        acc: dict[LabelSet, float] = {}
        for _, series in self._matching(name, labels):
            v = series.last()
            if v is None:
                continue
            acc[series.labels] = acc.get(series.labels, 0.0) + v
        return {k: acc[k] for k in sorted(acc)}

    def render_prom(self) -> str:
        """The aggregated fleet exposition: every series summed across
        sources, with ``# HELP``/``# TYPE`` headers and proper label
        escaping — what the daemon serves as its ``/metricz``."""
        from torchx_tpu.obs.metrics import _escape, _format_value

        lines: list[str] = []
        for name in self.names():
            kind = self.kind_of(name)
            lines.append(f"# HELP {name} aggregated across fleet sources")
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in self.latest(name).items():
                if labels:
                    inner = ",".join(
                        f'{k}="{_escape(v)}"' for k, v in labels
                    )
                    lines.append(f"{name}{{{inner}}} {_format_value(value)}")
                else:
                    lines.append(f"{name} {_format_value(value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def histogram_deltas(
        self,
        name: str,
        range_s: Optional[float],
        now: Optional[float] = None,
        labels: Optional[dict] = None,
    ) -> dict[LabelSet, list[tuple[float, float]]]:
        """Windowed cumulative-bucket increases of histogram ``name``,
        grouped by label set minus ``le`` and summed across sources:
        ``{labels: [(le, delta), ...]}`` sorted by ``le``. The SLO
        engine's raw material."""
        now = self.clock() if now is None else now
        acc: dict[LabelSet, dict[float, float]] = {}
        for _, series in self._matching(f"{name}_bucket", labels):
            lab = dict(series.labels)
            le = _parse_value(lab.pop("le", ""))
            if le is None:
                continue
            group = tuple(sorted(lab.items()))
            by_le = acc.setdefault(group, {})
            by_le[le] = by_le.get(le, 0.0) + series.delta(range_s, now)
        return {
            group: sorted(by_le.items())
            for group, by_le in sorted(acc.items())
        }

    def percentile(
        self,
        name: str,
        q: float,
        range_s: Optional[float] = None,
        now: Optional[float] = None,
        labels: Optional[dict] = None,
    ) -> dict[LabelSet, float]:
        """Per-label-set ``q``-percentile (0..100) of histogram ``name``
        over the window, linear-interpolated within the winning bucket
        (the classic ``histogram_quantile`` estimate)."""
        out: dict[LabelSet, float] = {}
        for group, buckets in self.histogram_deltas(
            name, range_s, now=now, labels=labels
        ).items():
            total = buckets[-1][1] if buckets else 0.0
            if total <= 0:
                continue
            rank = (q / 100.0) * total
            lo_bound, lo_count = 0.0, 0.0
            value = buckets[-1][0]
            for le, cum in buckets:
                if cum >= rank:
                    width = le - lo_bound
                    frac = (
                        (rank - lo_count) / (cum - lo_count)
                        if cum > lo_count
                        else 0.0
                    )
                    value = (
                        lo_bound + width * frac
                        if math.isfinite(le)
                        else lo_bound
                    )
                    break
                lo_bound, lo_count = le, cum
            out[group] = value
        return out

    def query(
        self,
        name: str,
        labels: Optional[dict] = None,
        reduce: Optional[str] = None,
        range_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> dict:
        """The JSON query API: raw windowed series plus an optional
        reduced scalar per label set.

        Reducers: ``last``/``sum`` (same thing for the cross-source
        aggregate), ``avg``/``max``/``min`` over the window, ``rate``
        (counter increase / window seconds), and ``pNN`` histogram
        percentiles. Unknown reducers raise ``ValueError``."""
        now = self.clock() if now is None else now
        matches = self._matching(name, labels)
        series_out = [
            {
                "source": source,
                "labels": dict(s.labels),
                "points": [[t, v] for t, v in s.window(range_s, now)],
            }
            for source, s in sorted(matches, key=lambda x: (x[0], x[1].labels))
        ]
        doc: dict[str, Any] = {
            "name": name,
            "kind": self.kind_of(name),
            "reduce": reduce or "none",
            "range_s": range_s,
            "series": series_out,
        }
        if not reduce:
            return doc
        pm = _PERCENTILE_RE.match(reduce)
        result: dict[LabelSet, float] = {}
        if pm:
            result = self.percentile(
                name, float(pm.group(1)), range_s=range_s, now=now, labels=labels
            )
        elif reduce in ("last", "sum"):
            result = self.latest(name, labels)
        elif reduce == "rate":
            span = range_s or 60.0
            for _, s in matches:
                d = s.delta(range_s, now)
                result[s.labels] = result.get(s.labels, 0.0) + d / span
        elif reduce in ("avg", "max", "min"):
            fn = {"avg": None, "max": max, "min": min}[reduce]
            per: dict[LabelSet, list[float]] = {}
            for _, s in matches:
                vals = [v for _, v in s.window(range_s, now)]
                if vals:
                    per.setdefault(s.labels, []).extend(vals)
            for lset, vals in per.items():
                result[lset] = (
                    sum(vals) / len(vals) if fn is None else fn(vals)
                )
        else:
            raise ValueError(
                f"unknown reducer {reduce!r}; use one of"
                f" {SCALAR_REDUCERS} or pNN"
            )
        doc["result"] = [
            {"labels": dict(lset), "value": value}
            for lset, value in sorted(result.items())
        ]
        return doc


def scrape_metricz(url: str, timeout: float = 5.0) -> str:
    """GET one replica's Prometheus exposition. ``url`` may be a base
    (``http://host:port``) or already end in ``/metricz``."""
    target = url if url.rstrip("/").endswith("/metricz") else (
        url.rstrip("/") + "/metricz"
    )
    with urllib.request.urlopen(target, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


class Collector:
    """The periodic ingest loop the control daemon runs.

    Each cycle: scrape every registered HTTP target, re-read every obs
    session's ``metrics-*.prom`` textfiles (per-file sources, so per-pid
    writers never clobber each other in the store), then run the
    registered hooks (the daemon's SLO evaluation). Scrape failures are
    counted per target and never abort the cycle."""

    def __init__(
        self,
        store: MetricStore,
        interval_s: Optional[float] = None,
        obs_dir: Optional[str] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if interval_s is None:
            raw = os.environ.get(settings.ENV_TPX_TELEMETRY_INTERVAL, "")
            try:
                interval_s = float(raw) if raw else None
            except ValueError:
                interval_s = None
        self.store = store
        self.interval_s = (
            settings.DEFAULT_TELEMETRY_INTERVAL
            if interval_s is None
            else float(interval_s)
        )
        self.obs_dir = obs_dir
        self.clock = clock
        self.hooks: list[Callable[[], None]] = []
        self.errors: dict[str, str] = {}
        self.cycles = 0
        self._targets: dict[str, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_target(self, url: str, name: Optional[str] = None) -> str:
        """Register one ``/metricz`` scrape target; returns its source
        name (used as the store's source key and in error reports)."""
        key = name or url
        with self._lock:
            self._targets[key] = url
        return key

    def remove_target(self, name: str) -> bool:
        """Drop a scrape target by its source name."""
        with self._lock:
            return self._targets.pop(name, None) is not None

    def targets(self) -> dict[str, str]:
        """Snapshot of registered targets (``{name: url}``)."""
        with self._lock:
            return dict(self._targets)

    def collect_once(self) -> int:
        """One full cycle; returns samples ingested. Never raises."""
        n = 0
        ts = self.clock()
        for name, url in self.targets().items():
            try:
                n += self.store.ingest_text(
                    name, scrape_metricz(url), ts=ts
                )
                self.errors.pop(name, None)
            except Exception as e:  # noqa: BLE001 - a dead replica is data
                self.errors[name] = f"{type(e).__name__}: {e}"
        root = self.obs_dir or sinks.obs_root()
        for path in glob.glob(
            os.path.join(root, "*", sinks.METRICS_GLOB)
        ):
            try:
                with open(path) as f:
                    text = f.read()
            except OSError:
                continue
            session = os.path.basename(os.path.dirname(path))
            source = f"file:{session}/{os.path.basename(path)}"
            n += self.store.ingest_text(source, text, ts=ts)
        for hook in list(self.hooks):
            try:
                hook()
            except Exception as e:  # noqa: BLE001 - hooks must not kill it
                logger.warning("telemetry hook failed: %s", e)
        self.cycles += 1
        return n

    def start(self) -> "Collector":
        """Run :meth:`collect_once` every ``interval_s`` on a daemon
        thread until :meth:`stop`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                self.collect_once()

        self._thread = threading.Thread(
            target=_loop, name="tpx-telemetry", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the collect loop (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
