"""In-process metrics registry with a Prometheus-textfile exporter.

Counters, gauges, and fixed-bucket histograms, labeled, thread-safe, and
dependency-free — the launch path is low-rate, so a dict behind a lock is
the right amount of machinery. :meth:`MetricsRegistry.render` emits the
Prometheus text exposition format; :func:`torchx_tpu.obs.sinks.flush_metrics`
writes it atomically to a per-process ``.prom`` textfile that a node
exporter (or ``tpx trace --metrics``) picks up.

The module-level instruments below are the launcher's standard metrics:
API latency, poll counts, retries per failure class, backoff time, and
launch latency (submit-to-app-id client-side, launch-to-first-step
in-job).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Mapping, Optional, Sequence

LabelValues = tuple[str, ...]

#: default histogram buckets (seconds), tuned for launcher latencies:
#: sub-second API calls up to multi-minute scheduling waits.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.005,
    0.025,
    0.1,
    0.5,
    1.0,
    5.0,
    15.0,
    30.0,
    60.0,
    120.0,
    300.0,
    600.0,
)


def _format_labels(names: Sequence[str], values: LabelValues) -> str:
    if not names:
        return ""
    pairs = ",".join(
        f'{n}="{_escape(v)}"' for n, v in zip(names, values)
    )
    return "{" + pairs + "}"


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class _Metric:
    """Shared label plumbing for all instrument types."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]) -> None:  # noqa: A002
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, str]) -> LabelValues:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames},"
                f" got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def render(self) -> list[str]:
        """One Prometheus text-format sample line per labeled series."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count (e.g. polls, retries)."""

    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:  # noqa: A002
        super().__init__(name, help, labelnames)
        self._values: dict[LabelValues, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        """Add ``amount`` (must be >= 0) to the labeled series."""
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        """Current value of the labeled series (0 if never incremented)."""
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            return [
                f"{self.name}{_format_labels(self.labelnames, k)} {_format_value(v)}"
                for k, v in sorted(self._values.items())
            ]


class Gauge(_Metric):
    """A value that can go up and down (e.g. active attempts)."""

    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()) -> None:  # noqa: A002
        super().__init__(name, help, labelnames)
        self._values: dict[LabelValues, float] = {}

    def set(self, value: float, **labels: str) -> None:
        """Set the labeled series to ``value``."""
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def value(self, **labels: str) -> float:
        """Current value of the labeled series (0 if never set)."""
        return self._values.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        with self._lock:
            return [
                f"{self.name}{_format_labels(self.labelnames, k)} {_format_value(v)}"
                for k, v in sorted(self._values.items())
            ]


class Histogram(_Metric):
    """Fixed-bucket distribution (cumulative buckets, Prometheus style)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,  # noqa: A002
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        super().__init__(name, help, labelnames)
        if list(buckets) != sorted(buckets) or not buckets:
            raise ValueError(f"histogram {name} buckets must be sorted and non-empty")
        self.buckets = tuple(float(b) for b in buckets)
        # per-series: [bucket counts..., +Inf count], sum
        self._counts: dict[LabelValues, list[int]] = {}
        self._sums: dict[LabelValues, float] = {}

    def observe(self, value: float, **labels: str) -> None:
        """Record one observation into the labeled series."""
        key = self._key(labels)
        idx = bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + float(value)

    def count(self, **labels: str) -> int:
        """Total observations in the labeled series."""
        return sum(self._counts.get(self._key(labels), ()))

    def sum(self, **labels: str) -> float:
        """Sum of observations in the labeled series."""
        return self._sums.get(self._key(labels), 0.0)

    def render(self) -> list[str]:
        lines = []
        with self._lock:
            for key in sorted(self._counts):
                counts = self._counts[key]
                cumulative = 0
                names = (*self.labelnames, "le")
                for bound, n in zip(self.buckets, counts):
                    cumulative += n
                    values = (*key, _format_value(bound))
                    lines.append(
                        f"{self.name}_bucket{_format_labels(names, values)} {cumulative}"
                    )
                cumulative += counts[-1]
                values = (*key, "+Inf")
                lines.append(
                    f"{self.name}_bucket{_format_labels(names, values)} {cumulative}"
                )
                lines.append(
                    f"{self.name}_sum{_format_labels(self.labelnames, key)}"
                    f" {_format_value(self._sums[key])}"
                )
                lines.append(
                    f"{self.name}_count{_format_labels(self.labelnames, key)}"
                    f" {cumulative}"
                )
        return lines


class MetricsRegistry:
    """Name-keyed collection of instruments; ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent across modules), and
    :meth:`render` emits the whole registry in Prometheus text format."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def __repr__(self) -> str:
        # stable (no object address): this repr lands in generated docs
        return f"MetricsRegistry({sorted(self._metrics)})"

    def _get_or_create(self, cls, name: str, *args, **kwargs) -> _Metric:  # noqa: ANN001,ANN002
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name} already registered as {existing.kind}"
                    )
                return existing
            metric = cls(name, *args, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str, labelnames: Sequence[str] = ()  # noqa: A002
    ) -> Counter:
        """Get or create a :class:`Counter`."""
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str, labelnames: Sequence[str] = ()  # noqa: A002
    ) -> Gauge:
        """Get or create a :class:`Gauge`."""
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str,  # noqa: A002
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        """Get or create a :class:`Histogram` with fixed ``buckets``."""
        return self._get_or_create(Histogram, name, help, labelnames, buckets)

    def get(self, name: str) -> Optional[_Metric]:
        """The registered instrument, or None."""
        return self._metrics.get(name)

    def render(self) -> str:
        """The whole registry in Prometheus text exposition format
        (HELP/TYPE headers + one line per labeled series). Series-less
        instruments render headers only, so the page documents every
        metric the launcher can emit."""
        out: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            out.extend(m.render())
        return "\n".join(out) + "\n"


#: the process-wide registry every instrument below lives in.
REGISTRY = MetricsRegistry()

#: latency of each Runner API call, by api + scheduler.
API_LATENCY = REGISTRY.histogram(
    "tpx_api_latency_seconds",
    "Runner API call latency in seconds",
    ("api", "scheduler"),
)

#: Runner API call count by api + scheduler + outcome ("ok"/"error").
API_CALLS = REGISTRY.counter(
    "tpx_api_calls_total",
    "Runner API calls",
    ("api", "scheduler", "status"),
)

#: status polls issued by Runner.wait, by scheduler.
WAIT_POLLS = REGISTRY.counter(
    "tpx_wait_polls_total",
    "status polls issued while waiting for a terminal state",
    ("scheduler",),
)

#: supervisor resubmissions, by failure class.
RETRIES = REGISTRY.counter(
    "tpx_supervisor_retries_total",
    "supervisor resubmissions by failure class",
    ("failure_class",),
)

#: total seconds the supervisor spent in backoff sleeps.
BACKOFF_SECONDS = REGISTRY.counter(
    "tpx_supervisor_backoff_seconds_total",
    "total supervisor backoff sleep seconds",
)

#: unhealthy gang verdicts from the gang monitor (status still RUNNING),
#: by verdict kind (HANG / PARTIAL_LOSS / STRAGGLER).
GANG_UNHEALTHY = REGISTRY.counter(
    "tpx_gang_unhealthy_total",
    "unhealthy gang-health verdicts by kind",
    ("kind",),
)

#: elastic mesh reshapes computed for a resubmission (dp/fsdp shrunk to
#: fit surviving capacity).
GANG_RESHAPES = REGISTRY.counter(
    "tpx_gang_reshapes_total",
    "elastic mesh reshapes applied on resubmit",
)

#: client-side launch latency: schedule() call to app_id in hand.
LAUNCH_SECONDS = REGISTRY.histogram(
    "tpx_launch_seconds",
    "scheduler submit latency (schedule call to app id) in seconds",
    ("scheduler",),
)

#: in-job launch-to-first-step latency (reported by train heartbeats).
LAUNCH_TO_FIRST_STEP = REGISTRY.histogram(
    "tpx_launch_to_first_step_seconds",
    "process start to first completed training step in seconds",
)

#: steady-state training step time, by phase: "total" = wall time per
#: step, "data_wait" = the slice of it the host spent blocked on input
#: (prefetcher queue waits). Fed at each log fence with the window's
#: per-step averages — the ``step.*`` trace-family counterpart.
STEP_SECONDS = REGISTRY.histogram(
    "tpx_step_seconds",
    "training step seconds by phase (total / data_wait)",
    ("phase",),
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)

#: step-profiler summary exports (obs/profile.py): the last profiled
#: run's per-step attribution, published so the telemetry plane and
#: ``tpx top`` can surface fleet-wide MFU / data-wait / overlap without
#: reading any profile journal. Gauges (not histograms): each profiled
#: run overwrites its process's snapshot.
PROFILE_PHASE_SECONDS = REGISTRY.gauge(
    "tpx_profile_phase_seconds",
    "profiled per-step seconds by attribution phase",
    ("phase",),
)

#: model FLOPs utilization of the last profiled run.
PROFILE_MFU = REGISTRY.gauge(
    "tpx_profile_mfu",
    "model FLOPs utilization measured by the step profiler",
)

#: fraction of profiled step time the host spent blocked on input.
PROFILE_DATA_WAIT_FRAC = REGISTRY.gauge(
    "tpx_profile_data_wait_frac",
    "fraction of profiled step time spent waiting on input",
)

#: collective overlap fraction (1 - exposed/modeled comm time).
PROFILE_OVERLAP_FRAC = REGISTRY.gauge(
    "tpx_profile_overlap_frac",
    "profiled collective overlap fraction (1 - exposed/modeled comm)",
)

#: per-stage breakdown of launch-to-first-step (the ``launch.*`` span
#: family): import / backend_init / init_state / restore / data_setup /
#: compile / first_step — makes launch regressions attributable.
LAUNCH_STAGE_SECONDS = REGISTRY.histogram(
    "tpx_launch_stage_seconds",
    "seconds spent per launch bootstrap stage",
    ("stage",),
)

#: Runner describe-cache hits (TTL-fresh, pinned-terminal, or coalesced
#: onto an in-flight fetch), by scheduler.
DESCRIBE_CACHE_HITS = REGISTRY.counter(
    "tpx_describe_cache_hits_total",
    "describe calls served from the Runner describe cache",
    ("scheduler",),
)

#: Runner describe-cache misses (a real backend describe was issued).
DESCRIBE_CACHE_MISSES = REGISTRY.counter(
    "tpx_describe_cache_misses_total",
    "describe calls that went through to the scheduler backend",
    ("scheduler",),
)

#: preflight lint runs, by entry point ("runner"/"cli") and outcome
#: ("clean"/"errors").
LINT_RUNS = REGISTRY.counter(
    "tpx_lint_runs_total",
    "preflight analyzer runs",
    ("gate", "status"),
)

#: diagnostics emitted by the preflight analyzer, by code + severity.
LINT_DIAGNOSTICS = REGISTRY.counter(
    "tpx_lint_diagnostics_total",
    "preflight diagnostics emitted",
    ("code", "severity"),
)

#: deep-preflight (``tpx explain``) runs, by entry point and outcome.
EXPLAIN_RUNS = REGISTRY.counter(
    "tpx_explain_runs_total",
    "deep-preflight analyzer runs",
    ("gate", "status"),
)

#: TPX7xx diagnostics emitted by the deep preflight, by code + severity.
EXPLAIN_DIAGNOSTICS = REGISTRY.counter(
    "tpx_explain_diagnostics_total",
    "deep-preflight diagnostics emitted",
    ("code", "severity"),
)

#: statically-predicted per-chip HBM usage of the last explained plan,
#: by role — compared against the measured/compiled numbers in BENCH.
EXPLAIN_HBM_TOTAL_BYTES = REGISTRY.gauge(
    "tpx_explain_hbm_total_bytes",
    "per-chip HBM bytes the deep preflight predicts for a role's plan",
    ("role",),
)

#: candidates the config autotuner (``tpx tune``) enumerated, by model
#: config — the top of the prune funnel.
TUNE_CANDIDATES = REGISTRY.counter(
    "tpx_tune_candidates_total",
    "autotuner candidates enumerated from the search space",
    ("config",),
)

#: autotuner candidates killed before any device time, by prune stage
#: ("static" = deep-preflight verdict, "aot" = XLA AOT memory fit) and
#: the diagnostic code / verdict that killed them.
TUNE_PRUNED = REGISTRY.counter(
    "tpx_tune_pruned_total",
    "autotuner candidates pruned with zero device seconds",
    ("stage", "code"),
)

#: autotuner trials that reached a device, by outcome ("ok"/"failed").
TUNE_MEASURED = REGISTRY.counter(
    "tpx_tune_measured_total",
    "autotuner measured trials",
    ("status",),
)

#: control-plane calls issued through the resilient seam, by backend +
#: logical op + outcome ("ok"/"error"/"rejected" — rejected means the
#: backend's circuit breaker refused the call).
CONTROL_PLANE_CALLS = REGISTRY.counter(
    "tpx_control_plane_calls_total",
    "control-plane calls issued through the resilient seam",
    ("backend", "op", "status"),
)

#: control-plane call retries, by backend + op + classified failure kind.
CONTROL_PLANE_RETRIES = REGISTRY.counter(
    "tpx_control_plane_retries_total",
    "control-plane call retries by failure kind",
    ("backend", "op", "kind"),
)

#: per-backend circuit breaker state (0 closed, 1 half-open, 2 open).
BREAKER_STATE = REGISTRY.gauge(
    "tpx_control_plane_breaker_state",
    "control-plane circuit breaker state (0 closed, 1 half-open, 2 open)",
    ("backend",),
)

#: serving-engine slot occupancy: fraction of decode slots holding an
#: active sequence this step (sustained occupancy is what keeps
#: HBM-bandwidth-bound decode fed — the continuous-batching win).
SERVE_OCCUPANCY = REGISTRY.gauge(
    "tpx_serve_slot_occupancy",
    "fraction of decode slots active in the serving engine",
)

#: decode slots currently holding an active sequence.
SERVE_SLOTS_ACTIVE = REGISTRY.gauge(
    "tpx_serve_slots_active",
    "decode slots currently active in the serving engine",
)

#: requests admitted but not yet completed, waiting for a free slot.
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "tpx_serve_queue_depth",
    "requests waiting for a decode slot in the serving engine",
)

#: paged KV blocks currently allocated to live sequences.
SERVE_KV_BLOCKS_USED = REGISTRY.gauge(
    "tpx_serve_kv_blocks_used",
    "paged KV-cache blocks held by active sequences",
)

#: decode tokens produced, by phase ("prefill" first tokens vs "decode").
SERVE_TOKENS = REGISTRY.counter(
    "tpx_serve_tokens_total",
    "tokens produced by the serving engine",
    ("phase",),
)

#: completed requests, by outcome ("ok"/"error").
SERVE_REQUESTS = REGISTRY.counter(
    "tpx_serve_requests_total",
    "requests completed by the serving engine",
    ("status",),
)

#: sequences preempted (blocks reclaimed, request requeued) because the
#: KV pool ran out of free blocks mid-decode.
SERVE_PREEMPTIONS = REGISTRY.counter(
    "tpx_serve_preemptions_total",
    "sequences preempted for KV-pool pressure and requeued",
)

#: time-to-first-token per request, seconds.
SERVE_TTFT_SECONDS = REGISTRY.histogram(
    "tpx_serve_ttft_seconds",
    "request time-to-first-token in seconds",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)

#: per-token decode latency (time-per-output-token) per request, seconds.
SERVE_TPOT_SECONDS = REGISTRY.histogram(
    "tpx_serve_tpot_seconds",
    "request mean time-per-output-token in seconds",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0),
)

#: serve-pool replica count, as last applied by the autoscaler.
SERVE_REPLICAS = REGISTRY.gauge(
    "tpx_serve_replicas",
    "generate_server replicas the serve pool is currently running",
)

#: serve-pool autoscaling decisions, by direction ("up"/"down").
SERVE_SCALE_EVENTS = REGISTRY.counter(
    "tpx_serve_scale_events_total",
    "serve-pool autoscale resizes applied",
    ("direction",),
)

#: prefix-cache lookups that matched at least one cached block.
SERVE_PREFIX_HITS = REGISTRY.counter(
    "tpx_serve_prefix_hits_total",
    "prefix-cache lookups that reused cached KV blocks",
)

#: prefix-cache lookups that matched nothing (cold prefix).
SERVE_PREFIX_MISSES = REGISTRY.counter(
    "tpx_serve_prefix_misses_total",
    "prefix-cache lookups with no cached prefix",
)

#: prompt tokens served from cached KV blocks instead of re-prefilling.
SERVE_PREFIX_HIT_TOKENS = REGISTRY.counter(
    "tpx_serve_prefix_hit_tokens_total",
    "prompt tokens whose KV came from the prefix cache",
)

#: KV blocks currently pinned by the prefix cache (refcount held).
SERVE_PREFIX_CACHED_BLOCKS = REGISTRY.gauge(
    "tpx_serve_prefix_cached_blocks",
    "paged KV blocks pinned by the prefix cache",
)

#: cache-only blocks evicted (LRU) under pool pressure or reserve cap.
SERVE_PREFIX_EVICTIONS = REGISTRY.counter(
    "tpx_serve_prefix_evictions_total",
    "prefix-cache blocks evicted back to the free list",
)

#: copy-on-write block copies (shared tail block about to be written).
SERVE_COW_COPIES = REGISTRY.counter(
    "tpx_serve_cow_copies_total",
    "shared KV blocks copied before an in-place append",
)

#: prefill->decode KV handoffs, by outcome ("ok"/"rejected"/"error") —
#: "rejected" is a draining decode target (the transfer is requeued).
SERVE_KV_TRANSFERS = REGISTRY.counter(
    "tpx_serve_kv_transfers_total",
    "KV block transfers between prefill and decode replicas",
    ("status",),
)

#: payload bytes moved prefill->decode (K+V blocks, serialized).
SERVE_KV_TRANSFER_BYTES = REGISTRY.counter(
    "tpx_serve_kv_transfer_bytes_total",
    "bytes of KV blocks streamed from prefill to decode replicas",
)

# -- fleet control plane (torchx_tpu/control/) ------------------------------

#: state-transition events emitted by scheduler watch streams, by source
#: ("sidecar"/"kubectl"/"poll") — the control plane's unit of work.
WATCH_EVENTS = REGISTRY.counter(
    "tpx_watch_events_total",
    "scheduler watch-stream state events observed",
    ("scheduler", "source"),
)

#: live watch streams, one per (scheduler, reconciler) pair.
WATCH_STREAMS = REGISTRY.gauge(
    "tpx_watch_streams",
    "watch streams currently owned by a reconciler",
    ("scheduler",),
)

#: Runner.wait waiters woken early by a reconciler event (instead of
#: sleeping out their full poll interval).
WAITER_WAKEUPS = REGISTRY.counter(
    "tpx_waiter_wakeups_total",
    "wait() waiters woken by a watch event before their poll interval",
    ("scheduler",),
)

#: control-daemon HTTP requests, by logical op and response code.
CONTROL_REQUESTS = REGISTRY.counter(
    "tpx_control_requests_total",
    "control daemon API requests served",
    ("op", "code"),
)

#: control-daemon request latency by logical op.
CONTROL_REQUEST_SECONDS = REGISTRY.histogram(
    "tpx_control_request_seconds",
    "control daemon API request latency in seconds",
    ("op",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0),
)

#: active (non-terminal) jobs the daemon tracks per tenant — the value the
#: per-tenant 429 cap is enforced against.
CONTROL_ACTIVE_JOBS = REGISTRY.gauge(
    "tpx_control_active_jobs",
    "active jobs per control-daemon tenant",
    ("tenant",),
)

# -- fleet scheduler (torchx_tpu/fleet/) -------------------------------------

#: gangs waiting in the fleet queue, per priority class.
FLEET_QUEUE_DEPTH = REGISTRY.gauge(
    "tpx_fleet_queue_depth",
    "gangs queued in the fleet scheduler per priority class",
    ("klass",),
)

#: modeled fleet capacity in chips (series: state="total" / state="free").
FLEET_CHIPS = REGISTRY.gauge(
    "tpx_fleet_chips",
    "modeled fleet capacity in chips, total and currently free",
    ("state",),
)

#: chips currently placed per tenant (the quota accounting value).
FLEET_TENANT_CHIPS = REGISTRY.gauge(
    "tpx_fleet_tenant_chips",
    "chips currently placed per fleet tenant",
    ("tenant",),
)

#: gang placements executed, per priority class.
FLEET_PLACEMENTS = REGISTRY.counter(
    "tpx_fleet_placements_total",
    "gangs placed by the fleet scheduler",
    ("klass",),
)

#: market actions taken: kind="shrink" (elastic mesh-reshape, no kill) or
#: kind="requeue" (checkpoint-preempt of a non-elastic victim).
FLEET_PREEMPTIONS = REGISTRY.counter(
    "tpx_fleet_preemptions_total",
    "preemption-market actions executed, by kind",
    ("kind",),
)

#: shrink debts repaid — gangs grown back to their launch mesh.
FLEET_GROWBACKS = REGISTRY.counter(
    "tpx_fleet_growbacks_total",
    "shrunk gangs grown back to launch size",
)

#: queue wait from submit (or requeue) to placement, per priority class.
FLEET_GANG_WAIT_SECONDS = REGISTRY.histogram(
    "tpx_fleet_gang_wait_seconds",
    "gang wait time from enqueue to placement in seconds",
    ("klass",),
)


# -- pipelines (torchx_tpu/pipelines/) ------------------------------------

#: pipelines that reached a terminal state, by that state
#: (PROMOTED/SUCCEEDED/FAILED/ROLLED_BACK/CANCELLED).
PIPELINE_RUNS = REGISTRY.counter(
    "tpx_pipeline_runs_total",
    "pipelines finished, by terminal state",
    ("state",),
)

#: pipelines currently in a non-terminal state.
PIPELINE_ACTIVE = REGISTRY.gauge(
    "tpx_pipeline_active",
    "pipelines currently pending, running, or in canary",
)

#: stage transitions, by stage kind and the state entered.
PIPELINE_STAGES = REGISTRY.counter(
    "tpx_pipeline_stages_total",
    "pipeline stage transitions, by kind and state",
    ("kind", "state"),
)

#: eval-gate and canary-gate verdicts.
PIPELINE_GATES = REGISTRY.counter(
    "tpx_pipeline_gate_decisions_total",
    "pipeline gate decisions (eval threshold + canary gates)",
    ("decision",),
)

#: automatic canary rollbacks, by reason (eval_regression/slo_burn/
#: rollout_failed).
PIPELINE_ROLLBACKS = REGISTRY.counter(
    "tpx_pipeline_rollbacks_total",
    "canary rollbacks executed, by reason",
    ("reason",),
)

#: wall-clock from stage submit to terminal, per stage kind.
PIPELINE_STAGE_SECONDS = REGISTRY.histogram(
    "tpx_pipeline_stage_seconds",
    "pipeline stage duration from submit to terminal in seconds",
    ("kind",),
)


# -- virtual-time simulation (tpx sim) --------------------------------------

#: events processed by the sim harness's virtual-time loop, by kind
#: (arrival/gang_done/fault/tick/pipeline/wake).
SIM_EVENTS = REGISTRY.counter(
    "tpx_sim_events_total",
    "virtual-time events processed by the sim harness, by kind",
    ("kind",),
)

#: faults the harness injected, by kind.
SIM_FAULTS = REGISTRY.counter(
    "tpx_sim_faults_total",
    "faults injected into the simulated fleet, by kind",
    ("kind",),
)

#: virtual seconds covered by the last completed sim run.
SIM_VIRTUAL_SECONDS = REGISTRY.gauge(
    "tpx_sim_virtual_seconds",
    "virtual time span of the last completed sim run in seconds",
)

#: wall seconds the last completed sim run took to execute.
SIM_WALL_SECONDS = REGISTRY.gauge(
    "tpx_sim_wall_seconds",
    "wall-clock execution time of the last completed sim run in seconds",
)

#: virtual/wall speedup of the last completed sim run.
SIM_SPEEDUP = REGISTRY.gauge(
    "tpx_sim_speedup",
    "virtual-over-wall time ratio of the last completed sim run",
)


# -- federation (torchx_tpu/federation/) ------------------------------------

#: gauge encoding for a cell's lifecycle state (UNCORDONED is
#: transitional and reads back as HEALTHY).
CELL_STATE_VALUES = {"HEALTHY": 0, "DRAINING": 1, "DRAINED": 2}

#: one cell's lifecycle state, using :data:`CELL_STATE_VALUES`.
FED_CELL_STATE = REGISTRY.gauge(
    "tpx_federation_cell_state",
    "federation cell lifecycle (0=healthy, 1=draining, 2=drained)",
    ("cell",),
)

#: the long-window SLO burn the router last observed per cell.
FED_CELL_BURN = REGISTRY.gauge(
    "tpx_federation_cell_burn",
    "max long-window SLO burn rate the router last observed, per cell",
    ("cell",),
)

#: requests the federation router dispatched, by target cell + outcome
#: (ok/error/refused).
FED_REQUESTS = REGISTRY.counter(
    "tpx_federation_requests_total",
    "requests dispatched by the federation router, by cell and outcome",
    ("cell", "outcome"),
)

#: requests that landed on a cell other than the router's first choice
#: (burn over budget, breaker open, drain, or dial failure).
FED_SPILLOVERS = REGISTRY.counter(
    "tpx_federation_spillovers_total",
    "requests spilled past the first-choice cell, by reason",
    ("reason",),
)

#: per-cell circuit breaker state
#: (:data:`torchx_tpu.resilience.breaker.STATE_VALUES` encoding).
FED_BREAKER_STATE = REGISTRY.gauge(
    "tpx_federation_breaker_state",
    "per-cell dial circuit breaker state (0=closed, 1=half-open, 2=open)",
    ("cell",),
)
