"""Durable observability sinks: JSONL traces + Prometheus textfile metrics.

Everything lands under one session directory,
``~/.torchx_tpu/obs/<session>/`` (override the root with ``$TPX_OBS_DIR``),
following the per-user dotfile convention of
:mod:`torchx_tpu.util.registry`:

* ``trace.jsonl`` — every span and :class:`TpxEvent` the session emitted,
  one JSON object per line, appended by every participating process (the
  client AND locally-launched replicas share the session via
  ``$TPX_INTERNAL_SESSION_ID``, so their spans interleave into one file);
* ``metrics-<pid>.prom`` — each process's metrics registry in Prometheus
  text format, rewritten atomically on flush (per-pid files so client and
  job processes never clobber each other; textfile collectors and
  ``tpx trace --metrics`` aggregate the glob).

Both are exposed as named event destinations (``jsonl``, ``prom``) through
the ``tpx.event_handlers`` registry in
:mod:`torchx_tpu.runner.events.handlers`, and the JSONL sink is also
attached to the events logger whenever tracing is enabled — spans and
events share one pipeline either way.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Callable, Optional

from torchx_tpu import settings
from torchx_tpu.obs.trace import tracing_enabled

logger = logging.getLogger(__name__)

TRACE_FILE = "trace.jsonl"
METRICS_GLOB = "metrics-*.prom"


def obs_root() -> str:
    """Root of all durable observability output:
    ``$TPX_OBS_DIR`` or ``~/.torchx_tpu/obs``."""
    return os.environ.get(settings.ENV_TPX_OBS_DIR) or os.path.join(
        os.path.expanduser("~"), ".torchx_tpu", "obs"
    )


def default_session_name() -> str:
    """The session directory name, derived from the process-wide session id
    exactly like ``get_runner``'s default Runner name — so the client, its
    subprocesses, and locally-launched replicas (which inherit
    ``$TPX_INTERNAL_SESSION_ID``) all write into one directory."""
    from torchx_tpu.util.session import get_session_id_or_create_new

    return f"tpx_{get_session_id_or_create_new()[:8]}"


def session_dir(session: Optional[str] = None) -> str:
    """Directory holding one session's trace + metrics files."""
    return os.path.join(obs_root(), session or default_session_name())


def trace_path(session: Optional[str] = None) -> str:
    """The session's JSONL trace file path."""
    return os.path.join(session_dir(session), TRACE_FILE)


def metrics_path(session: Optional[str] = None) -> str:
    """This process's metrics textfile path within the session dir."""
    return os.path.join(session_dir(session), f"metrics-{os.getpid()}.prom")


class JsonlTraceHandler(logging.Handler):
    """Logging handler appending each record's message (an already
    serialized span or TpxEvent JSON object) as one line to the session's
    ``trace.jsonl``.

    The path is resolved per emit — cheap at launcher event rates, and it
    honors ``$TPX_OBS_DIR``/``$HOME`` changes mid-process (tests, in-job
    redirection). Single-line ``O_APPEND`` writes keep concurrent
    processes' records intact. Emission is best-effort: telemetry must
    never break the launch path."""

    def emit(self, record: logging.LogRecord) -> None:
        if not tracing_enabled():
            return
        try:
            path = trace_path()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "a") as f:
                f.write(record.getMessage().rstrip("\n") + "\n")
        except Exception:  # noqa: BLE001 - never break the caller
            self.handleError(record)


class PromMetricsHandler(logging.Handler):
    """Logging handler keeping the metrics textfile current — for
    operators who point ``$TPX_EVENT_DESTINATION=prom`` at a node
    exporter's textfile directory and want metrics without traces.

    Flushes are DEBOUNCED: re-rendering the full registry per event is
    O(metrics) disk work, and a burst (a supervisor restarting a gang, a
    serve pool draining) can emit hundreds of events in a second. The
    first event of a quiet period flushes immediately; later events
    inside ``min_interval_s`` (``$TPX_METRICS_MIN_INTERVAL``, default
    2s) only mark the registry dirty, and the next emit past the
    interval — or :meth:`flush`/:meth:`close`, which ``logging`` calls
    at shutdown — writes the final state. Nothing is ever lost: the
    textfile is a snapshot of the whole registry, so one deferred write
    covers every skipped one."""

    def __init__(
        self,
        min_interval_s: Optional[float] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        super().__init__()
        # resolved at construction, not in the signature: tests patch
        # time.monotonic on the module and must see the substitute
        self._clock = clock if clock is not None else time.monotonic
        if min_interval_s is None:
            raw = os.environ.get(settings.ENV_TPX_METRICS_MIN_INTERVAL, "")
            try:
                min_interval_s = float(raw) if raw else None
            except ValueError:
                min_interval_s = None
        self.min_interval_s = (
            settings.DEFAULT_METRICS_MIN_INTERVAL
            if min_interval_s is None
            else float(min_interval_s)
        )
        self._lock_flush = threading.Lock()
        self._last_flush = -float("inf")  # monotonic stamp of last write
        self._dirty = False

    def emit(self, record: logging.LogRecord) -> None:
        try:
            with self._lock_flush:
                now = self._clock()
                if now - self._last_flush < self.min_interval_s:
                    self._dirty = True
                    return
                self._last_flush = now
                self._dirty = False
            flush_metrics()
        except Exception:  # noqa: BLE001
            self.handleError(record)

    def flush(self) -> None:
        """Write any debounce-deferred state now (logging shutdown and
        tests call this — the 'final flush' of the burst)."""
        with self._lock_flush:
            if not self._dirty:
                return
            self._dirty = False
            self._last_flush = self._clock()
        try:
            flush_metrics()
        except Exception:  # noqa: BLE001 - never break shutdown
            pass

    def close(self) -> None:
        self.flush()
        super().close()


#: alias matching the handler's role name in operator docs/issues.
MetricsFlushHandler = PromMetricsHandler


def flush_metrics(session: Optional[str] = None) -> Optional[str]:
    """Atomically write this process's metrics registry to its ``.prom``
    textfile (tmp + ``os.replace``, same torn-read protection as
    ``util.registry``). No-op with tracing disabled. Returns the path
    written, or None."""
    if not tracing_enabled():
        return None
    from torchx_tpu.obs.metrics import REGISTRY

    path = metrics_path(session)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), prefix=".metrics_"
        )
        try:
            with os.fdopen(fd, "w") as f:
                f.write(REGISTRY.render())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError as e:
        logger.debug("could not flush metrics to %s: %s", path, e)
        return None
    return path
