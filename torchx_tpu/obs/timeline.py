"""Read stored traces back and render them as an indented timeline.

This is the inspection half of the obs subsystem: ``tpx trace`` feeds an
app handle (or raw trace id) through :func:`find_trace_ids` /
:func:`build_timeline` / :func:`render_timeline` to answer "where did my
launch time go", entirely from the JSONL files under the obs directory —
no scheduler round-trips, works after the job is gone.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from torchx_tpu.obs import sinks
from torchx_tpu.obs.trace import SPAN_KIND, Span


def load_records(path: str) -> list[dict[str, Any]]:
    """Parse one JSONL file into dicts, silently skipping unparseable
    lines (a crashed writer may leave a torn tail; readers must survive)."""
    records: list[dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict):
                    records.append(obj)
    except OSError:
        pass
    return records


def iter_trace_files(obs_dir: Optional[str] = None) -> Iterable[str]:
    """Every session's ``trace.jsonl`` under the obs root, newest session
    first (mtime order) so searches hit recent runs before old ones."""
    root = obs_dir or sinks.obs_root()
    paths = glob.glob(os.path.join(root, "*", sinks.TRACE_FILE))
    return sorted(paths, key=lambda p: os.path.getmtime(p), reverse=True)


def is_span(record: dict[str, Any]) -> bool:
    """True when a JSONL record is a serialized span (vs a TpxEvent)."""
    return record.get("kind") == SPAN_KIND


def _record_app_id(record: dict[str, Any]) -> Optional[str]:
    if is_span(record):
        return (record.get("attrs") or {}).get("app_id")
    return record.get("app_id")


def find_trace_ids(records: list[dict[str, Any]], app_id: str) -> list[str]:
    """Trace ids that touched ``app_id`` (order of first appearance). A
    supervised run keeps one trace across attempts, so this is normally a
    single id; multiple ids mean the app was driven by separate client
    invocations (e.g. ``tpx run`` then ``tpx status``)."""
    out: list[str] = []
    for r in records:
        tid = r.get("trace_id")
        if tid and _record_app_id(r) == app_id and tid not in out:
            out.append(tid)
    return out


@dataclass
class TimelineNode:
    """One span plus its children, ordered by start time."""

    span: Span
    children: list["TimelineNode"] = field(default_factory=list)
    #: TpxEvent records correlated to this span (via their span_id).
    events: list[dict[str, Any]] = field(default_factory=list)


def build_timeline(
    records: list[dict[str, Any]], trace_id: str
) -> list[TimelineNode]:
    """Reconstruct one trace's span tree from mixed JSONL records.

    Returns the root nodes (usually one) sorted by start time; spans whose
    parent never got recorded (crashed writer) surface as roots rather
    than vanishing. TpxEvent records carrying a ``span_id`` are attached
    to their span for ``--events`` rendering."""
    nodes: dict[str, TimelineNode] = {}
    events: list[dict[str, Any]] = []
    for r in records:
        if r.get("trace_id") != trace_id:
            continue
        if is_span(r):
            span = Span.deserialize(json.dumps(r))
            nodes[span.span_id] = TimelineNode(span)
        else:
            events.append(r)
    roots: list[TimelineNode] = []
    for node in nodes.values():
        parent = nodes.get(node.span.parent_span_id or "")
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    for ev in events:
        node = nodes.get(ev.get("span_id") or "")
        if node is not None:
            node.events.append(ev)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.span.start_epoch_usec)
        node.events.sort(key=lambda e: e.get("start_epoch_time_usec") or 0)
    roots.sort(key=lambda n: n.span.start_epoch_usec)
    return roots


def _fmt_duration(usec: Optional[int]) -> str:
    if usec is None:
        return "open"
    s = usec / 1e6
    if s < 0.001:
        return f"{usec}us"
    if s < 1:
        return f"{s * 1000:.1f}ms"
    return f"{s:.2f}s"


_HIDDEN_ATTRS = {"app_id"}  # shown inline with the name, not in the attr list


def _fmt_attrs(attrs: dict[str, Any]) -> str:
    parts = [
        f"{k}={v}"
        for k, v in attrs.items()
        if k not in _HIDDEN_ATTRS and v is not None
    ]
    return f"  [{', '.join(parts)}]" if parts else ""


def render_timeline(
    roots: list[TimelineNode],
    include_events: bool = False,
) -> str:
    """Render a span tree as an indented timeline: per-line relative start
    offset (from the trace's first span), name, app id, duration, attrs,
    and an ``!ERROR`` marker on failed spans."""
    if not roots:
        return "(no spans)"
    t0 = min(r.span.start_epoch_usec for r in roots)
    lines: list[str] = []

    def walk(node: TimelineNode, depth: int) -> None:
        sp = node.span
        offset = (sp.start_epoch_usec - t0) / 1e6
        app_id = sp.attrs.get("app_id")
        name = f"{sp.name} ({app_id})" if app_id else sp.name
        err = "  !ERROR" if sp.status == "ERROR" else ""
        lines.append(
            f"+{offset:9.3f}s  {'  ' * depth}{name}"
            f"  {_fmt_duration(sp.duration_usec())}"
            f"{_fmt_attrs(sp.attrs)}{err}"
        )
        if include_events:
            for ev in node.events:
                ts = ev.get("start_epoch_time_usec")
                eoff = f"+{(ts - t0) / 1e6:9.3f}s" if ts else " " * 11
                meta = ev.get("app_metadata") or {}
                label = meta.get("transition") or ev.get("api") or "event"
                detail = ", ".join(
                    f"{k}={v}"
                    for k, v in meta.items()
                    if k != "transition" and v is not None
                )
                lines.append(
                    f"{eoff}  {'  ' * (depth + 1)}· {label}"
                    + (f"  [{detail}]" if detail else "")
                )
        for child in node.children:
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return "\n".join(lines)


# -- metrics table ---------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)


def load_metrics(session_dir: str) -> list[tuple[str, str, float]]:
    """Parse every ``metrics-*.prom`` textfile in a session dir into
    ``(name, labels, value)`` rows, summing series that appear in several
    processes' files (counters/histograms aggregate correctly; a gauge
    duplicated across processes is summed too, which is the standard
    textfile-collector caveat)."""
    acc: dict[tuple[str, str], float] = {}
    order: list[tuple[str, str]] = []
    for path in sorted(glob.glob(os.path.join(session_dir, sinks.METRICS_GLOB))):
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = _PROM_LINE.match(line)
            if not m:
                continue
            try:
                value = float(m.group("value"))
            except ValueError:
                continue
            key = (m.group("name"), m.group("labels") or "")
            if key not in acc:
                order.append(key)
            acc[key] = acc.get(key, 0.0) + value
    return [(name, labels, acc[(name, labels)]) for name, labels in order]


def render_metrics_table(
    rows: list[tuple[str, str, float]], include_buckets: bool = False
) -> str:
    """Align metric rows into a readable table; histogram ``_bucket``
    series are collapsed by default (``_count``/``_sum`` tell the story)."""
    visible = [
        (n, l, v)
        for n, l, v in rows
        if include_buckets or not n.endswith("_bucket")
    ]
    if not visible:
        return "(no metrics)"
    name_w = max(len(n) for n, _, _ in visible)
    label_w = max(len(l) for _, l, _ in visible)
    return "\n".join(
        f"{n:<{name_w}}  {l:<{label_w}}  {_strip_float(v)}"
        for n, l, v in visible
    )


def _strip_float(v: float) -> str:
    return str(int(v)) if v.is_integer() else f"{v:.6g}"
